"""SLO-driven autoscaling for the serve fleet.

The autoscaler is a pure poll-driven state machine in the failure
detector's mold: no clocks, no threads, no stores — it consumes one
:class:`ReplicaSample` per live replica per poll and answers "how many
replicas should exist". Time enters only as POLL COUNTS (the fleet
monitor polls on its own cadence), so every path unit-tests in
microseconds and replays exactly.

Signals (the PR 12 wave-boundary live gauges, read from the telemetry
registry through the typed ``get_tagged``/``tagged_series`` path — no
Prometheus text parsing):

  * ``serve_ttft_p95_s`` tagged ``engine:<id>`` — the user-facing SLO:
    scale up when any fresh replica's rolling p95 breaches
    ``ttft_high_s``;
  * ``serve_queue_depth`` tagged ``engine:<id>`` — the backlog signal:
    scale up when the mean depth across fresh replicas breaches
    ``queue_high``.

Hysteresis: a breach must hold for ``breach_polls`` CONSECUTIVE polls
before a scale-up, and every signal must sit below HALF its threshold
for ``clear_polls`` consecutive polls before a scale-down — one spiky
wave or one idle gap never moves the fleet, and the asymmetric bounds
(clear is stricter than breach by default) bias toward serving the SLO
over saving a replica.

Staleness: a gauge registry keeps a frozen emitter's LAST values
forever, so a wedged engine that stopped publishing would otherwise
look permanently healthy (its last-known ttft was fine). Every
emission carries the registry's global sequence number
(``GaugeSample.seq``); a BUSY replica whose sequence hasn't advanced
for ``stale_polls`` polls is STALE — excluded from every aggregate,
reported in the decision so the fleet can cross-check the failure
detector, and a blocker for scale-down (shrinking the fleet on signals
we can't trust is the one unsafe direction). Idle replicas legitimately
stop publishing between serve calls, so only busy ones accrue
staleness.

Scaling moves one replica per decision: scale-up placement/engine
builds are expensive and the hysteresis window re-evaluates before the
next step — ramping is polls × one, never a thundering herd.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

from nexus_tpu.utils.telemetry import (
    METRIC_SERVE_QUEUE_DEPTH,
    METRIC_SERVE_TTFT_P95,
    StatsdClient,
)


class ReplicaSample(NamedTuple):
    """One replica's vitals at one autoscaler poll. ``seq`` is the
    newest registry emission sequence across the replica's gauge series
    (0 = never published); ``busy`` is the fleet's local knowledge that
    the replica is mid-serve (only busy replicas can be stale — an idle
    engine publishing nothing is resting, not wedged). NaN signals mean
    "never published" and are excluded from aggregates."""

    replica_id: str
    busy: bool
    ttft_p95_s: float
    queue_depth: float
    seq: int


def read_replica_sample(client: StatsdClient, replica_id: str,
                        busy: bool) -> ReplicaSample:
    """Build one replica's sample from the telemetry registry via the
    typed per-engine read path (``tagged_series("engine:<id>")``)."""
    series = client.tagged_series(f"engine:{replica_id}")
    ttft = series.get(METRIC_SERVE_TTFT_P95)
    depth = series.get(METRIC_SERVE_QUEUE_DEPTH)
    seq = max((s.seq for s in series.values()), default=0)
    return ReplicaSample(
        replica_id=replica_id,
        busy=bool(busy),
        ttft_p95_s=float(ttft.value) if ttft is not None else float("nan"),
        queue_depth=(
            float(depth.value) if depth is not None else float("nan")
        ),
        seq=int(seq),
    )


class ScaleDecision(NamedTuple):
    target: int  # desired replica count after this poll
    current: int
    reason: str  # human-readable cause ("" = hold)
    stale: Tuple[str, ...]  # busy replicas with frozen gauges this poll
    breach_streak: int
    clear_streak: int


class SloAutoscaler:
    """Poll-driven replica-count controller (see module docstring).

    Thread-safety: ``observe`` is called from the fleet monitor; the
    per-replica staleness ledger and the hysteresis streaks are guarded
    so introspection from other threads (tests, exposition) is safe."""

    def __init__(
        self,
        min_replicas: int,
        max_replicas: int,
        ttft_high_s: float = 0.0,
        queue_high: float = 0.0,
        breach_polls: int = 3,
        clear_polls: int = 6,
        stale_polls: int = 3,
    ) -> None:
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}"
            )
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) below min_replicas "
                f"({min_replicas})"
            )
        if ttft_high_s <= 0 and queue_high <= 0:
            raise ValueError(
                "autoscaler needs at least one scale signal: "
                "ttft_high_s and/or queue_high"
            )
        if breach_polls < 1 or clear_polls < 1 or stale_polls < 1:
            raise ValueError(
                "breach_polls, clear_polls and stale_polls must be >= 1"
            )
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.ttft_high_s = float(ttft_high_s)
        self.queue_high = float(queue_high)
        self.breach_polls = int(breach_polls)
        self.clear_polls = int(clear_polls)
        self.stale_polls = int(stale_polls)
        self._lock = threading.Lock()
        self._last_seq: Dict[str, int] = {}  # guarded-by: _lock
        self._frozen_polls: Dict[str, int] = {}  # guarded-by: _lock
        self._breach_streak = 0  # guarded-by: _lock
        self._clear_streak = 0  # guarded-by: _lock
        self.decisions = 0  # guarded-by: _lock

    # ------------------------------------------------------------- staleness
    def _update_staleness(self, samples) -> List[str]:  # guarded-by: _lock
        """Per-poll staleness bookkeeping (caller holds ``_lock``):
        a busy replica whose newest emission sequence did not advance
        since the previous poll accrues one frozen poll; ``stale_polls``
        of them make it stale. Any advance — or going idle — resets."""
        stale: List[str] = []
        seen = set()
        for s in samples:
            seen.add(s.replica_id)
            prev = self._last_seq.get(s.replica_id)
            # seq == 0 means the replica has NEVER published — a fresh
            # scale-up busy with its first-serve compile, not a wedged
            # emitter (the same silence window the lease birth rule
            # exempts); staleness accrues only once gauges existed
            if s.busy and prev is not None and 0 < s.seq <= prev:
                n = self._frozen_polls.get(s.replica_id, 0) + 1
                self._frozen_polls[s.replica_id] = n
                if n >= self.stale_polls:
                    stale.append(s.replica_id)
            else:
                self._frozen_polls[s.replica_id] = 0
            self._last_seq[s.replica_id] = s.seq
        for rid in list(self._last_seq):
            if rid not in seen:  # replica left the fleet
                del self._last_seq[rid]
                self._frozen_polls.pop(rid, None)
        return stale

    # -------------------------------------------------------------- decision
    def observe(self, samples: Sequence[ReplicaSample],
                current: Optional[int] = None) -> ScaleDecision:
        """One autoscaler poll → the desired replica count."""
        cur = int(current if current is not None else len(samples))
        with self._lock:
            self.decisions += 1
            stale = self._update_staleness(samples)
            stale_set = set(stale)
            fresh = [s for s in samples if s.replica_id not in stale_set]
            ttfts = [s.ttft_p95_s for s in fresh
                     if not math.isnan(s.ttft_p95_s)]
            depths = [s.queue_depth for s in fresh
                      if not math.isnan(s.queue_depth)]
            breach_causes: List[str] = []
            if self.ttft_high_s > 0 and ttfts:
                worst = max(ttfts)
                if worst > self.ttft_high_s:
                    breach_causes.append(
                        f"ttft_p95 {worst:.4f}s > slo {self.ttft_high_s}s"
                    )
            if self.queue_high > 0 and depths:
                mean_depth = sum(depths) / len(depths)
                if mean_depth > self.queue_high:
                    breach_causes.append(
                        f"mean queue depth {mean_depth:.1f} > "
                        f"{self.queue_high:g}"
                    )
            breached = bool(breach_causes)
            # "clear" is stricter than "not breached": every fresh
            # signal under HALF its threshold — the hysteresis band
            # between scale-up and scale-down where the fleet holds
            clear = bool(fresh) and not stale and (
                (self.ttft_high_s <= 0
                 or all(t <= self.ttft_high_s / 2 for t in ttfts))
                and (self.queue_high <= 0
                     or all(d <= self.queue_high / 2 for d in depths))
            )
            self._breach_streak = self._breach_streak + 1 if breached else 0
            self._clear_streak = self._clear_streak + 1 if clear else 0
            target, reason = cur, ""
            if (self._breach_streak >= self.breach_polls
                    and cur < self.max_replicas):
                target = cur + 1
                reason = (
                    f"scale up: {'; '.join(breach_causes)} for "
                    f"{self._breach_streak} polls"
                )
                self._breach_streak = 0
                self._clear_streak = 0
            elif (self._clear_streak >= self.clear_polls
                    and cur > self.min_replicas):
                # scale-down additionally requires ZERO stale busy
                # replicas this poll (enforced by `clear`): shrinking on
                # signals we can't trust is the one unsafe direction
                target = cur - 1
                reason = (
                    "scale down: all signals under half thresholds for "
                    f"{self._clear_streak} polls"
                )
                self._clear_streak = 0
                self._breach_streak = 0
            return ScaleDecision(
                target=target, current=cur, reason=reason,
                stale=tuple(stale),
                breach_streak=self._breach_streak,
                clear_streak=self._clear_streak,
            )
