"""Fleet-scale serving (round 14): replicated engines, prefix-affinity
routing, SLO-driven autoscaling — the serve plane's analogue of the
controller's multi-shard fan-out (PAPER.md's NCC pattern applied to
engines instead of templates; docs/fleet.md).

  * :mod:`~nexus_tpu.fleet.router` — :class:`PrefixAffinityRouter`:
    rendezvous-hash each prompt's radix chain-key prefix to a replica
    so same-prefix traffic single-homes (cache locality survives load
    balancing), with power-of-two-choices spill-over on live
    queue-depth gauges bounding hot-key imbalance.
  * :mod:`~nexus_tpu.fleet.autoscaler` — :class:`SloAutoscaler`:
    poll-driven replica-count control on the live ``serve_ttft_p95_s``
    / ``serve_queue_depth`` gauges with breach/clear hysteresis and a
    frozen-gauge staleness guard.
  * :mod:`~nexus_tpu.fleet.fleet` — :class:`ServeFleet` (live threaded
    harness: per-replica leases, detector-confirmed deaths,
    drain-and-requeue onto survivors) and :func:`serve_fleet_local`
    (the deterministic thread-free drive the entrypoint and bench use).
"""

from nexus_tpu.fleet.autoscaler import (  # noqa: F401
    ReplicaSample,
    ScaleDecision,
    SloAutoscaler,
    read_replica_sample,
)
from nexus_tpu.fleet.fleet import ServeFleet, serve_fleet_local  # noqa: F401
from nexus_tpu.fleet.router import (  # noqa: F401
    PrefixAffinityRouter,
    affinity_key,
    rendezvous_weight,
)
