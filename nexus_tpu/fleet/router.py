"""Prefix-affinity request routing for the serve fleet.

The single-engine serve plane wins most of its throughput from KV
reuse: the radix prefix cache (PR 9), the host spill tier under it
(PR 10), and Hydragen's shared-prefix decomposition (PR 8) all feed on
same-prefix requests LANDING ON THE SAME ENGINE. A cache-blind load
balancer destroys exactly that: scatter a 24-request family with one
system preamble across 4 replicas and the preamble prefills four times
— four cold leaders instead of one — and every replica's radix tree
holds a quarter of the family's warmth (SGLang's cache-aware routing
observation, PAPERS.md).

:class:`PrefixAffinityRouter` keeps locality through load balancing:

  * **Affinity key** — each prompt's radix chain keys (the PR 9 digest
    chain, ``runtime/prefix_cache.py::chain_keys``, reused not
    reimplemented) hashed to depth ``affinity_depth`` FULL blocks. A
    chain digest commits to every token through its block, so two
    prompts share the key iff they agree on the whole prefix through
    that depth — the same collision-safety argument the prefix cache
    itself rests on. Prompts without a full block hash their raw
    leading tokens instead (identical short prompts still single-home).
  * **Rendezvous choice** — the key rendezvous-hashes over the live
    replica set (the ``controller/placement.py`` rule at the request
    level): replica death or scale-down re-homes ONLY the keys that
    lived on the removed replica; every other family stays put on its
    warm cache.
  * **Load-aware spill-over** — pure affinity piles a hot key's whole
    family on one replica no matter how deep its queue grows. The
    router ranks the top ``spill_candidates`` replicas by affinity
    weight and applies power-of-two-choices among them, reading each
    candidate's live load (``serve_queue_depth`` tagged
    ``engine:<id>``, published by the PR 12 wave-boundary gauges, read
    through the registry's typed ``get_tagged`` path — plus whatever
    the fleet already assigned locally); it spills off the affinity
    home only when the home is busier by at least ``spill_threshold``
    requests, so locality is the default and imbalance is bounded, not
    chased per request.

Priority contract (docs/fleet.md is the one normative home):
``ServeRequest.priority`` orders FLEET DISPATCH — :meth:`route_batch`
routes higher-priority requests first, so when load forces spill-over
it is the low-priority tail that migrates off warm caches — exactly as
it orders shedding inside an engine (lowest sheds first). Within one
engine, admission ordering remains the engine's ``admission_policy``.

Routing is scheduling, never semantics: whatever the assignment,
results are token-for-token identical (the fleet bench re-proves it
in-run via ``fleet_exact``).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from nexus_tpu.runtime.prefix_cache import chain_keys
from nexus_tpu.utils.telemetry import (
    METRIC_SERVE_QUEUE_DEPTH,
    StatsdClient,
    get_client,
)

ROUTER_POLICIES = ("affinity", "random")


def affinity_key(prompt: Sequence[int], block_size: int,
                 depth: int = 2) -> bytes:
    """The routing digest of a prompt: its radix chain key at
    ``min(full blocks, depth)`` — commits to every token of the prefix
    through that block. Sub-block prompts (no full block to key) hash
    their raw leading tokens so identical short prompts still share a
    home. ``depth`` should not exceed the workload's shared-preamble
    depth in blocks: a deeper key folds request-specific tail tokens
    into the digest and scatters the family."""
    if depth < 1:
        raise ValueError(f"affinity depth must be >= 1, got {depth}")
    keys = chain_keys(prompt, block_size, limit=depth)
    if keys:
        return keys[-1]
    head = np.asarray(list(prompt)[:block_size], dtype=np.int32)
    return hashlib.sha256(b"sub-block:" + head.tobytes()).digest()


def rendezvous_weight(key: bytes, replica_id: str) -> bytes:
    """Stable pseudo-random weight of (affinity key, replica) — the
    highest-random-weight rule ``controller/placement.py`` uses for
    shard homes, applied per request key."""
    return hashlib.blake2b(
        key + b"\x00" + replica_id.encode(), digest_size=8
    ).digest()


class PrefixAffinityRouter:
    """Assign requests to replicas, preserving prefix locality.

    ``load_fn(replica_id) -> float`` injects the spill-over load signal;
    the default reads the replica's live ``serve_queue_depth`` gauge
    (tagged ``engine:<id>``) from the telemetry registry — the fleet
    adds its locally-known pending counts on top. ``policy="random"``
    is the cache-blind baseline (seeded, deterministic) the fleet bench
    A/Bs against.

    Thread-safety: the replica set shrinks on confirmed deaths and
    grows on scale-up from the fleet monitor while workers run —
    membership reads/writes hold ``_lock``."""

    def __init__(
        self,
        replica_ids: Sequence[str],
        block_size: int,
        affinity_depth: int = 2,
        spill_candidates: int = 2,
        spill_threshold: int = 4,
        policy: str = "affinity",
        load_fn: Optional[Callable[[str], float]] = None,
        client: Optional[StatsdClient] = None,
        seed: int = 0,
        decision_log: Any = None,
    ) -> None:
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if policy not in ROUTER_POLICIES:
            raise ValueError(
                f"router policy must be one of {ROUTER_POLICIES}, "
                f"got {policy!r}"
            )
        if spill_candidates < 1:
            raise ValueError(
                f"spill_candidates must be >= 1, got {spill_candidates}"
            )
        if spill_threshold < 1:
            raise ValueError(
                f"spill_threshold must be >= 1, got {spill_threshold}"
            )
        self.block_size = int(block_size)
        self.affinity_depth = int(affinity_depth)
        self.spill_candidates = int(spill_candidates)
        self.spill_threshold = int(spill_threshold)
        self.policy = policy
        self._load_fn = load_fn
        self._client = client
        # round-15 audit surface (obs/fleet_log.py): when attached,
        # every route records its evidence — the affinity key, the
        # rendezvous ranking, and the candidate loads that justified
        # (or vetoed) a spill. The fleet wires its own log in; None
        # keeps routing record-free.
        self.decision_log = decision_log
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()
        self._replicas: List[str] = list(replica_ids)  # guarded-by: _lock
        # ---- routing ledger (monitor-thread writes) ----
        self.routed: Dict[str, int] = {}  # guarded-by: _lock
        self.spills = 0  # guarded-by: _lock — non-affinity-home placements
        self.decisions = 0  # guarded-by: _lock

    def _pending_load(self, rid: str) -> float:
        with self._lock:
            return float(self.routed.get(rid, 0))

    def enable_pending_load(self) -> None:
        """Switch the spill-over load signal to the router's OWN routed
        counts — the offline routing pass's analogue of live queue
        depth. An upfront pass (``serve_fleet_local``, the bench legs)
        routes the whole queue before any engine has published a gauge,
        so the registry default would read 0.0 everywhere and silently
        disable spill-over; pending-assigned counts are the load that
        actually exists at that point."""
        self._load_fn = self._pending_load

    # ------------------------------------------------------------ membership
    def replicas(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    def set_replicas(self, replica_ids: Sequence[str]) -> None:
        with self._lock:
            self._replicas = list(replica_ids)

    def add_replica(self, replica_id: str) -> None:
        with self._lock:
            if replica_id not in self._replicas:
                self._replicas.append(replica_id)

    def remove_replica(self, replica_id: str) -> None:
        with self._lock:
            self._replicas = [r for r in self._replicas if r != replica_id]

    def unroute(self, replica_id: str) -> None:
        """Roll back one routed count for an ABANDONED assignment (the
        replica died between routing and delivery and the entry is
        being re-routed) — keeps the per-replica ledger, and with
        pending-load enabled the spill-over signal, honest through
        re-route races. The decision count stands: a re-route is a
        second decision."""
        with self._lock:
            n = self.routed.get(replica_id, 0)
            if n > 1:
                self.routed[replica_id] = n - 1
            elif n:
                del self.routed[replica_id]

    # --------------------------------------------------------------- routing
    def _load(self, replica_id: str) -> float:
        if self._load_fn is not None:
            return float(self._load_fn(replica_id))
        client = self._client or get_client()
        sample = client.get_tagged(
            METRIC_SERVE_QUEUE_DEPTH, [f"engine:{replica_id}"]
        )
        return float(sample.value) if sample is not None else 0.0

    def rank(self, key: bytes) -> List[str]:
        """The live replica set by DESCENDING affinity weight for
        ``key`` — rank[0] is the affinity home; churn in the set moves
        only the keys homed on the changed replica (rendezvous)."""
        with self._lock:
            reps = list(self._replicas)
        if not reps:
            raise RuntimeError("router has zero live replicas")
        return sorted(
            reps, key=lambda r: rendezvous_weight(key, r), reverse=True
        )

    def route(self, request) -> Tuple[str, bool]:
        """One request → ``(replica_id, spilled)``: the affinity home
        unless power-of-two-choices found a top candidate less loaded
        by at least ``spill_threshold`` (``spilled=True`` then). The
        ``random`` policy draws uniformly over live replicas — the
        cache-blind baseline."""
        log = self.decision_log
        if self.policy == "random":
            with self._lock:
                reps = list(self._replicas)
                if not reps:
                    raise RuntimeError("router has zero live replicas")
                chosen = reps[int(self._rng.randint(len(reps)))]
                self.decisions += 1
                self.routed[chosen] = self.routed.get(chosen, 0) + 1
            if log is not None:
                log.record(
                    "route",
                    journey=str(getattr(request, "journey", "") or ""),
                    key="", policy="random", ranked=[], loads=[],
                    chosen=chosen, spilled=False,
                    spill_threshold=self.spill_threshold,
                )
            return chosen, False
        key = affinity_key(
            request.prompt, self.block_size, self.affinity_depth
        )
        ranked = self.rank(key)
        candidates = ranked[: self.spill_candidates]
        chosen = candidates[0]
        spilled = False
        loads: List[float] = []
        if len(candidates) > 1:
            loads = [self._load(r) for r in candidates]
            best = min(range(len(candidates)), key=lambda i: loads[i])
            # affinity wins ties AND small imbalances: spill only when
            # the home is busier by the full threshold — bounded hot-key
            # imbalance without chasing per-request noise off warm caches
            if best != 0 and loads[0] - loads[best] >= self.spill_threshold:
                chosen = candidates[best]
                spilled = True
        with self._lock:
            self.decisions += 1
            self.spills += int(spilled)
            self.routed[chosen] = self.routed.get(chosen, 0) + 1
        if log is not None:
            # the decision WITH its evidence: candidates in affinity
            # order and the loads actually read (the live queue-depth
            # gauges + pending counts power-of-two-choices compared) —
            # an auditor can recompute spill-or-stay from this line
            log.record(
                "route",
                journey=str(getattr(request, "journey", "") or ""),
                key=key.hex()[:16], policy=self.policy,
                ranked=list(candidates),
                loads=[round(float(x), 3) for x in loads],
                chosen=chosen, spilled=spilled,
                spill_threshold=self.spill_threshold,
            )
        return chosen, spilled

    def route_batch(self, entries: Sequence) -> List[Tuple[object, str, bool]]:
        """Route a batch of queue entries (anything carrying a
        ``.request``) → ``[(entry, replica_id, spilled), ...]``.

        The batch is routed in PRIORITY order — higher
        ``ServeRequest.priority`` first, FIFO within a priority tier
        (the fleet half of the priority contract, docs/fleet.md): when
        load forces spill-over it is the low-priority tail, routed
        last into the fullest queues, that migrates off the warm
        affinity homes. The returned list is in routing order, so
        replica inboxes inherit it."""
        order = sorted(
            range(len(entries)),
            key=lambda i: (-int(getattr(
                entries[i].request, "priority", 0) or 0), i),
        )
        out: List[Tuple[object, str, bool]] = []
        for i in order:
            rid, spilled = self.route(entries[i].request)
            out.append((entries[i], rid, spilled))
        return out

    def ledger(self) -> Dict[str, object]:
        with self._lock:
            return {
                "router_policy": self.policy,
                "router_decisions": self.decisions,
                "router_spills": self.spills,
                "router_routed": dict(self.routed),
            }
