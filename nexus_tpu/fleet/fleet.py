"""The serve fleet: N engine replicas behind one router, with
SLO-driven autoscaling and drain-and-requeue failover.

Two drive modes share the router and the failover planner:

  * :func:`serve_fleet_local` — the DETERMINISTIC, thread-free drive:
    route the whole queue (priority-ordered), serve each replica's
    partition on its own engine, merge results back into request order.
    This is the entrypoint path (``ServeSpec.replicas > 1``) and the
    bench's measurement harness: replicas model independent engines on
    disjoint shards, so the CPU lane time-multiplexes them and reports
    ``fleet_busy_max_s`` (the slowest replica's serve seconds — the
    wall a real fleet would realize) next to the raw sum.
  * :class:`ServeFleet` — the LIVE harness: each replica serves from an
    inbox in its own worker thread while renewing a per-replica
    ``hb-serve-<template>--<id>`` lease; one monitor thread probes the
    shared :class:`~nexus_tpu.ha.detector.FailureDetector`, harvests
    results, polls the :class:`~nexus_tpu.fleet.autoscaler
    .SloAutoscaler`, and — on a confirmed replica death OR a
    scale-down — drains the replica and requeues its work onto the
    SURVIVORS through the PR 6 :class:`~nexus_tpu.ha.serve_failover
    .ServeFailoverPlanner`: committed tokens fold into the merged
    prompt, and because the router re-routes the requeued entries by
    the SAME affinity hash (minus the dead replica — rendezvous moves
    only its keys), a recovered cohort's shared prefixes re-match on
    their new home exactly as PR 9 proved per-engine.

Retries semantics: ``ServeResult.retries`` counts MIGRATIONS of any
cause — replica death and graceful scale-down both requeue through the
planner, so a request that completed on its second home reports
``failed_over``/``retries >= 1`` either way (the honest record that
more than one engine served it; docs/fleet.md).

Engine caches are ENGINE-LIFETIME (round 16): each replica's block
pool, radix tree, and host tier are built at engine init and survive
across its serve calls, so affinity pays off across the whole run —
the router's stable keys home repeat prefixes onto replicas whose
warm trees already hold them, and every call boundary passes the
NEXUS_SANITIZE warm-boundary audits. ``run(..., source=)`` is the
matching OPEN-LOOP drive: a trace source (``nexus_tpu/runtime/
traffic.py``) streams arrivals into the monitor loop while engines
run, so the autoscaler scales, the router spills, and failover drains
against live load; per-entry arrival stamps rebase onto each engine
call's clock so ``ServeResult.queue_s`` and the goodput rollup anchor
at TRUE arrival, not ``serve()`` entry.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from nexus_tpu.fleet.autoscaler import SloAutoscaler, read_replica_sample
from nexus_tpu.fleet.router import PrefixAffinityRouter
from nexus_tpu.ha.detector import EVENT_LEASE_EXPIRED, FailureDetector
from nexus_tpu.ha.lease import LeaseRenewer, heartbeat_name, list_heartbeats
from nexus_tpu.ha.serve_failover import (
    RequeueEntry,
    ServeFailoverPlanner,
    replica_of_serve_lease,
    serve_replica_template,
)
from nexus_tpu.obs.federation import FleetGauges
from nexus_tpu.obs.fleet_log import FleetDecisionLog
from nexus_tpu.obs.journey import (
    JourneyBook,
    goodput_under_slo,
    slo_verdicts,
)
from nexus_tpu.obs.trace import ServeTracer
from nexus_tpu.utils.telemetry import (
    METRIC_SERVE_AFFINITY_HIT_RATE,
    StatsdClient,
    get_client,
)

logger = logging.getLogger("nexus_tpu.fleet")


# --------------------------------------------------------------- local drive

def serve_fleet_local(
    engines: Dict[str, Any],
    router: PrefixAffinityRouter,
    requests: Sequence[Any],
    cancel: Any = None,
    heartbeat: Optional[Callable[[int], None]] = None,
    planner: Optional[ServeFailoverPlanner] = None,
    clock: Callable[[], float] = time.monotonic,
    journeys: bool = True,
    decision_log: Any = None,
    slo_s: float = 0.0,
) -> Tuple[List[Optional[Any]], Dict[str, Any]]:
    """Deterministic fleet drive (no threads, no store): route the
    queue through ``router`` (priority-ordered), serve each replica's
    partition on its engine, return ``(results, metrics)`` with
    ``results[i]`` answering ``requests[i]``.

    ``heartbeat`` is called at every wave boundary of every replica
    with the FLEET-cumulative committed-token count (the entrypoint
    wires it to the template's serve lease exactly as the single-engine
    path does). A fired ``cancel`` drains the replica currently serving
    at its next boundary and skips the rest; per-replica drains land in
    ``metrics['interrupted']`` + each engine's own ``last_drain``.

    Per-replica serve seconds ride the metrics: ``fleet_busy_max_s`` is
    the slowest replica — the wall N independent shards would realize —
    next to ``fleet_busy_sum_s`` (the time-multiplexed CPU-lane total).

    Fleet observability (round 15, default ON): ``journeys`` attaches
    a fresh per-call tracer to every replica serve and stitches the
    cross-replica journey dump into ``metrics['journeys']``;
    ``decision_log`` (None → a fresh :class:`FleetDecisionLog`;
    ``False`` disables) records every route decision with its evidence
    into ``metrics['fleet_decision_log']``; ``slo_s > 0`` adds the
    goodput-under-SLO rollup (``fleet_slo_attainment`` /
    ``fleet_goodput_tok_s`` against the slowest-replica wall).
    """
    planner = planner or ServeFailoverPlanner()
    t_run0 = clock()
    if decision_log is False:
        log = None
    else:
        log = decision_log or FleetDecisionLog(clock=clock)
    book = JourneyBook() if journeys else None
    if router._load_fn is None:
        # no injected load signal: the registry default reads live
        # gauges, which are all unpublished during an upfront routing
        # pass — spill-over would silently never fire. Pending routed
        # counts are the real load here (see enable_pending_load).
        router.enable_pending_load()
    entries = planner.fresh(requests)
    # attach OUR log to the router ONLY around this drive's single
    # routing pass (the router may outlive this call — a permanently
    # attached first-run log would swallow later runs' route events
    # onto a stale time base); a caller-attached log stays untouched
    attached_log = log is not None and router.decision_log is None
    if attached_log:
        router.decision_log = log
    try:
        assignments = router.route_batch(entries)
    finally:
        if attached_log:
            router.decision_log = None
    partitions: Dict[str, List[RequeueEntry]] = {
        rid: [] for rid in engines
    }
    for entry, rid, _spilled in assignments:
        partitions[rid].append(entry)
    results: List[Optional[Any]] = [None] * len(requests)
    committed_total = [0]
    per_replica: Dict[str, Dict[str, Any]] = {}
    interrupted = False
    busy: List[float] = []
    walls: List[float] = []
    for rid, engine in engines.items():
        part = partitions.get(rid) or []
        if not part:
            per_replica[rid] = {"requests": 0, "busy_s": 0.0}
            busy.append(0.0)
            walls.append(0.0)
            continue
        base = committed_total[0]

        def hb(step, _base=base):
            committed_total[0] = _base + int(step)
            if heartbeat is not None:
                heartbeat(committed_total[0])

        call_tracer = ServeTracer() if book is not None else None
        t0 = clock()
        r_results, r_metrics = engine.serve(
            [e.request for e in part], cancel=cancel, heartbeat=hb,
            tracer=call_tracer,
        )
        busy_s = clock() - t0
        if book is not None:
            book.absorb_trace(
                call_tracer.to_dict(), replica=rid,
                t_start=t0 - t_run0,
                request_idxs=[e.request_idx for e in part],
            )
        busy.append(busy_s)
        # the engine's own wall excludes its program compiles (serve()
        # warms up before starting its clock) — the honest per-replica
        # serve time for throughput arithmetic
        walls.append(float(r_metrics.get("wall_s", busy_s) or 0.0))
        committed_total[0] = base + int(
            r_metrics.get("committed_tokens", 0) or 0
        )
        for entry, res in zip(part, r_results):
            if res is not None:
                results[entry.request_idx] = planner.stitch(entry, res)
        per_replica[rid] = {
            **r_metrics, "requests": len(part),
            "busy_s": round(busy_s, 6),
        }
        if r_metrics.get("interrupted"):
            interrupted = True
            break  # the cancel is fleet-wide: stop starting replicas
    busy_max = max(busy) if busy else 0.0
    wall_max = max(walls) if walls else 0.0
    metrics: Dict[str, Any] = {
        "fleet_replicas": len(engines),
        "fleet_committed_tokens": committed_total[0],
        "fleet_busy_max_s": round(busy_max, 6),
        "fleet_busy_sum_s": round(sum(busy), 6),
        "fleet_wall_max_s": round(wall_max, 6),
        "fleet_prefix_hit_tokens": sum(
            int(m.get("prefix_hit_tokens", 0) or 0)
            for m in per_replica.values()
        ),
        "fleet_per_replica": per_replica,
        "interrupted": interrupted,
        # the single-engine ledger names, at fleet scope: committed
        # total, and aggregate tok/s against the SLOWEST replica's
        # compile-free serve wall — the wall N independent shards would
        # realize (the CPU lane time-multiplexes replicas;
        # fleet_busy_sum_s is the honest single-box total, compiles
        # included)
        "committed_tokens": committed_total[0],
        "tokens_per_sec": round(
            committed_total[0] / max(wall_max, 1e-9), 2
        ),
        **router.ledger(),
    }
    if book is not None:
        metrics["journeys"] = book.to_dict()
    if log is not None:
        metrics["fleet_decision_log"] = log.to_dict()
    if slo_s > 0:
        g = goodput_under_slo(
            [r for r in results if r is not None], slo_s, wall_max,
        )
        metrics["fleet_slo_s"] = g["slo_s"]
        metrics["fleet_slo_attainment"] = g["slo_attainment"]
        metrics["fleet_goodput_tok_s"] = g["goodput_tok_s"]
    return results, metrics


# ---------------------------------------------------------------- live fleet

class _Replica:
    """One live fleet member's shared state. Every mutable field below
    the thread handle is guarded by the OWNING FLEET's ``_lock`` — a
    cross-object guard NX-LOCK's per-class annotations can't express,
    so the discipline here is structural: only ``ServeFleet`` methods
    and ``_worker`` touch these, always inside ``with self._lock`` on
    the fleet."""

    def __init__(self, rid: str, engine: Any) -> None:
        self.id = rid
        self.engine = engine
        self.thread: Optional[threading.Thread] = None
        self.inbox: List[RequeueEntry] = []
        self.busy = False
        self.killed = False  # chaos/fence: renewals stop immediately
        self.draining = False  # graceful scale-down: finish, don't take more
        self.stopped = False  # worker thread exited
        self.collected = False  # drain harvested by the monitor
        self.cancel: Any = None
        self.current_batch: Optional[List[RequeueEntry]] = None
        self.pending_drain: Optional[Tuple[List[RequeueEntry], List[Any]]] = None
        self.error: Optional[BaseException] = None
        self.committed = 0
        self.busy_s = 0.0
        self.serve_calls = 0
        self.metrics_log: List[dict] = []
        self.flight_dumps: List[dict] = []


class ServeFleet:
    """Drive one serve queue to completion across N replicas, replica
    deaths, and scale events (see module docstring).

    ``make_engine(replica_id)`` builds one replica's engine — it SHOULD
    pass ``gauge_tags=["engine:<replica_id>"]`` so the router's
    spill-over and the autoscaler read that replica's live gauges.
    ``concurrency`` bounds how many replicas serve simultaneously
    (0 = all — the chaos/HA mode; 1 = time-multiplexed, the
    deterministic CPU measurement mode)."""

    def __init__(
        self,
        make_engine: Callable[[str], Any],
        store: Any,
        namespace: str,
        template: str,
        replicas: int = 2,
        router: Optional[PrefixAffinityRouter] = None,
        block_size: int = 32,
        autoscaler: Optional[SloAutoscaler] = None,
        planner: Optional[ServeFailoverPlanner] = None,
        ttl_seconds: float = 0.25,
        poll_s: Optional[float] = None,
        pace_s: float = 0.0,
        concurrency: int = 0,
        max_failures: int = 3,
        shard: str = "serve-fleet",
        detector: Optional[FailureDetector] = None,
        client: Optional[StatsdClient] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        journeys: bool = True,
        decision_log: Any = None,
        fleet_gauges: bool = True,
        slo_s: float = 0.0,
        death_storm_threshold: int = 2,
        flap_window: int = 6,
    ) -> None:
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.make_engine = make_engine
        self.store = store
        self.namespace = namespace
        self.template = template
        self.initial_replicas = int(replicas)
        self.ttl = float(ttl_seconds)
        self.poll_s = float(poll_s) if poll_s else max(0.01, self.ttl / 5.0)
        self.pace_s = float(pace_s)
        self.max_failures = int(max_failures)
        self.shard = shard
        self.planner = planner or ServeFailoverPlanner()
        self.autoscaler = autoscaler
        self.detector = detector or FailureDetector(
            ttl_seconds=self.ttl, suspect_misses=2,
            probe_interval=self.poll_s,
        )
        self.router = router or PrefixAffinityRouter(
            [], block_size=block_size
        )
        # the router's default load signal is the live queue-depth
        # gauge alone — 0 before any wave and frozen between serve
        # calls; stack the fleet's not-yet-served inbox counts on top
        # so routing sees work the engines haven't admitted yet.
        # Applied whenever the caller injected no explicit signal
        # (injected router included), mirroring serve_fleet_local
        if self.router._load_fn is None:
            self.router._load_fn = self._route_load
        self._client = client or get_client()
        self._clock = clock
        self._sleep = sleep
        # ---- fleet observability (round 15, nexus_tpu/obs/) ----
        # journey stitching, the decision audit log (also attached to
        # the router so routes self-record), federated gauges, and the
        # goodput SLO — all default ON, each independently disableable
        self._t_base = clock()
        if decision_log is False:
            self.decision_log: Optional[FleetDecisionLog] = None
        else:
            self.decision_log = decision_log or FleetDecisionLog(clock=clock)
        # the router gets this log attached for the DURATION OF run()
        # only (see run's try/finally): an injected router may be
        # shared or reused, and must not keep recording into a retired
        # fleet's log
        self._book = JourneyBook() if journeys else None
        self.slo_s = float(slo_s)
        self.fleet_gauges = (
            FleetGauges(
                client=self._client, tags=[f"fleet:{template}"],
                slo_s=self.slo_s,
            ) if fleet_gauges else None
        )
        self.death_storm_threshold = int(death_storm_threshold)
        self.flap_window = int(flap_window)
        self._sema = (
            threading.BoundedSemaphore(int(concurrency))
            if concurrency and concurrency > 0 else None
        )
        self._lock = threading.Lock()
        self._replicas: Dict[str, _Replica] = {}  # guarded-by: _lock
        self._spawn_counter = 0  # guarded-by: _lock
        self._finished: List[Tuple[RequeueEntry, Any]] = []  # guarded-by: _lock
        self._shutdown = False  # guarded-by: _lock
        self._obs_dumps: List[dict] = []  # monitor-thread only
        self._tripped: set = set()  # monitor-thread only
        self._death_journeys: List[str] = []  # monitor-thread only
        self._monitor_polls = 0  # monitor-thread only
        # streaming run clock base: set by run(source=) BEFORE replicas
        # spawn, cleared in its finally — workers read it to rebase
        # entry arrivals onto their engine call's clock (write-once per
        # run, so no lock needed on the read side)
        self._stream_t0: Optional[float] = None
        # (autoscaler poll index, +1 up / -1 down) of the last scale
        # move — the flap detector's memory (monitor-thread only)
        self._last_scale: Optional[Tuple[int, int]] = None

    # ------------------------------------------------------------------ load
    def _inbox_depth(self, rep: "_Replica") -> int:
        """Routed-but-unserved entries waiting on ``rep`` — the
        engine's ``ext_backlog`` hook, so its live ``serve_queue_depth``
        gauge counts work the fleet has committed to this replica that
        the engine hasn't admitted yet (the autoscaler and p2c spill
        read real backlog, not just the in-call queue)."""
        with self._lock:
            return len(rep.inbox)

    def _route_load(self, rid: str) -> float:
        from nexus_tpu.utils.telemetry import METRIC_SERVE_QUEUE_DEPTH

        sample = self._client.get_tagged(
            METRIC_SERVE_QUEUE_DEPTH, [f"engine:{rid}"]
        )
        live = float(sample.value) if sample is not None else 0.0
        with self._lock:
            rep = self._replicas.get(rid)
            local = len(rep.inbox) if rep is not None else 0
        return live + local

    # -------------------------------------------------------- observability
    def _log(self, kind: str, **fields) -> None:
        if self.decision_log is not None:
            self.decision_log.record(kind, **fields)

    def _trip_fleet(self, reason: str, detail: dict,
                    journey_ids: Optional[Sequence[str]]) -> None:
        """Freeze the decision ring + the affected cohort's stitched
        journeys into a fleet postmortem dump — once per reason per
        run (the engine flight recorder's discipline), persisted to
        NEXUS_FLIGHT_DUMP_DIR when set."""
        if self.decision_log is None or reason in self._tripped:
            return
        self._tripped.add(reason)
        cohort = None
        if self._book is not None:
            with self._lock:
                cohort = self._book.to_dict(only=journey_ids)
        dump = self.decision_log.trip(reason, detail, journeys=cohort)
        self._obs_dumps.append(dump)
        dump_dir = os.environ.get("NEXUS_FLIGHT_DUMP_DIR", "")
        if dump_dir:
            try:
                from nexus_tpu.obs.recorder import write_dump

                write_dump(dump, os.path.join(
                    dump_dir, f"fleet-{self.template}-{reason}.json",
                ))
            except Exception:  # noqa: BLE001 — telemetry never blocks recovery
                logger.debug("fleet obs dump not persisted", exc_info=True)

    # ------------------------------------------------------------ membership
    def alive_ids(self) -> List[str]:
        with self._lock:
            return [
                rid for rid, r in self._replicas.items()
                if not (r.killed or r.draining or r.stopped)
            ]

    def _spawn_replica(self) -> str:
        with self._lock:
            rid = f"r{self._spawn_counter}"
            self._spawn_counter += 1
        engine = self.make_engine(rid)
        rep = _Replica(rid, engine)
        with self._lock:
            self._replicas[rid] = rep
        t = threading.Thread(
            target=self._worker, args=(rep,), daemon=True,
            name=f"serve-fleet-{self.template}-{rid}",
        )
        rep.thread = t
        t.start()
        self.router.add_replica(rid)
        self._log("spawn", replica=rid)
        return rid

    # ------------------------------------------------------------------ chaos
    def kill_replica(self, rid: str, hard: bool = True) -> bool:
        """Launcher-style kill of one replica: its renewer falls silent
        (the detector must confirm by lease expiry) and its current
        serve call drains at the next wave boundary. Returns True if
        the replica existed and was alive."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or rep.killed or rep.stopped:
                return False
            rep.killed = True
            cancel = rep.cancel
        self.router.remove_replica(rid)
        self._log("kill", replica=rid, hard=bool(hard))
        if cancel is not None:
            cancel.cancel(hard=hard)
        return True

    # ----------------------------------------------------------------- worker
    def _worker(self, rep: _Replica) -> None:
        from nexus_tpu.utils.signals import CancelToken

        renewer = LeaseRenewer(
            self.store, self.namespace,
            serve_replica_template(self.template, rep.id),
            holder=rep.id, ttl_seconds=self.ttl,
        )
        idle_wait = max(0.005, self.ttl / 4.0)
        # the lease is BORN at the replica's first served wave, not at
        # spawn: an engine's first serve() call compiles its programs
        # in silence, and a lease created before that gap would expire
        # mid-compile and read as a death (the single-engine supervisor
        # has the same property — its renewer first writes at the first
        # wave boundary). No lease, nothing to confirm.
        lease_live = [False]

        def hb(step: int) -> None:
            with self._lock:
                silenced = rep.killed
            if not silenced:
                renewer.renew(int(step))
                lease_live[0] = True
            if self.pace_s > 0:
                self._sleep(self.pace_s)

        graceful = False
        while True:
            with self._lock:
                if self._shutdown or rep.killed or rep.draining:
                    graceful = rep.draining and not rep.killed
                    break
                has_work = bool(rep.inbox)
            if not has_work:
                if lease_live[0]:  # idle AFTER first serve: stay alive
                    renewer.renew(rep.committed)
                self._sleep(idle_wait)
                continue
            if self._sema is not None:
                self._sema.acquire()
            try:
                with self._lock:
                    if self._shutdown or rep.killed or rep.draining:
                        graceful = rep.draining and not rep.killed
                        break
                    batch = rep.inbox
                    rep.inbox = []
                    if not batch:
                        continue
                    cancel = CancelToken()
                    rep.cancel = cancel
                    rep.current_batch = batch
                    rep.busy = True
                # one FRESH tracer per serve call (round 15): this
                # call's span timelines become the batch's journey
                # legs, without touching the engine-attached
                # observability surface (gauges keep publishing, the
                # engine's own flight recorder keeps recording)
                call_tracer = (
                    ServeTracer() if self._book is not None else None
                )
                t0 = self._clock()
                # arrival rebase (round 16 streaming): an entry's
                # arrival is stamped on the FLEET's streaming clock;
                # the engine anchors queue/latency on ITS OWN call
                # clock, so shift each arrival by this call's start
                # (negative = the request waited in the inbox before
                # this engine ever saw it — exactly the wait the
                # arrival-anchored queue_s must charge)
                stream_t0 = self._stream_t0
                if stream_t0 is not None:
                    import dataclasses

                    rel = t0 - stream_t0
                    serve_reqs = [
                        dataclasses.replace(
                            e.request,
                            arrival_s=(
                                float(e.arrival_s) - rel
                                if e.arrival_s is not None else 0.0
                            ),
                        )
                        for e in batch
                    ]
                else:
                    serve_reqs = [e.request for e in batch]
                try:
                    r_results, r_metrics = rep.engine.serve(
                        serve_reqs,
                        cancel=cancel, heartbeat=hb, tracer=call_tracer,
                        ext_backlog=lambda: self._inbox_depth(rep),
                    )
                except BaseException as e:  # noqa: BLE001 — surfaced by run()
                    with self._lock:
                        rep.error = e
                        rep.busy = False
                        rep.stopped = True
                    return
                elapsed = self._clock() - t0
            finally:
                if self._sema is not None:
                    self._sema.release()
            drained = (
                list(rep.engine.last_drain or [])
                if r_metrics.get("interrupted") else []
            )
            dump = getattr(rep.engine, "last_flight_dump", None)
            # fleet-side batch annotation: which serve calls carried
            # MIGRATED entries (death/scale-down requeues) — the chaos
            # tests and bench read re-match evidence off exactly these
            r_metrics = dict(r_metrics)
            r_metrics["fleet_batch_requests"] = len(batch)
            r_metrics["fleet_batch_migrated"] = any(
                int(getattr(e.request, "retries", 0) or 0) > 0
                for e in batch
            )
            if self.fleet_gauges is not None:
                # per-replica affinity yield: radix-matched tokens over
                # prompt tokens this call served — the router's
                # locality, measured where it pays (tagged engine:<id>,
                # stamped with the replica's serve-call count)
                prompt_toks = sum(
                    len(e.request.prompt) for e in batch
                )
                self._client.gauge(
                    METRIC_SERVE_AFFINITY_HIT_RATE,
                    round(
                        int(r_metrics.get("prefix_hit_tokens", 0) or 0)
                        / max(1, prompt_toks), 4,
                    ),
                    tags=[f"engine:{rep.id}"],
                    stamp=float(rep.serve_calls + 1),
                )
            with self._lock:
                rep.busy = False
                rep.cancel = None
                rep.current_batch = None
                rep.serve_calls += 1
                rep.busy_s += elapsed
                rep.committed += int(
                    r_metrics.get("committed_tokens", 0) or 0
                )
                rep.metrics_log.append(r_metrics)
                if call_tracer is not None:
                    # stitch this call's timelines in as journey legs
                    # (t_start on the fleet clock orders legs globally;
                    # span t stays engine-local per the schema)
                    self._book.absorb_trace(
                        call_tracer.to_dict(), replica=rep.id,
                        t_start=t0 - self._t_base,
                        request_idxs=[e.request_idx for e in batch],
                    )
                if drained and dump is not None:
                    rep.flight_dumps.append(dump)
                for entry, res in zip(batch, r_results):
                    if res is not None:
                        self._finished.append((entry, res))
                if drained:
                    rep.pending_drain = (batch, drained)
        if graceful and lease_live[0]:
            # scale-down: mark the lease done so the detector reads the
            # silence that follows as completion, never as a death
            renewer.complete(rep.committed)
        with self._lock:
            rep.stopped = True

    # ---------------------------------------------------------------- monitor
    def _probe(self) -> List:
        try:
            heartbeats = list_heartbeats(self.store)
        except Exception as e:  # noqa: BLE001 — outage is an observation
            return self.detector.observe_api_error(self.shard, e)
        return self.detector.observe(self.shard, heartbeats)

    def _confirmed_replicas(self, events) -> List[Tuple[str, float]]:
        out = []
        for ev in events:
            if ev.kind != EVENT_LEASE_EXPIRED or ev.lease is None:
                continue
            rid = replica_of_serve_lease(ev.lease.template, self.template)
            if rid is not None:
                out.append((rid, float(ev.detection_seconds)))
        return out

    def _reap_lease(self, rid: str) -> None:
        from nexus_tpu.api.types import ConfigMap
        from nexus_tpu.cluster.store import NotFoundError

        try:
            self.store.delete(
                ConfigMap.KIND, self.namespace,
                heartbeat_name(serve_replica_template(self.template, rid)),
            )
        except NotFoundError:
            pass
        except Exception:  # noqa: BLE001 — cleanup is advisory
            logger.debug("fleet lease reap incomplete", exc_info=True)

    def _dispatch(self, entries: Sequence[RequeueEntry],
                  report: Dict[str, Any]) -> None:
        """Route entries (priority-ordered) into replica inboxes. The
        workers pick assigned batches up as soon as they land, so later
        decisions of one dispatch already read live gauges."""
        if not entries:
            return
        for entry, rid, _spilled in self.router.route_batch(entries):
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is not None and not (
                    rep.killed or rep.draining or rep.stopped
                ):
                    rep.inbox.append(entry)
                    continue
            # raced a death/scale between rank and append: the router
            # may still list the stale member (its removal runs after
            # the killed flag lands), so rendezvous could hand the SAME
            # dead replica back — drop stale members as we find them
            # and retry until a live one answers or none remain
            placed = False
            for _ in range(8):
                self.router.unroute(rid)  # the abandoned assignment
                self.router.remove_replica(rid)
                if not self.router.replicas():
                    break
                rid, _ = self.router.route(entry.request)
                with self._lock:
                    rep = self._replicas.get(rid)
                    if rep is not None and not (
                        rep.killed or rep.draining or rep.stopped
                    ):
                        rep.inbox.append(entry)
                        placed = True
                        break
            if not placed:
                raise RuntimeError(
                    "no live replica to route to (all routed "
                    "candidates dead or draining)"
                )
        report["dispatches"] = report.get("dispatches", 0) + len(entries)

    def _collect_retired(self, rep: _Replica, report: Dict[str, Any],
                         reason: str = "death") -> List[RequeueEntry]:
        """Harvest a dead/draining replica's unfinished work: drained
        in-flight entries re-enter through the planner (committed
        tokens folded into the merged prompt), never-admitted inbox
        entries requeue verbatim — in that order, preserving the dying
        engine's serving order ahead of its backlog. The audit log
        records the drain→requeue mapping (which journeys left this
        replica, and why); their subsequent ``route`` events are the
        requeue side."""
        with self._lock:
            pending = rep.pending_drain
            rep.pending_drain = None
            inbox = rep.inbox
            rep.inbox = []
            rep.collected = True
            dumps = list(rep.flight_dumps)
        requeued: List[RequeueEntry] = []
        if pending is not None:
            batch, drained = pending
            requeued.extend(self.planner.requeue(batch, drained))
        requeued.extend(inbox)
        if self._stream_t0 is not None:
            # streaming: a migrated entry RE-ARRIVES now — restamp so
            # its next engine charges the post-requeue wait as queue
            # time (prior serve time rides elapsed_s into the stitched
            # latency; the detection gap stays uncharged, the planner's
            # documented engine-clock-pauses discipline)
            now_rel = self._clock() - self._stream_t0
            for e in requeued:
                e.arrival_s = now_rel
        jids = [
            str(getattr(e.request, "journey", "") or "")
            for e in requeued
        ]
        self._log("drain", replica=rep.id, reason=reason, journeys=jids)
        if reason == "death":
            self._death_journeys.extend(j for j in jids if j)
        report["flight_dumps"].extend(dumps)
        report["migrations"] += len(requeued)
        return requeued

    def _handle_death(self, rid: str, detection_s: Optional[float],
                      report: Dict[str, Any]) -> None:
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or rep.collected:
                return
            was_killed = rep.killed
            rep.killed = True
            cancel = rep.cancel
        self.router.remove_replica(rid)
        if not was_killed:
            # confirmed dead with the process still serving: a WEDGED
            # engine — fence it before its requests re-enter the queue
            report["fenced_alive"] = True
            if cancel is not None:
                cancel.cancel(hard=True)
        if rep.thread is not None:
            rep.thread.join(timeout=30.0)
        if rep.thread is not None and rep.thread.is_alive():
            raise RuntimeError(
                f"fleet replica {rid!r} did not stop within 30s of "
                "fencing; its requests cannot be drained in-process"
            )
        report["deaths"] += 1
        if detection_s is not None:
            report["detections_s"].append(detection_s)
        self._log(
            "death_confirmed", replica=rid,
            detection_s=(
                round(float(detection_s), 6)
                if detection_s is not None else None
            ),
            fenced_alive=not was_killed,
        )
        if report["deaths"] > self.max_failures:
            raise RuntimeError(
                f"serve fleet gave up after {self.max_failures} replica "
                "deaths"
            )
        requeued = self._collect_retired(rep, report, reason="death")
        self._reap_lease(rid)
        if report["deaths"] >= self.death_storm_threshold:
            # a DEATH STORM: several replicas confirmed dead in one run
            # — freeze the decision ring with the drained cohort's
            # journeys (each engine's own recorder shows ONE drain;
            # only the fleet view shows the storm)
            self._trip_fleet(
                "death_storm",
                {"deaths": report["deaths"],
                 "detections_s": [
                     round(float(d), 6) for d in report["detections_s"]
                 ]},
                journey_ids=list(dict.fromkeys(self._death_journeys)),
            )
        if not self.alive_ids():
            # last replica died: spawn a replacement or the queue
            # strands (the single-engine supervisor's restart, at
            # fleet scope)
            new_rid = self._spawn_replica()
            report["scale_events"].append(
                {"kind": "respawn", "replica": new_rid}
            )
        self._dispatch(requeued, report)

    def _lease_exists(self, rid: str) -> bool:
        from nexus_tpu.api.types import ConfigMap
        from nexus_tpu.cluster.store import NotFoundError

        try:
            self.store.get(
                ConfigMap.KIND, self.namespace,
                heartbeat_name(serve_replica_template(self.template, rid)),
            )
            return True
        except NotFoundError:
            return False
        except Exception:  # noqa: BLE001 — outage: let the detector decide
            return True

    def _harvest_leaseless_kills(self, report: Dict[str, Any]) -> None:
        """A replica killed DURING ITS FIRST serve's program compile
        never renewed, so its lease was never born and the detector has
        nothing to confirm — but its worker has exited and its drain
        snapshot is final. Requeue directly; every killed replica whose
        lease DOES exist still waits for detector confirmation (the
        PR 6 discipline: never requeue work an unconfirmed engine might
        still be committing)."""
        with self._lock:
            candidates = [
                r for r in self._replicas.values()
                if r.killed and r.stopped and not r.collected
            ]
        for rep in candidates:
            if not self._lease_exists(rep.id):
                self._handle_death(rep.id, None, report)

    def _scale_down(self, report: Dict[str, Any], reason: str) -> None:
        # LIFO victim: the newest replica has the coldest cache and the
        # fewest affinity keys homed on it
        alive = self.alive_ids()
        if len(alive) <= 1:
            return
        rid = alive[-1]
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            rep.draining = True
            cancel = rep.cancel
        self.router.remove_replica(rid)
        if cancel is not None:
            cancel.cancel(hard=False)
        report["scale_events"].append(
            {"kind": "down", "replica": rid, "reason": reason}
        )

    def _scale_up(self, report: Dict[str, Any], reason: str) -> None:
        rid = self._spawn_replica()
        report["scale_events"].append(
            {"kind": "up", "replica": rid, "reason": reason}
        )

    def _autoscale_poll(self, report: Dict[str, Any]) -> None:
        if self.autoscaler is None:
            return
        alive = self.alive_ids()
        if not alive:
            return
        samples = []
        with self._lock:
            busy = {
                rid: self._replicas[rid].busy
                for rid in alive if rid in self._replicas
            }
        for rid in alive:
            samples.append(read_replica_sample(
                self._client, rid, busy=busy.get(rid, False)
            ))
        decision = self.autoscaler.observe(samples, current=len(alive))
        # the audit record: the decision WITH the per-replica vitals it
        # was computed from (NaN = never published → None, JSON-safe)
        self._log(
            "scale_decision",
            current=decision.current, target=decision.target,
            reason=decision.reason,
            breach_streak=decision.breach_streak,
            clear_streak=decision.clear_streak,
            stale=list(decision.stale),
            samples=[
                {
                    "replica": s.replica_id, "busy": s.busy,
                    "ttft_p95_s": (
                        None if math.isnan(s.ttft_p95_s)
                        else round(s.ttft_p95_s, 6)
                    ),
                    "queue_depth": (
                        None if math.isnan(s.queue_depth)
                        else round(s.queue_depth, 3)
                    ),
                    "seq": s.seq,
                }
                for s in samples
            ],
        )
        if decision.stale:
            report["stale_observations"] += 1
        if decision.target != decision.current:
            direction = 1 if decision.target > decision.current else -1
            last = self._last_scale
            if (last is not None and last[1] == -direction
                    and self._monitor_polls - last[0] <= self.flap_window):
                # AUTOSCALE FLAPPING: a reversal inside the flap window
                # — hysteresis should make this rare, so when it
                # happens the decisions (and their gauge evidence)
                # leading up to it are exactly the postmortem
                self._trip_fleet(
                    "autoscale_flap",
                    {"window_polls": self.flap_window,
                     "reversal": f"{last[1]:+d} -> {direction:+d}",
                     "reason": decision.reason},
                    journey_ids=None,  # the whole in-flight cohort
                )
            self._last_scale = (self._monitor_polls, direction)
        if decision.target > decision.current:
            self._scale_up(report, decision.reason)
        elif decision.target < decision.current:
            self._scale_down(report, decision.reason)

    # -------------------------------------------------------------------- run
    def _fresh_streamed(self, reqs: Sequence[Any],
                        base: int) -> List[RequeueEntry]:
        """Planner-``fresh`` semantics for a MID-RUN delivery: indices
        and journey ids continue from ``base`` (the queue length before
        this delivery), and each entry keeps its source-stamped arrival
        on the fleet streaming clock."""
        import dataclasses

        out: List[RequeueEntry] = []
        for k, req in enumerate(reqs):
            i = base + k
            if (dataclasses.is_dataclass(req)
                    and hasattr(req, "journey")
                    and not getattr(req, "journey")):
                req = dataclasses.replace(req, journey=f"j{i}")
            out.append(RequeueEntry(
                request_idx=i, request=req,
                arrival_s=float(getattr(req, "arrival_s", 0.0) or 0.0),
            ))
        return out

    def run_stream(self, source: Any, timeout_s: float = 180.0
                   ) -> Tuple[List[Optional[Any]], Dict[str, Any]]:
        """Open-loop drive: serve everything ``source`` delivers (see
        ``run``'s ``source=``) starting from an empty queue."""
        return self.run([], timeout_s=timeout_s, source=source)

    def run(self, requests: Sequence[Any], timeout_s: float = 180.0,
            source: Any = None,
            ) -> Tuple[List[Optional[Any]], Dict[str, Any]]:
        """Serve ``requests`` to terminal results across the fleet →
        ``(results, report)``. ``results[i]`` answers ``requests[i]``
        (None only for requests genuinely lost — the acceptance gate
        requires zero). The report carries deaths/detections, scale
        events, migrations, the router ledger, per-replica serve
        metrics (``replica_metrics`` — every engine teardown's pool
        partition rides here for the leak audit), and flight dumps of
        every drained generation.

        ``source`` (round 16) streams arrivals INTO the running fleet:
        every monitor poll delivers ``source.poll(now_s)`` (``now_s``
        seconds since run start), routes the new entries while engines
        serve, and the run completes only when the source is exhausted
        AND every delivered request has a result — ``results`` then
        answers ``requests`` + deliveries in arrival order. Entry
        arrivals anchor queue/latency attribution (see ``_worker``'s
        rebase) and ``report['streamed']`` counts deliveries."""
        requests = list(requests)
        results: List[Optional[Any]] = [None] * len(requests)
        run_t0 = self._clock()
        if source is not None:
            self._stream_t0 = run_t0
        report: Dict[str, Any] = {
            "deaths": 0,
            "detections_s": [],
            "migrations": 0,
            "fenced_alive": False,
            "scale_events": [],
            "stale_observations": 0,
            "flight_dumps": [],
        }
        attached_log = (
            self.decision_log is not None
            and self.router.decision_log is None
        )
        if attached_log:
            self.router.decision_log = self.decision_log
        for _ in range(self.initial_replicas):
            self._spawn_replica()
        try:
            entries = self.planner.fresh(requests)
            if source is not None:
                for e in entries:
                    e.arrival_s = float(
                        getattr(e.request, "arrival_s", 0.0) or 0.0
                    )
            self._dispatch(entries, report)
            deadline = self._clock() + float(timeout_s)
            while True:
                if source is not None:
                    fresh = source.poll(self._clock() - run_t0)
                    if fresh:
                        new_entries = self._fresh_streamed(
                            fresh, base=len(requests)
                        )
                        requests.extend(fresh)
                        results.extend([None] * len(fresh))
                        report["streamed"] = (
                            report.get("streamed", 0) + len(fresh)
                        )
                        self._dispatch(new_entries, report)
                with self._lock:
                    finished = self._finished
                    self._finished = []
                    errors = [
                        r.error for r in self._replicas.values()
                        if r.error is not None
                    ]
                if errors:
                    raise errors[0]
                for entry, res in finished:
                    stitched = self.planner.stitch(entry, res)
                    results[entry.request_idx] = stitched
                    if (self.fleet_gauges is not None
                            and stitched is not None):
                        # merged-sample fleet percentiles + the SLO
                        # counter feed on every stitched finish —
                        # "ok"/"failed_over" = completed (the planner's
                        # terminal-status contract)
                        self.fleet_gauges.observe_result(
                            stitched.ttft_s, stitched.latency_s,
                            ok=stitched.status in ("ok", "failed_over"),
                        )
                if all(r is not None for r in results) and (
                    source is None or source.exhausted()
                ):
                    break
                if self._clock() > deadline:
                    raise TimeoutError(
                        f"fleet serve of {self.template!r} exceeded "
                        f"{timeout_s}s with "
                        f"{sum(1 for r in results if r is None)} requests "
                        "outstanding"
                    )
                for rid, detection in self._confirmed_replicas(
                    self._probe()
                ):
                    self._handle_death(rid, detection, report)
                self._harvest_leaseless_kills(report)
                # graceful scale-down drains complete asynchronously:
                # harvest any retired replica whose worker has exited
                with self._lock:
                    retired = [
                        r for r in self._replicas.values()
                        if r.draining and r.stopped and not r.collected
                        and not r.killed
                    ]
                for rep in retired:
                    self._dispatch(
                        self._collect_retired(
                            rep, report, reason="scale_down",
                        ),
                        report,
                    )
                self._autoscale_poll(report)
                self._monitor_polls += 1
                if self.fleet_gauges is not None:
                    self.fleet_gauges.publish(
                        self.alive_ids(),
                        stamp=float(self._monitor_polls),
                    )
                self._sleep(self.poll_s)
        finally:
            with self._lock:
                self._shutdown = True
                threads = [
                    r.thread for r in self._replicas.values()
                    if r.thread is not None
                ]
            for t in threads:
                t.join(timeout=30.0)
            if attached_log:
                self.router.decision_log = None
            self._stream_t0 = None
        with self._lock:
            report["replica_metrics"] = {
                rid: list(r.metrics_log)
                for rid, r in self._replicas.items()
            }
            report["replica_committed"] = {
                rid: r.committed for rid, r in self._replicas.items()
            }
            report["replica_busy_s"] = {
                rid: round(r.busy_s, 6)
                for rid, r in self._replicas.items()
            }
            report["replicas_started"] = self._spawn_counter
        report.update(self.router.ledger())
        report["requests_lost"] = sum(1 for r in results if r is None)
        # ---- fleet observability (round 15) ----
        if self._book is not None:
            with self._lock:
                report["journeys"] = self._book.to_dict()
        if self.decision_log is not None:
            report["fleet_decision_log"] = self.decision_log.to_dict()
        report["fleet_obs_dumps"] = list(self._obs_dumps)
        if self.fleet_gauges is not None:
            # one final federated publication so post-run scrapes see
            # the end state (and the percentiles of the whole run)
            self.fleet_gauges.publish(
                self.alive_ids(), stamp=float(self._monitor_polls + 1),
            )
        if self.slo_s > 0:
            wall = max(1e-9, self._clock() - run_t0)
            report["slo"] = {
                **goodput_under_slo(
                    [r for r in results if r is not None],
                    self.slo_s, wall,
                ),
                "wall_s": round(wall, 6),
                "verdicts": (
                    slo_verdicts(report["journeys"], self.slo_s)
                    if self._book is not None else []
                ),
            }
        return results, report
