"""The reconciliation core: converge declared templates/workgroups onto shards.

Behavioral spec (reproduced, not translated, from the reference
``controller.go`` — see SURVEY.md §2a/§3 for the full catalog):

  * Template/Workgroup add+update events enqueue the object; Secret/ConfigMap
    events resolve ``ownerReferences`` to the owning template and enqueue it
    (reference: controller.go:169-224), with a resourceVersion-equality skip
    on resync updates (controller.go:322-328).
  * Template delete events fan the delete out to every shard inline
    (reference: controller.go:196-205 — the known-unclear delete path). This
    build *also* supports a principled finalizer-based path via
    ``use_finalizers=True`` (SURVEY.md §7 hard part (f)).
  * The work loop pops a rate-limited queue; success → ``forget``; failure →
    ``add_rate_limited`` with MaxOf(per-item exponential, global bucket)
    backoff (controller.go:373-426, 257-260). Two gauges per item:
    ``reconcile_latency`` and ``workqueue_length`` (controller.go:389-390).
  * ``template_sync_handler``: lister get → init condition (only when the
    resource has no conditions) → adopt referenced secrets/configmaps in the
    controller cluster → per shard: create-or-update template (spec
    DeepEqual-drift), sync secrets, sync configmaps → ready condition with
    synced bookkeeping → Synced event. Fail-fast on first error → requeue
    (controller.go:761-845).
  * Rogue detection: a shard resource with zero owner references is "rogue" —
    warning event + error; owned-by-someone-else → adopt by appending this
    template's owner reference (controller.go:484-502).

Concurrency model (beyond the reference, which loops shards sequentially):
per-shard work in ``template_sync_handler`` / ``workgroup_sync_handler`` /
the delete fan-outs runs on a bounded per-controller
:class:`~nexus_tpu.controller.sharding.ShardSyncExecutor`; the first shard
error cooperatively cancels unstarted siblings and every error aggregates
into one ``SyncError`` → one rate-limited requeue, exactly like the
sequential path. A content-hash
:class:`~nexus_tpu.controller.sharding.WriteSkipCache` lets re-reconciles
of unchanged templates/secrets/configmaps skip the per-shard compare/write
entirely (see docs/reconciler-concurrency.md).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

from nexus_tpu.api.template import NexusAlgorithmTemplate
from nexus_tpu.api.types import (
    API_VERSION,
    CONTROLLER_APP_NAME,
    LABEL_CONTROLLER_APP,
    ConfigMap,
    OwnerReference,
    Secret,
    deep_equal,
    new_resource_ready_condition,
    utcnow,
)
from nexus_tpu.api.workgroup import NexusAlgorithmWorkgroup
from nexus_tpu.cluster.informer import InformerFactory
from nexus_tpu.cluster.store import ClusterStore, NotFoundError
from nexus_tpu.controller.events import (
    EVENT_TYPE_NORMAL,
    EVENT_TYPE_WARNING,
    FIELD_MANAGER,
    MSG_RESOURCE_EXISTS,
    MSG_RESOURCE_MISSING,
    MSG_RESOURCE_OPERATION_FAILED,
    MSG_RESOURCE_SYNCED,
    REASON_ERR_PLACEMENT,
    REASON_ERR_RESOURCE_EXISTS,
    REASON_ERR_RESOURCE_MISSING,
    REASON_ERR_RESOURCE_SYNC,
    REASON_SYNCED,
    EventRecorder,
)
from nexus_tpu.controller.sharding import (
    ShardFanOutError,
    ShardSyncExecutor,
    WriteSkipCache,
    stable_hash,
)
from nexus_tpu.ha.failover import FailoverConfig, FailoverManager
from nexus_tpu.shards.shard import Shard
from nexus_tpu.utils.telemetry import (
    METRIC_COALESCED_TOTAL,
    METRIC_RECONCILE_LATENCY,
    METRIC_SHARD_SYNC_LATENCY,
    METRIC_TEMPLATE_TO_RUNNING,
    METRIC_TEMPLATE_TO_RUNNING_P50,
    METRIC_WORKQUEUE_DEPTH,
    METRIC_WORKQUEUE_LENGTH,
    StatsdClient,
    get_client,
)

logger = logging.getLogger("nexus_tpu.controller")

TYPE_TEMPLATE = "template"
TYPE_WORKGROUP = "workgroup"

FINALIZER = "science.sneaksanddata.com/shard-cleanup"


@dataclass(frozen=True)
class Element:
    """Work-queue element: object reference + kind tag (reference:
    controller.go:86-96). Frozen → hashable → dedupable by the queue."""

    namespace: str
    name: str
    obj_type: str


class SyncError(RuntimeError):
    pass


class Controller:
    """Multi-cluster configuration controller."""

    def __init__(
        self,
        controller_store: ClusterStore,
        shards: Sequence[Shard],
        informer_factory: Optional[InformerFactory] = None,
        recorder: Optional[EventRecorder] = None,
        statsd: Optional[StatsdClient] = None,
        failure_rate_base_delay: float = 0.030,
        failure_rate_max_delay: float = 5.0,
        rate_limit_elements_per_second: float = 50.0,
        rate_limit_elements_burst: int = 300,
        use_finalizers: bool = True,
        resync_period: float = 30.0,
        queue_backend: str = "auto",
        shard_sync_workers: int = 0,
        write_skip_cache: bool = True,
        failover: Optional[FailoverConfig] = None,
    ):
        self.store = controller_store
        self.shards = list(shards)
        # Parallel shard fan-out: one bounded executor per controller shared
        # by all reconcile workers. 0 = auto-size (resolved in run(), where
        # the reconcile worker count is known: shards x workers, capped —
        # sizing to shard count alone makes concurrent reconciles queue for
        # fan-out slots and halves the win); 1 = strictly sequential
        # reference behavior.
        self._shard_sync_workers_auto = shard_sync_workers <= 0
        if shard_sync_workers <= 0:
            shard_sync_workers = min(8, max(1, len(self.shards)))
        self.shard_executor = ShardSyncExecutor(shard_sync_workers)
        # Content-hash write-skip cache: unchanged specs/data skip the
        # per-shard compare + write entirely (invalidated automatically by
        # shard-side resourceVersion changes, explicitly on deletes/rogues).
        self.write_skip_cache = WriteSkipCache()
        self._write_skip = bool(write_skip_cache)
        self.informers = informer_factory or InformerFactory(
            controller_store, resync_period=resync_period
        )
        if recorder is None:
            # real-cluster stores post v1 Events (reference broadcaster →
            # EventSink, controller.go:252-256); in-process stores just log
            sink = getattr(controller_store, "create_event", None)
            recorder = EventRecorder(sink=sink)
        self.recorder = recorder
        self.statsd = statsd or get_client()
        self.use_finalizers = use_finalizers

        # native (C++) queue when it builds/loads; Python otherwise — both
        # implement the same client-go contract (see nexus_tpu/native).
        from nexus_tpu.native import make_queue

        self.work_queue = make_queue(
            base_delay=failure_rate_base_delay,
            max_delay=failure_rate_max_delay,
            rate=rate_limit_elements_per_second,
            burst=rate_limit_elements_burst,
            backend=queue_backend,
        )

        self.template_informer = self.informers.informer(NexusAlgorithmTemplate.KIND)
        self.workgroup_informer = self.informers.informer(NexusAlgorithmWorkgroup.KIND)
        self.secret_informer = self.informers.informer(Secret.KIND)
        self.config_map_informer = self.informers.informer(ConfigMap.KIND)

        self.template_lister = self.template_informer.lister
        self.workgroup_lister = self.workgroup_informer.lister
        self.secret_lister = self.secret_informer.lister
        self.config_map_lister = self.config_map_informer.lister

        self._register_handlers()
        self._workers: List[threading.Thread] = []
        self._stop = threading.Event()
        # template-to-running latency bookkeeping (BASELINE config #3):
        # first-Running timestamps by template uid + rolling samples for p50
        self._t2r_lock = threading.Lock()
        self._t2r_emitted: set = set()
        self._t2r_samples: List[float] = []
        # Shard health + single-home placement state (nexus_tpu/ha/):
        # every shard starts healthy; the FailoverManager (when configured)
        # flips health on confirmed API outages. _home is the sticky
        # assignment for workgroup scheduling="any" templates; _home_avoid
        # pins the shard a workload last died on so failover placement
        # cannot hand it straight back.
        self._health_lock = threading.Lock()
        self.shard_health: dict = {s.name: True for s in self.shards}
        self._home: dict = {}
        self._home_avoid: dict = {}
        # fleet serve placement (ServeSpec.replicas > 1 under workgroup
        # scheduling="any"): the sticky ORDERED tuple of shard names the
        # template's engine replicas are homed on — the N-home analogue
        # of _home, kept separate so single-home semantics (home_of and
        # the failover planner's lookups) stay byte-for-byte unchanged
        self._replica_homes: dict = {}  # guarded-by: _health_lock
        # sticky replica IDENTITY per (template, shard): ids must not be
        # positional over _replica_homes or a death would shift every
        # later survivor's id (restarting healthy engines — deep-equal
        # sees a new NEXUS_SERVE_REPLICA_ID — and churning their
        # leases). A survivor keeps its id for as long as it stays a
        # home; a replacement takes the smallest id no current home
        # holds (usually the dead replica's — its reaped lease name is
        # reused exactly like a single-engine replacement's).
        self._replica_ids: dict = {}  # guarded-by: _health_lock
        self.failover_manager: Optional[FailoverManager] = (
            FailoverManager(self, failover) if failover is not None else None
        )

    # ------------------------------------------------------------ registration
    def _register_handlers(self) -> None:
        self.template_informer.add_event_handler(
            on_add=self.enqueue_resource,
            on_update=lambda old, new: self.enqueue_resource(new),
            on_delete=self.handle_object_delete,
        )
        self.workgroup_informer.add_event_handler(
            on_add=self._handle_workgroup_event,
            on_update=self._handle_workgroup_update,
            # deletion widens placement back to all shards — re-place
            # referencing templates immediately, same as add/update
            on_delete=self._handle_workgroup_event,
        )
        # Dependent resources: owner-resolution enqueue, with the
        # resourceVersion-equality resync skip (reference:
        # controller.go:322-328,345-351).
        for informer in (self.secret_informer, self.config_map_informer):
            informer.add_event_handler(
                on_add=self.handle_object,
                on_update=self._handle_dependent_update,
                on_delete=self.handle_object,
            )
        # Workload plane: shard-side Job events (status transitions written
        # by the shard's kubelet / local launcher) re-enqueue the owning
        # template so workload phase back-propagates into template status.
        for shard in self.shards:
            shard.job_informer.add_event_handler(
                on_add=self._handle_shard_job_event,
                on_update=lambda old, new: self._handle_shard_job_event(new),
                on_delete=self._handle_shard_job_event,
            )

    def _handle_workgroup_event(self, workgroup) -> None:
        """Enqueue the workgroup itself plus every template whose
        ``workgroup_ref`` names it — a workgroup appearing or changing its
        cluster/capabilities must re-place referencing templates immediately,
        not on the next resync."""
        self.enqueue_resource(workgroup)
        for template in self.template_lister.list(workgroup.metadata.namespace):
            if template.spec.workgroup_ref.name == workgroup.metadata.name:
                self.enqueue_resource(template)

    def _handle_workgroup_update(self, old, new) -> None:
        """Real spec changes fan out to referencing templates; periodic
        resyncs (old is new / unchanged resourceVersion) only re-enqueue the
        workgroup itself — templates already get their own level-triggered
        resync, and W×M fan-out every resync period is pure churn."""
        if (
            old is not None
            and old.metadata.resource_version == new.metadata.resource_version
        ):
            self.enqueue_resource(new)
            return
        self._handle_workgroup_event(new)

    def _handle_dependent_update(self, old, new) -> None:
        if (
            old is not None
            and old.metadata.resource_version == new.metadata.resource_version
        ):
            # periodic resync of an unchanged object — nothing to do
            return
        self.handle_object(new)

    # ----------------------------------------------------------------- enqueue
    def enqueue_resource(self, obj) -> None:
        """Type-switch enqueue of the two CRD kinds (reference:
        controller.go:136-162)."""
        if isinstance(obj, NexusAlgorithmTemplate):
            obj_type = TYPE_TEMPLATE
        elif isinstance(obj, NexusAlgorithmWorkgroup):
            obj_type = TYPE_WORKGROUP
        else:
            logger.error("unsupported type passed into work queue: %r", type(obj))
            return
        self.work_queue.add(
            Element(obj.metadata.namespace, obj.metadata.name, obj_type)
        )

    def handle_object(self, obj) -> None:
        """Resolve a dependent object's ownerReferences to its owning
        template(s) and enqueue them (reference: controller.go:208-221)."""
        for ref in obj.metadata.owner_references:
            if ref.kind != NexusAlgorithmTemplate.KIND:
                continue
            try:
                template = self.template_lister.get(obj.metadata.namespace, ref.name)
            except NotFoundError:
                # a shared secret/configmap may carry refs to several
                # templates; one being gone must not mask the others
                logger.debug(
                    "ignore orphaned owner ref %s on %s", ref.name, obj.key()
                )
                continue
            self.enqueue_resource(template)

    def _handle_shard_job_event(self, job) -> None:
        """A materialized Job changed on a shard: enqueue the owning template
        (resolved via the template label the materializer stamps)."""
        from nexus_tpu.runtime.materializer import LABEL_TEMPLATE

        name = (job.metadata.labels or {}).get(LABEL_TEMPLATE, "")
        if not name:
            return
        try:
            template = self.template_lister.get(job.metadata.namespace, name)
        except NotFoundError:
            return
        self.enqueue_resource(template)

    def handle_object_delete(self, obj) -> None:
        """Template deletion: fan the delete out to every shard (reference
        inline path controller.go:196-205)."""
        if not isinstance(obj, NexusAlgorithmTemplate):
            self.handle_object(obj)
            return
        if self.use_finalizers:
            # DELETED only fires after the finalizer was cleared, i.e. after
            # the sync handler already removed the template from every shard
            return
        logger.info("template %s deleted, removing from shards", obj.key())

        def delete_from_shard(shard: Shard) -> None:
            try:
                shard.delete_template(obj)
            except NotFoundError:
                pass
            except Exception:
                # one unreachable shard must not strand the template on the
                # remaining shards; the finalizer path retries, this inline
                # path at least covers every shard it can
                logger.exception(
                    "error deleting template from shard %s", shard.name
                )
            self.write_skip_cache.invalidate_object(
                shard.name, NexusAlgorithmTemplate.KIND,
                obj.metadata.namespace, obj.metadata.name,
            )

        # every shard is attempted even if one fails (fn swallows errors)
        self._fan_out(self.shards, delete_from_shard, fail_fast=False)
        self.write_skip_cache.invalidate_owner(obj.metadata.uid)
        self._drop_home(obj.metadata.namespace, obj.metadata.name)

    # --------------------------------------------------------------- work loop
    def run(
        self,
        workers: int = 2,
        wait_cache_sync_timeout: float = 30.0,
        warmup_timeout: float = 20.0,
    ) -> None:
        """Start informers, gate on cache sync, spawn worker threads
        (reference: controller.go:851-884)."""
        if self.work_queue.shutting_down():
            raise RuntimeError(
                "controller cannot be restarted after stop(); construct a new "
                "Controller"
            )
        logger.info("starting nexus controller (%d workers)", workers)
        if self._shard_sync_workers_auto and len(self.shards) > 1:
            # every reconcile worker fans out to all shards concurrently;
            # the pool must hold workers x shards tasks to keep them all
            # in flight (bounded so a large fleet can't spawn unbounded
            # threads)
            self.shard_executor.max_workers = min(
                32, max(1, len(self.shards)) * max(1, workers)
            )
        # Warm the model registry off the critical path: template
        # admission (validate -> hbm_budget_gb -> get_family) imports the
        # JAX model stack on first use (~1.3 s cold), and paying that
        # inside the first template's reconcile lands straight in the
        # template-to-running latency (BASELINE config #3's p50).
        warmup = threading.Thread(
            target=self._warm_admission_imports,
            name="nexus-warmup", daemon=True,
        )
        warmup.start()
        self.informers.start()
        for shard in self.shards:
            shard.start()
        if not self.informers.wait_for_cache_sync(wait_cache_sync_timeout):
            raise RuntimeError("failed to wait for controller caches to sync")
        for shard in self.shards:
            if not shard.wait_for_cache_sync(wait_cache_sync_timeout):
                raise RuntimeError(
                    f"failed to wait for shard {shard.name} caches to sync"
                )
        # Readiness gate: don't accept work until admission is warm —
        # otherwise a burst arriving right after startup serializes behind
        # the cold import INSIDE the first reconciles' latency (observed as
        # two ~1.1 s reconciles that drag the whole burst's t2r p50).
        # Bounded: a wedged import must not block the controller forever.
        warmup.join(timeout=max(warmup_timeout, 0.0))
        if warmup.is_alive():
            logger.warning(
                "admission warmup still running after %.0fs; starting "
                "workers anyway", warmup_timeout,
            )
        logger.info("informer caches synced; starting workers")
        self._stop.clear()
        for i in range(workers):
            t = threading.Thread(
                target=self._worker_loop, name=f"nexus-worker-{i}", daemon=True
            )
            t.start()
            self._workers.append(t)
        if self.failover_manager is not None:
            # after caches + workers: failover enqueues templates and reads
            # listers, both of which need the controller fully up
            self.failover_manager.start()

    # ---------------------------------------------------------- shard health
    def set_shard_health(self, shard_name: str, healthy: bool) -> None:
        with self._health_lock:
            self.shard_health[shard_name] = healthy

    def healthy_shards(self) -> List[Shard]:
        with self._health_lock:
            return [s for s in self.shards if self.shard_health.get(s.name, True)]

    def home_of(self, namespace: str, name: str) -> Optional[str]:
        """Sticky single-home assignment (workgroup scheduling="any")."""
        with self._health_lock:
            return self._home.get((namespace, name))

    def replica_homes_of(self, namespace: str, name: str) -> List[str]:
        """Sticky N-home assignment of a fleet serve template
        (ServeSpec.replicas > 1 under workgroup scheduling="any") — the
        ordered shard names its engine replicas are placed on."""
        with self._health_lock:
            return list(self._replica_homes.get((namespace, name), ()))

    def _resolve_replica_ids(self, key, homes: List[str]) -> dict:
        """Sticky replica identity for a fleet template's current homes
        → ``{shard_name: "r<i>"}``. A shard that is still a home keeps
        the id it already held (its engine's lease name, gauge tags,
        and Job spec stay bit-identical — no churn on unrelated
        reconciles, no restart of healthy survivors after another
        replica's death); a NEW home takes the smallest id no current
        home holds, which after a failover is the dead replica's freed
        id (its lease was reaped, exactly the single-engine replacement
        contract)."""
        with self._health_lock:
            assigned = dict(self._replica_ids.get(key, {}))
            ids = {s: assigned[s] for s in homes if s in assigned}
            used = set(ids.values())
            next_i = 0
            for s in homes:
                if s in ids:
                    continue
                while f"r{next_i}" in used:
                    next_i += 1
                ids[s] = f"r{next_i}"
                used.add(f"r{next_i}")
            self._replica_ids[key] = ids
            return dict(ids)

    def evict_home(self, namespace: str, name: str, shard_name: str) -> None:
        """Failover hook: forget the sticky assignment and avoid the shard
        the workload just died on when the next placement runs. For a
        fleet serve template only the replica homed on the dead shard is
        forgotten — the survivors keep their (warm-cache) assignments."""
        with self._health_lock:
            key = (namespace, name)
            if self._home.get(key) == shard_name:
                del self._home[key]
            homes = self._replica_homes.get(key)
            if homes and shard_name in homes:
                self._replica_homes[key] = tuple(
                    h for h in homes if h != shard_name
                )
            self._home_avoid[key] = shard_name

    def _drop_home(self, namespace: str, name: str) -> None:
        with self._health_lock:
            self._home.pop((namespace, name), None)
            self._replica_homes.pop((namespace, name), None)
            self._replica_ids.pop((namespace, name), None)
            self._home_avoid.pop((namespace, name), None)

    @staticmethod
    def _warm_admission_imports() -> None:
        try:
            from nexus_tpu.models.registry import get_family

            get_family("llama").config("tiny")
        except Exception:  # noqa: BLE001 — warmup is best-effort
            logger.debug("admission import warmup failed", exc_info=True)

    def stop(self) -> None:
        if self.failover_manager is not None:
            self.failover_manager.stop()
        self._stop.set()
        self.work_queue.shut_down()
        for t in self._workers:
            t.join(timeout=5.0)
        self._workers = []
        self.shard_executor.shutdown()
        self.informers.stop()
        for shard in self.shards:
            shard.informers.stop()

    # ----------------------------------------------------------- shard fan-out
    def _fan_out(self, shards: Sequence[Shard], fn, fail_fast: bool = True):
        """Run ``fn(shard)`` across shards on the bounded executor, timing
        each task into the per-shard ``shard_sync_latency`` gauge. Errors
        (aggregated across shards) surface as a single :class:`SyncError`
        so the work loop's failure protocol — requeue with backoff — fires
        exactly once per reconcile, as in the sequential reference path."""

        def timed(shard: Shard):
            start = time.monotonic()
            try:
                return fn(shard)
            finally:
                self.statsd.gauge_duration(
                    METRIC_SHARD_SYNC_LATENCY, start,
                    tags=[f"shard:{shard.name}"],
                )

        try:
            return self.shard_executor.map_shards(
                shards, timed, fail_fast=fail_fast
            )
        except ShardFanOutError as e:
            raise SyncError(str(e)) from e.first

    def _worker_loop(self) -> None:
        # wait.UntilWithContext semantics: crash-guard the loop, restart after 1s
        while not self._stop.is_set():
            try:
                while self.process_next_work_item():
                    pass
                return  # queue shut down
            except Exception:
                logger.exception("worker crashed; restarting in 1s")
                time.sleep(1.0)

    def process_next_work_item(self, timeout: Optional[float] = None) -> bool:
        """One queue pop + dispatch (reference: controller.go:373-426)."""
        item, shutdown = self.work_queue.get(timeout=timeout)
        if shutdown:
            return False
        if item is None:  # timeout (test convenience)
            return True
        start = time.monotonic()
        try:
            try:
                if item.obj_type == TYPE_TEMPLATE:
                    self.template_sync_handler(item.namespace, item.name)
                elif item.obj_type == TYPE_WORKGROUP:
                    self.workgroup_sync_handler(item.namespace, item.name)
                else:
                    logger.error("unknown element type in workqueue: %r", item)
            except Exception as e:
                logger.warning("error syncing %r: %s; requeuing", item, e)
                self.work_queue.add_rate_limited(item)
            else:
                self.work_queue.forget(item)
        finally:
            self.work_queue.done(item)
            self.statsd.gauge_duration(
                METRIC_RECONCILE_LATENCY, start, tags=[f"object_type:{item.obj_type}"]
            )
            # same value under two names: workqueue_length is the
            # reference-parity series, workqueue_depth the coalescing
            # queue's native pair with coalesced_total
            depth = self.work_queue.depth()
            self.statsd.gauge(METRIC_WORKQUEUE_LENGTH, depth)
            self.statsd.gauge(METRIC_WORKQUEUE_DEPTH, depth)
            coalesced = getattr(self.work_queue, "coalesced_total", None)
            if coalesced is not None:
                self.statsd.gauge(METRIC_COALESCED_TOTAL, coalesced())
        return True

    def _finalize_template_delete(self, template: NexusAlgorithmTemplate) -> None:
        """Finalizer-based delete: remove from every shard, then clear the
        finalizer so the API server completes the delete. Any shard error
        raises → rate-limited requeue → retried until all shards are clean —
        the crash-safe path the reference lacks (its inline fan-out,
        controller.go:195-205, is fire-and-forget; SURVEY.md §7 hard
        part (f))."""
        logger.info("finalizing delete of template %s", template.key())

        def delete_from_shard(shard: Shard) -> None:
            try:
                shard.delete_template(template)
            except NotFoundError:
                pass  # already gone from this shard
            self.write_skip_cache.invalidate_object(
                shard.name, NexusAlgorithmTemplate.KIND,
                template.metadata.namespace, template.metadata.name,
            )

        # fail_fast=False: cover every reachable shard before the requeue —
        # the finalizer retry then only has the failed shard(s) left to clean
        self._fan_out(self.shards, delete_from_shard, fail_fast=False)
        self.write_skip_cache.invalidate_owner(template.metadata.uid)
        self._drop_home(template.metadata.namespace, template.metadata.name)
        updated = template.deepcopy()
        updated.metadata.finalizers = [
            f for f in updated.metadata.finalizers if f != FINALIZER
        ]
        self.store.update(updated, field_manager=FIELD_MANAGER)
        self.template_lister._delete(template)

    # --------------------------------------------------------- status reports
    def _report_template_init_condition(
        self, template: NexusAlgorithmTemplate
    ) -> NexusAlgorithmTemplate:
        """Init condition is only assigned to new resources (reference:
        controller.go:428-437)."""
        if template.status.conditions:
            return template
        updated = template.deepcopy()
        updated.status.conditions = [
            new_resource_ready_condition(
                utcnow(), False, f'Algorithm "{template.name}" initializing'
            )
        ]
        return self.store.update_status(updated, field_manager=FIELD_MANAGER)  # type: ignore[return-value]

    def _report_workgroup_init_condition(
        self, workgroup: NexusAlgorithmWorkgroup
    ) -> NexusAlgorithmWorkgroup:
        if workgroup.status.conditions:
            return workgroup
        updated = workgroup.deepcopy()
        updated.status.conditions = [
            new_resource_ready_condition(
                utcnow(), False, f'Workgroup "{workgroup.name}" initializing'
            )
        ]
        return self.store.update_status(updated, field_manager=FIELD_MANAGER)  # type: ignore[return-value]

    def _report_template_synced_condition(
        self,
        template: NexusAlgorithmTemplate,
        synced_secrets: List[str],
        synced_config_maps: List[str],
        shard_names: List[str],
        workload_phases: Optional[dict] = None,
    ) -> NexusAlgorithmTemplate:
        """Ready=True + sync bookkeeping, guarded by status DeepEqual so
        no-op reconciles don't write (reference: controller.go:463-480 — the
        new condition first reuses the previous LastTransitionTime so
        DeepEqual sees only real changes)."""
        from nexus_tpu.api.workload import aggregate_phase

        updated = template.deepcopy()
        prev_ltt = updated.status.conditions[0].last_transition_time
        updated.status.conditions[0] = new_resource_ready_condition(
            prev_ltt, True, f'Algorithm "{template.name}" ready'
        )
        updated.status.synced_secrets = list(synced_secrets)
        updated.status.synced_configurations = list(synced_config_maps)
        updated.status.synced_to_clusters = list(shard_names)
        if workload_phases is not None:
            # {} (runtime block absent) clears any stale workload status
            updated.status.workload_phases = dict(workload_phases)
            updated.status.workload_phase = aggregate_phase(
                list(workload_phases.values())
            )
        if not deep_equal(template.status, updated.status):
            updated.status.conditions[0].last_transition_time = utcnow()
            return self.store.update_status(updated, field_manager=FIELD_MANAGER)  # type: ignore[return-value]
        return template

    def _report_workgroup_synced_condition(
        self, workgroup: NexusAlgorithmWorkgroup
    ) -> NexusAlgorithmWorkgroup:
        updated = workgroup.deepcopy()
        prev_ltt = updated.status.conditions[0].last_transition_time
        updated.status.conditions[0] = new_resource_ready_condition(
            prev_ltt, True, f'Workgroup "{workgroup.name}" ready'
        )
        if not deep_equal(workgroup.status, updated.status):
            updated.status.conditions[0].last_transition_time = utcnow()
            return self.store.update_status(updated, field_manager=FIELD_MANAGER)  # type: ignore[return-value]
        return workgroup

    # ------------------------------------------------------ ownership machinery
    def _is_owned_by(self, meta, template: NexusAlgorithmTemplate) -> bool:
        return any(
            ref.uid == template.metadata.uid for ref in meta.owner_references
        )

    def _is_missing_ownership(self, obj, owner) -> bool:
        """Rogue / adoption check (reference: controller.go:484-502).

        Returns True when the object exists but lacks this owner (→ adopt).
        Raises SyncError for rogue objects (zero owner references)."""
        refs = obj.metadata.owner_references
        if refs:
            for ref in refs:
                if (
                    ref.kind == NexusAlgorithmTemplate.KIND
                    and ref.uid == owner.metadata.uid
                ):
                    return False
            return True
        msg = MSG_RESOURCE_EXISTS.format(obj.metadata.name)
        self.recorder.event(obj, EVENT_TYPE_WARNING, REASON_ERR_RESOURCE_EXISTS, msg)
        raise SyncError(msg)

    def _adopt_references(self, template: NexusAlgorithmTemplate) -> None:
        """Append this template's ownerReference to its referenced secrets and
        configmaps in the **controller** cluster (reference:
        controller.go:647-695)."""
        for kind, lister, names in (
            (Secret.KIND, self.secret_lister, template.get_secret_names()),
            (ConfigMap.KIND, self.config_map_lister, template.get_config_map_names()),
        ):
            for name in names:
                try:
                    referenced = lister.get(template.namespace, name)
                except NotFoundError:
                    msg = MSG_RESOURCE_MISSING.format(name, template.name)
                    self.recorder.event(
                        template,
                        EVENT_TYPE_WARNING,
                        REASON_ERR_RESOURCE_MISSING,
                        msg,
                    )
                    raise SyncError(msg)
                if self._is_owned_by(referenced.metadata, template):
                    continue
                updated = referenced.deepcopy()
                updated.metadata.owner_references.append(
                    OwnerReference(
                        api_version=API_VERSION,
                        kind=NexusAlgorithmTemplate.KIND,
                        name=template.name,
                        uid=template.metadata.uid,
                    )
                )
                try:
                    stored = self.store.update(updated)
                except Exception as e:
                    self.recorder.event(
                        template,
                        EVENT_TYPE_WARNING,
                        REASON_ERR_RESOURCE_SYNC,
                        MSG_RESOURCE_OPERATION_FAILED.format(name, template.name, e),
                    )
                    raise
                # keep the local cache hot so subsequent stages observe the
                # adoption even before the watch event lands
                lister._set_if_newer(stored)

    # ------------------------------------------------------- dependent syncing
    def _sync_template_spec_to_shard(
        self,
        template: NexusAlgorithmTemplate,
        shard: Shard,
        spec_hash: str,
    ) -> NexusAlgorithmTemplate:
        """Create-or-update the template on one shard (reference:
        controller.go:790-806), with a write-skip fast path: when the source
        spec hash AND the shard copy's resourceVersion both match the last
        converged sync, the deep-compare and write are skipped outright.
        Any shard-side edit bumps the resourceVersion → automatic miss."""
        namespace, name = template.namespace, template.name
        shard_template: Optional[NexusAlgorithmTemplate]
        try:
            shard_template = shard.template_lister.get(namespace, name)  # type: ignore[assignment]
        except NotFoundError:
            shard_template = None

        if (
            shard_template is not None
            and self._write_skip
            and self.write_skip_cache.check(
                shard.name, NexusAlgorithmTemplate.KIND, namespace, name,
                spec_hash, shard_template.metadata.resource_version,
            )
        ):
            return shard_template

        if shard_template is not None and not deep_equal(
            shard_template.spec, template.spec
        ):
            logger.debug(
                "spec drift for template %s on shard %s, updating",
                name,
                shard.name,
            )
            shard_template = shard.update_template(
                shard_template, template.spec, FIELD_MANAGER
            )
            shard.template_lister._set_if_newer(shard_template)
        elif shard_template is None:
            logger.debug(
                "template %s not found in shard %s, creating", name, shard.name
            )
            shard_template = shard.create_template(
                template.name, template.namespace, template.spec, FIELD_MANAGER
            )
            shard.template_lister._set_if_newer(shard_template)

        if self._write_skip:
            self.write_skip_cache.store(
                shard.name, NexusAlgorithmTemplate.KIND, namespace, name,
                spec_hash, shard_template.metadata.resource_version,
            )
        return shard_template

    def _sync_dependents_to_shard(
        self,
        kind: str,
        names: List[str],
        controller_template: NexusAlgorithmTemplate,
        shard_template: NexusAlgorithmTemplate,
        shard: Shard,
    ) -> None:
        """Shared secret/configmap convergence (reference:
        controller.go:504-626 — the two functions are structurally identical).

        Per referenced name: controller-lister get (missing → warning event +
        error) → shard-lister get (missing → create on shard) → rogue check →
        data drift → update data → missing ownership → update owner."""
        is_secret = kind == Secret.KIND
        controller_lister = self.secret_lister if is_secret else self.config_map_lister
        shard_lister = shard.secret_lister if is_secret else shard.config_map_lister
        create = shard.create_secret if is_secret else shard.create_config_map
        update = shard.update_secret if is_secret else shard.update_config_map
        # write-skip entries are verified per owning template: a hit for one
        # owner must not let another owner skip its own adoption write
        owner_uid = controller_template.metadata.uid

        for name in names:
            try:
                source = controller_lister.get(controller_template.namespace, name)
            except NotFoundError:
                msg = MSG_RESOURCE_MISSING.format(name, controller_template.name)
                self.recorder.event(
                    controller_template,
                    EVENT_TYPE_WARNING,
                    REASON_ERR_RESOURCE_MISSING,
                    msg,
                )
                raise SyncError(msg)

            data_hash = stable_hash(source.data) if self._write_skip else ""
            try:
                shard_obj = shard_lister.get(shard_template.namespace, name)
            except NotFoundError:
                shard_obj = None

            if (
                shard_obj is not None
                and self._write_skip
                and self.write_skip_cache.check(
                    shard.name, kind, shard_template.namespace, name,
                    data_hash, shard_obj.metadata.resource_version,
                    owner_uid,
                )
            ):
                continue  # converged at this exact content + shard rv

            if shard_obj is None:
                try:
                    shard_obj = create(shard_template, source, FIELD_MANAGER)
                except Exception as e:
                    self.recorder.event(
                        controller_template,
                        EVENT_TYPE_WARNING,
                        REASON_ERR_RESOURCE_SYNC,
                        MSG_RESOURCE_OPERATION_FAILED.format(
                            name, controller_template.name, e
                        ),
                    )
                    raise
                shard_lister._set_if_newer(shard_obj)

            try:
                missing_owner = self._is_missing_ownership(shard_obj, shard_template)
            except SyncError as e:
                # rogue object: make sure no stale converged entry survives
                self.write_skip_cache.invalidate_object(
                    shard.name, kind, shard_template.namespace, name
                )
                self.recorder.event(
                    controller_template,
                    EVENT_TYPE_WARNING,
                    REASON_ERR_RESOURCE_SYNC,
                    MSG_RESOURCE_OPERATION_FAILED.format(
                        name, controller_template.name, e
                    ),
                )
                raise

            if not deep_equal(source.data, shard_obj.data):
                logger.debug("content changed for %s %s, updating", kind, name)
                shard_obj = update(shard_obj, source.data, None, FIELD_MANAGER)
                shard_lister._set_if_newer(shard_obj)
            if missing_owner:
                logger.debug("ownership missing for %s %s, updating", kind, name)
                shard_obj = update(shard_obj, None, shard_template, FIELD_MANAGER)
                shard_lister._set_if_newer(shard_obj)

            if self._write_skip:
                self.write_skip_cache.store(
                    shard.name, kind, shard_template.namespace, name,
                    data_hash, shard_obj.metadata.resource_version,
                    owner_uid,
                )

    # ------------------------------------------------------------ sync handlers
    def _resolve_placement(self, template: NexusAlgorithmTemplate) -> List[Shard]:
        """Shards that should receive this template.

        Reference parity: no resolvable workgroup → every shard
        (controller.go:790). TPU extension (BASELINE config #5): a resolved
        workgroup's cluster/capabilities select the matching slice pools.
        Failover extension (nexus_tpu/ha/): only shards the failure
        detector currently considers healthy are candidates, and workgroup
        ``scheduling: any`` single-homes the template (sticky rendezvous
        pick, migrated on confirmed failure).

        Unsatisfiable constraints surface as a Ready=False status condition
        + warning Event (REASON_ERR_PLACEMENT), then a SyncError → requeue —
        operators can see exactly why a constrained template never lands
        instead of a silent infinite requeue loop.
        """
        from nexus_tpu.controller.placement import (
            PlacementError,
            select_home,
            select_replica_homes,
            select_shards,
        )

        ref = template.spec.workgroup_ref
        workgroup = None
        if ref.name:
            try:
                workgroup = self.workgroup_lister.get(
                    template.namespace, ref.name
                )
            except NotFoundError:
                workgroup = None
        try:
            candidates = self.healthy_shards()
            if self.shards and not candidates:
                raise PlacementError(
                    "no healthy shard connected (failure detector marked "
                    f"all {len(self.shards)} shard(s) unhealthy)"
                )
            sched = (
                (workgroup.spec.scheduling or "all").lower()
                if workgroup is not None else "all"
            )
            if sched not in ("all", "any"):
                # loud, not silent: an unvalidated typo falling back to
                # fan-out would run N concurrent copies of a workload the
                # user intended to single-home, racing on its checkpoints
                raise PlacementError(
                    f"workgroup {workgroup.name!r} has unknown scheduling "
                    f"{workgroup.spec.scheduling!r} (all | any)"
                )
            if workgroup is not None and sched == "any":
                key = (template.namespace, template.name)
                replicas = self._serve_replicas(template)
                if replicas > 1:
                    # fleet serve workload (ServeSpec.replicas): N engine
                    # replicas across distinct healthy shards — sticky
                    # per replica (a healthy engine's warm prefix cache
                    # is never migrated by a recomputation), dead shard
                    # avoided, remainder by rendezvous rank so churn
                    # moves only the replicas that lost their home
                    with self._health_lock:
                        current_homes = self._replica_homes.get(key, ())
                        avoid = self._home_avoid.get(key)
                    homes = select_replica_homes(
                        template, workgroup, candidates, replicas,
                        current=current_homes, avoid=avoid,
                    )
                    with self._health_lock:
                        self._replica_homes[key] = tuple(
                            h.name for h in homes
                        )
                    return homes
                with self._health_lock:
                    current = self._home.get(key)
                    avoid = self._home_avoid.get(key)
                home = select_home(
                    template, workgroup, candidates,
                    current=current, avoid=avoid,
                )
                with self._health_lock:
                    self._home[key] = home.name
                return [home]
            return select_shards(template, workgroup, candidates)
        except PlacementError as e:
            self._report_template_placement_error(template, str(e))
            self.recorder.event(
                template,
                EVENT_TYPE_WARNING,
                REASON_ERR_PLACEMENT,
                str(e),
            )
            raise SyncError(str(e)) from e

    @staticmethod
    def _serve_replicas(template: NexusAlgorithmTemplate) -> int:
        """The template's requested serve-engine replica count: >1 only
        for a ``mode: serve`` runtime that declares ``replicas`` — every
        other workload keeps the single-home path bit-for-bit."""
        rt = template.spec.runtime
        if rt is None or getattr(rt, "mode", "") != "serve":
            return 1
        return max(1, int(getattr(rt.serve, "replicas", 1) or 1))

    def _report_template_placement_error(
        self, template: NexusAlgorithmTemplate, msg: str
    ) -> None:
        """Surface an unsatisfiable placement as a Ready=False condition so
        the template's status answers "why is this not running" directly.
        DeepEqual-guarded: the condition is written once per distinct
        message, not on every requeue of the backoff loop. Best-effort — a
        status write failure must not mask the PlacementError itself."""
        if not template.status.conditions:
            return  # init condition not reported yet; next reconcile will
        updated = template.deepcopy()
        prev_ltt = updated.status.conditions[0].last_transition_time
        updated.status.conditions[0] = new_resource_ready_condition(
            prev_ltt, False, f"Placement failed: {msg}"
        )
        if deep_equal(template.status, updated.status):
            return
        updated.status.conditions[0].last_transition_time = utcnow()
        try:
            stored = self.store.update_status(
                updated, field_manager=FIELD_MANAGER
            )
            self.template_lister._set_if_newer(stored)
        except Exception:  # noqa: BLE001 — the SyncError carries the cause
            logger.debug("placement-error status write failed", exc_info=True)

    def template_sync_handler(self, namespace: str, name: str) -> None:
        """Core reconcile (reference: controller.go:761-845)."""
        try:
            template = self.template_lister.get(namespace, name)
        except NotFoundError:
            logger.info(
                "template %s/%s no longer exists; dropping", namespace, name
            )
            # the delete fan-outs already invalidate, but a template that
            # vanished without passing through them (e.g. lister raced the
            # finalizer) must not strand converged entries
            for shard in self.shards:
                self.write_skip_cache.invalidate_object(
                    shard.name, NexusAlgorithmTemplate.KIND, namespace, name
                )
            self._drop_home(namespace, name)
            return

        if self.use_finalizers:
            if template.metadata.deletion_timestamp is not None:
                self._finalize_template_delete(template)
                return
            if FINALIZER not in template.metadata.finalizers:
                updated = template.deepcopy()
                updated.metadata.finalizers.append(FINALIZER)
                template = self.store.update(updated, field_manager=FIELD_MANAGER)  # type: ignore[assignment]
                self.template_lister._set_if_newer(template)

        template = self._report_template_init_condition(template)
        self._adopt_references(template)

        placed_shards = self._resolve_placement(template)

        workgroup = None
        if template.spec.workgroup_ref.name:
            try:
                workgroup = self.workgroup_lister.get(
                    template.namespace, template.spec.workgroup_ref.name
                )
            except NotFoundError:
                workgroup = None

        spec_hash = stable_hash(template.spec) if self._write_skip else ""

        # fleet serve placement (round 15 materializer wiring): each
        # placed shard's engine launches knowing WHICH replica it is,
        # so it renews its own per-replica lease and tags its gauges
        # engine:<id> (the signals the fleet router/autoscaler read).
        # Identity is sticky PER SHARD (_replica_ids), never positional
        # over the homes tuple — see the field's comment.
        replica_ids: dict = {}
        if self._serve_replicas(template) > 1:
            replica_ids = self._resolve_replica_ids(
                (template.namespace, template.name),
                self.replica_homes_of(
                    template.namespace, template.name
                ),
            )

        def sync_one_shard(shard: Shard):
            shard_template = self._sync_template_spec_to_shard(
                template, shard, spec_hash
            )
            self._sync_dependents_to_shard(
                Secret.KIND,
                shard_template.get_secret_names(),
                template,
                shard_template,
                shard,
            )
            self._sync_dependents_to_shard(
                ConfigMap.KIND,
                shard_template.get_config_map_names(),
                template,
                shard_template,
                shard,
            )
            if template.spec.runtime is not None:
                return self._sync_workload_to_shard(
                    template, shard_template, shard, workgroup,
                    replica_id=replica_ids.get(shard.name, ""),
                )
            # runtime block removed: stop + clean up previously
            # materialized workloads (they'd otherwise burn TPU until the
            # template itself is deleted)
            self._remove_workload_from_shard(template, shard)
            return None

        results = self._fan_out(placed_shards, sync_one_shard)

        # per-shard bookkeeping rebuilt in placed-shard order so status and
        # events stay deterministic regardless of task completion order
        workload_phases: dict = {}
        workload_starts: dict = {}
        for shard, result in zip(placed_shards, results):
            if result is None:
                continue
            phase, started_at = result
            workload_phases[shard.name] = phase
            workload_starts[shard.name] = started_at

        self._remove_from_unselected_shards(template, placed_shards)

        if template.spec.runtime is not None:
            self._observe_template_to_running(
                template, workload_phases, workload_starts
            )

        template = self._report_template_synced_condition(
            template,
            template.get_secret_names(),
            template.get_config_map_names(),
            [s.name for s in placed_shards],
            workload_phases,
        )
        self.recorder.event(
            template,
            EVENT_TYPE_NORMAL,
            REASON_SYNCED,
            MSG_RESOURCE_SYNCED.format(NexusAlgorithmTemplate.KIND),
        )

    def _sync_workload_to_shard(
        self,
        template: NexusAlgorithmTemplate,
        shard_template: NexusAlgorithmTemplate,
        shard: Shard,
        workgroup,
        replica_id: str = "",
    ) -> str:
        """Materialize the template's jax_xla runtime as Jobs + headless
        Services on the shard and return the shard's workload phase.

        This is what makes fan-out *real* on Kubernetes shards (the north
        star's "template fan-out launches JAX/XLA jobs on the shard's TPU
        pods") — the reference stops at replicating configuration
        (controller.go:790-831).

        Cross-slice failure policy (multislice): a terminally-Failed slice
        Job (backoffLimit exhausted / fatal exit code) fails the whole
        workload — sibling slice Jobs are deleted (stop burning TPU) and not
        recreated while the failed Job's spec is current. A template spec
        change produces different Job specs, which replaces the failed Job
        and relaunches every slice (the JobSet failurePolicy equivalent).
        """
        from nexus_tpu.api.workload import Job, Service, aggregate_phase
        from nexus_tpu.runtime.materializer import (
            materialize_headless_service,
            materialize_job,
        )

        try:
            job_manifests = materialize_job(
                template, workgroup, shard.name, replica_id=replica_id,
            )
            svc_manifests = materialize_headless_service(template)
        except ValueError as e:
            self.recorder.event(
                template, EVENT_TYPE_WARNING, REASON_ERR_RESOURCE_SYNC, str(e)
            )
            raise SyncError(str(e)) from e

        ns = template.namespace
        # One label-filtered LIST per kind replaces the per-object GETs this
        # loop (and the prune pass below) used to issue — against a remote
        # shard every round trip is a cross-cluster RTT, and the server-side
        # selector keeps the payload O(this template's slices), not
        # O(namespace) (the burst hot path is CPU-bound on conversions).
        from nexus_tpu.runtime.materializer import LABEL_TEMPLATE as _LT

        selector = {
            LABEL_CONTROLLER_APP: CONTROLLER_APP_NAME,
            _LT: template.name,
        }
        jobs_by_name = {
            o.metadata.name: o
            for o in shard.store.list(Job.KIND, ns, label_selector=selector)
        }
        svcs_by_name = {
            o.metadata.name: o
            for o in shard.store.list(
                Service.KIND, ns, label_selector=selector
            )
        }

        for manifest in svc_manifests:
            shard.apply_service(
                shard_template, manifest, FIELD_MANAGER,
                existing=svcs_by_name.get(manifest["metadata"]["name"]),
            )

        current: dict = {
            m["metadata"]["name"]: jobs_by_name.get(m["metadata"]["name"])
            for m in job_manifests
        }

        def _is_current(job, manifest) -> bool:
            return job is not None and deep_equal(
                job.spec, manifest.get("spec") or {}
            )

        failed_current = [
            name
            for name, job in current.items()
            if _is_current(
                job, next(m for m in job_manifests if m["metadata"]["name"] == name)
            )
            and job.phase() == "Failed"
        ]

        phases = []
        starts = []
        for manifest in job_manifests:
            name = manifest["metadata"]["name"]
            job = current[name]
            if failed_current:
                # fail-fast: stop sibling slices, don't relaunch missing ones
                if (
                    job is not None
                    and name not in failed_current
                    and job.phase() in ("Running", "Pending")
                ):
                    try:
                        shard.store.delete(Job.KIND, ns, name)
                    except NotFoundError:
                        pass
                    job = None
                phases.append("Failed" if name in failed_current else "Pending")
                continue
            applied = shard.apply_job(
                shard_template, manifest, FIELD_MANAGER, existing=job
            )
            phases.append(applied.phase())
            starts.append(applied.status.start_time)

        # prune slices a spec change no longer declares (e.g. slice_count
        # reduced 3 → 2): anything provenance-labeled for this template
        # whose name left the manifest set is deleted, Jobs and Services both
        self._prune_stale_workload(
            template, shard,
            {m["metadata"]["name"] for m in job_manifests}
            | {m["metadata"]["name"] for m in svc_manifests},
            listed={
                Job.KIND: list(jobs_by_name.values()),
                Service.KIND: list(svcs_by_name.values()),
            },
        )

        phase = aggregate_phase(phases)
        if phase == "Failed" and len(job_manifests) > 1:
            logger.warning(
                "workload for template %s on shard %s failed (slices: %s); "
                "sibling slices stopped",
                template.key(), shard.name, ",".join(failed_current),
            )
        # the instant the whole workload was up: the latest Job startTime,
        # known only when every slice has one (feeds the t2r gauge even if
        # the controller never observes the Running window itself)
        started_at = None
        if starts and all(starts):
            import datetime as _dt

            try:
                started_at = max(_dt.datetime.fromisoformat(s) for s in starts)
            except ValueError:
                started_at = None
        return phase, started_at

    def _prune_stale_workload(
        self,
        template: NexusAlgorithmTemplate,
        shard: Shard,
        keep: set,
        listed: Optional[dict] = None,
    ) -> None:
        """``listed`` (kind -> objects) reuses the caller's LIST snapshot;
        without it each kind is listed here (one extra round trip each)."""
        from nexus_tpu.api.workload import Job, Service
        from nexus_tpu.runtime.materializer import LABEL_TEMPLATE

        for kind in (Job.KIND, Service.KIND):
            objs = (
                listed[kind] if listed is not None
                else shard.store.list(kind, template.namespace)
            )
            for obj in objs:
                labels = obj.metadata.labels or {}
                if (
                    labels.get(LABEL_CONTROLLER_APP) == CONTROLLER_APP_NAME
                    and labels.get(LABEL_TEMPLATE) == template.name
                    and obj.metadata.name not in keep
                ):
                    logger.info(
                        "pruning stale workload %s %s from shard %s",
                        kind, obj.key(), shard.name,
                    )
                    try:
                        shard.store.delete(kind, obj.namespace, obj.metadata.name)
                    except NotFoundError:
                        pass

    def _remove_workload_from_shard(
        self, template: NexusAlgorithmTemplate, shard: Shard
    ) -> None:
        """Delete this template's materialized Jobs/Services from a shard
        (runtime block removed from the spec). Only provenance-labeled
        objects carrying our template label are touched."""
        from nexus_tpu.api.workload import Job, Service
        from nexus_tpu.runtime.materializer import LABEL_TEMPLATE

        selector = {
            LABEL_CONTROLLER_APP: CONTROLLER_APP_NAME,
            LABEL_TEMPLATE: template.name,
        }
        for kind in (Job.KIND, Service.KIND):
            for obj in shard.store.list(
                kind, template.namespace, label_selector=selector
            ):
                try:
                    shard.store.delete(
                        kind, obj.namespace, obj.metadata.name
                    )
                except NotFoundError:
                    pass

    def _observe_template_to_running(
        self,
        template: NexusAlgorithmTemplate,
        workload_phases: dict,
        workload_starts: Optional[dict] = None,
    ) -> None:
        """Emit the template-to-running latency gauges the first time a
        template's workload is known to have run everywhere (the BASELINE
        config #3 p50 metric; the reference's only latency metric is
        per-reconcile, controller.go:389).

        The Running window is edge-y — a fast job can transition
        Pending→Succeeded between reconciles — so a first-observed
        Succeeded also counts, using the Jobs' recorded startTime (the
        kubelet/launcher stamps it) rather than observation time."""
        from nexus_tpu.api.workload import aggregate_phase

        phase = aggregate_phase(list(workload_phases.values()))
        if phase not in ("Running", "Succeeded"):
            return
        uid = template.metadata.uid
        created = template.metadata.creation_timestamp
        if created is None:
            return
        # prefer the Jobs' own startTime; fall back to observation time for
        # a live Running observation (Succeeded without startTimes is
        # skipped — an observation-time sample would overstate by the whole
        # run duration)
        starts = [
            s for s in (workload_starts or {}).values() if s is not None
        ]
        started_at = (
            max(starts) if starts and len(starts) == len(workload_phases)
            else None
        )
        if started_at is None and phase != "Running":
            return
        with self._t2r_lock:
            if uid in self._t2r_emitted:
                return
            self._t2r_emitted.add(uid)
            end = started_at if started_at is not None else utcnow()
            sample = max((end - created).total_seconds(), 0.0)
            self._t2r_samples.append(sample)
            if len(self._t2r_samples) > 1000:
                self._t2r_samples = self._t2r_samples[-1000:]
            samples = sorted(self._t2r_samples)
            p50 = samples[len(samples) // 2]
        self.statsd.gauge(
            METRIC_TEMPLATE_TO_RUNNING, sample,
            tags=[f"template:{template.name}"],
        )
        self.statsd.gauge(METRIC_TEMPLATE_TO_RUNNING_P50, p50)

    def _remove_from_unselected_shards(
        self, template: NexusAlgorithmTemplate, placed_shards: List[Shard]
    ) -> None:
        """Delete this controller's copies of the template from shards that
        placement no longer selects (e.g. the template fanned out everywhere
        before its workgroup synced, then the workgroup narrowed placement).
        Only copies stamped with our provenance label are touched — foreign
        templates sharing the name are left alone. Shards the failure
        detector currently marks unhealthy are skipped: their API is (or
        may be) unreachable, and failing the whole reconcile over a cleanup
        write to a dead cluster would starve the healthy placement — the
        shard-recovered path re-enqueues every template, and this removal
        then converges."""
        placed_names = {s.name for s in placed_shards}
        with self._health_lock:
            health = dict(self.shard_health)
        unselected = [
            s for s in self.shards
            if s.name not in placed_names and health.get(s.name, True)
        ]

        def remove_stale(shard: Shard) -> None:
            try:
                stale = shard.template_lister.get(
                    template.namespace, template.name
                )
            except NotFoundError:
                return
            labels = stale.metadata.labels or {}
            if labels.get(LABEL_CONTROLLER_APP) != CONTROLLER_APP_NAME:
                return
            logger.info(
                "removing template %s from shard %s (no longer selected by "
                "placement)", template.key(), shard.name,
            )
            try:
                shard.delete_template(stale)
            except NotFoundError:
                pass
            shard.template_lister._delete(stale)
            self.write_skip_cache.invalidate_object(
                shard.name, NexusAlgorithmTemplate.KIND,
                template.namespace, template.name,
            )
            self.write_skip_cache.invalidate_owner(
                template.metadata.uid, shard.name
            )

        self._fan_out(unselected, remove_stale)

    def workgroup_sync_handler(self, namespace: str, name: str) -> None:
        """Workgroup reconcile: same shape, no dependents (reference:
        controller.go:697-756)."""
        try:
            workgroup = self.workgroup_lister.get(namespace, name)
        except NotFoundError:
            logger.info(
                "workgroup %s/%s no longer exists; dropping", namespace, name
            )
            # drop its converged entries, or deleted workgroups leak one
            # cache entry per shard forever in a long-running controller
            for shard in self.shards:
                self.write_skip_cache.invalidate_object(
                    shard.name, NexusAlgorithmWorkgroup.KIND, namespace, name
                )
            return

        workgroup = self._report_workgroup_init_condition(workgroup)

        spec_hash = stable_hash(workgroup.spec) if self._write_skip else ""

        def sync_one_shard(shard: Shard) -> None:
            shard_wg: Optional[NexusAlgorithmWorkgroup]
            try:
                shard_wg = shard.workgroup_lister.get(namespace, name)  # type: ignore[assignment]
            except NotFoundError:
                shard_wg = None

            if (
                shard_wg is not None
                and self._write_skip
                and self.write_skip_cache.check(
                    shard.name, NexusAlgorithmWorkgroup.KIND, namespace, name,
                    spec_hash, shard_wg.metadata.resource_version,
                )
            ):
                return

            if shard_wg is not None and not deep_equal(shard_wg.spec, workgroup.spec):
                logger.debug(
                    "spec drift for workgroup %s on shard %s, updating",
                    name,
                    shard.name,
                )
                shard_wg = shard.update_workgroup(
                    shard_wg, workgroup.spec, FIELD_MANAGER
                )
                shard.workgroup_lister._set_if_newer(shard_wg)
            elif shard_wg is None:
                shard_wg = shard.create_workgroup(
                    workgroup.name, workgroup.namespace, workgroup.spec, FIELD_MANAGER
                )
                shard.workgroup_lister._set_if_newer(shard_wg)

            if self._write_skip:
                self.write_skip_cache.store(
                    shard.name, NexusAlgorithmWorkgroup.KIND, namespace, name,
                    spec_hash, shard_wg.metadata.resource_version,
                )

        self._fan_out(self.shards, sync_one_shard)

        workgroup = self._report_workgroup_synced_condition(workgroup)
        self.recorder.event(
            workgroup,
            EVENT_TYPE_NORMAL,
            REASON_SYNCED,
            MSG_RESOURCE_SYNCED.format(NexusAlgorithmWorkgroup.KIND),
        )
