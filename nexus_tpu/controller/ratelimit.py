"""Requeue rate limiters.

Rebuilds the failure-handling profile of the reference controller
(SURVEY.md §5 "failure detection"): per-item exponential backoff combined with
a global token bucket via MaxOf (reference: controller.go:257-260; defaults
30ms→5s, 50/s burst 300, .helm/values.yaml:159-169).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Sequence


class RateLimiter:
    def when(self, item: Any) -> float:
        """Seconds to wait before this item may be retried."""
        raise NotImplementedError

    def forget(self, item: Any) -> None:
        raise NotImplementedError

    def num_requeues(self, item: Any) -> int:
        raise NotImplementedError


class ItemExponentialFailureRateLimiter(RateLimiter):
    """Per-item exponential backoff: ``base * 2^failures`` capped at ``max``."""

    def __init__(self, base_delay: float = 0.030, max_delay: float = 5.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: Dict[Any, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Any) -> float:
        with self._lock:
            exp = self._failures.get(item, 0)
            self._failures[item] = exp + 1
        delay = self.base_delay * (2.0 ** exp)
        return min(delay, self.max_delay)

    def forget(self, item: Any) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Any) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class BucketRateLimiter(RateLimiter):
    """Global token bucket with reservation semantics.

    ``when`` always admits the item but returns how long it must wait for its
    token — tokens may be borrowed from the future (matching
    golang.org/x/time/rate ``Reserve().Delay()``).
    """

    def __init__(self, rate: float = 50.0, burst: int = 300,
                 clock: Callable[[], float] = time.monotonic):
        if rate <= 0:
            raise ValueError("rate must be positive")
        self.rate = rate
        self.burst = burst
        # injectable clock (the detector's pattern): token accrual is pure
        # arithmetic over clock readings, so backoff behavior unit-tests
        # deterministically without sleeps
        self._clock = clock
        self._tokens = float(burst)
        self._last = self._clock()
        self._lock = threading.Lock()

    def when(self, item: Any) -> float:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                float(self.burst), self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            self._tokens -= 1.0
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self.rate

    def forget(self, item: Any) -> None:  # token buckets hold no per-item state
        pass

    def num_requeues(self, item: Any) -> int:
        return 0


class MaxOfRateLimiter(RateLimiter):
    """Worst-case combination of child limiters (reference:
    workqueue.NewTypedMaxOfRateLimiter, controller.go:257)."""

    def __init__(self, limiters: Sequence[RateLimiter]):
        self.limiters = list(limiters)

    def when(self, item: Any) -> float:
        return max(l.when(item) for l in self.limiters)

    def forget(self, item: Any) -> None:
        for l in self.limiters:
            l.forget(item)

    def num_requeues(self, item: Any) -> int:
        return max(l.num_requeues(item) for l in self.limiters)


def default_controller_rate_limiter(
    base_delay: float = 0.030,
    max_delay: float = 5.0,
    rate: float = 50.0,
    burst: int = 300,
) -> MaxOfRateLimiter:
    """The exact combination the reference constructs (controller.go:257-260)."""
    return MaxOfRateLimiter(
        [
            ItemExponentialFailureRateLimiter(base_delay, max_delay),
            BucketRateLimiter(rate, burst),
        ]
    )
