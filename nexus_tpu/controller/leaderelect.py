"""Lease-based leader election — the client-go leaderelection equivalent.

BEYOND the reference: it pins itself to one replica with a Recreate
strategy because it has no election ("NCC only supports single replica for
now", reference .helm/templates/deployment.yaml:15-19). This module lifts
that: N controller replicas race for a coordination.k8s.io/v1 Lease; only
the holder runs the reconcile loop, and a standby takes over within one
lease duration of the leader dying.

The algorithm is the standard one (client-go
tools/leaderelection/leaderelection.go semantics, re-implemented — not
translated — against this repo's ClusterStore surface):

  * try to CREATE the lease naming yourself holder (409 → someone holds);
  * the holder RENEWs every ``renew_period`` by updating ``renewTime``;
  * a non-holder watches ``renewTime``: once ``lease_duration`` passes
    with no renewal, it UPDATEs the lease to itself (leaseTransitions+1);
  * every write is optimistic-concurrency guarded — the store raises
    ConflictError on a stale resourceVersion, so two standbys racing for
    an expired lease cannot both win;
  * a holder that cannot renew within ``lease_duration`` (e.g. API server
    partition) must assume it lost the lease and stop leading — the
    fencing rule that prevents two concurrent reconcilers.

Clock note: expiry is judged from each observer's LOCAL observation time
of a renewTime CHANGE (the client-go approach) — wall-clock skew between
replicas does not matter because nobody compares their clock to the
timestamp in the lease, only to how long ago they last SAW it move.
"""

from __future__ import annotations

import datetime
import logging
import threading
import uuid
from typing import Callable, Optional

from nexus_tpu.api.types import Lease, ObjectMeta
from nexus_tpu.cluster.store import ConflictError, NotFoundError

logger = logging.getLogger("nexus_tpu.leaderelect")


def _now_str() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="microseconds"
    )


class LeaderElector:
    """Campaigns for a Lease; drives on_started/on_stopped callbacks.

    ``store``: any ClusterStore-compatible backend (in-memory or the real
    Kubernetes adapter — the Lease kind is served by both).
    """

    def __init__(
        self,
        store,
        lease_name: str,
        namespace: str,
        identity: str = "",
        lease_duration: float = 15.0,
        renew_period: float = 5.0,
        retry_period: float = 2.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        if renew_period >= lease_duration:
            raise ValueError(
                f"renewPeriod {renew_period} must be < leaseDuration "
                f"{lease_duration} (a healthy leader must renew well "
                "before expiry)"
            )
        self.store = store
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity or f"nexus-{uuid.uuid4().hex[:8]}"
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._leading = False
        self._leading_lock = threading.Lock()
        # local observation of the other holder's liveness: identity and
        # WHEN WE SAW its renewTime last change (monotonic clock)
        self._observed_renew: str = ""
        self._observed_at: float = 0.0

    # ---------------------------------------------------------------- state
    def is_leading(self) -> bool:
        with self._leading_lock:
            return self._leading

    def _set_leading(self, leading: bool) -> None:
        with self._leading_lock:
            was, self._leading = self._leading, leading
        if leading and not was:
            logger.info("became leader: %s (%s)", self.lease_name,
                        self.identity)
            if self.on_started_leading is not None:
                # OWN THREAD (client-go runs OnStartedLeading in its own
                # goroutine for the same reason): controller startup can
                # block longer than the lease duration (cache sync), and a
                # renewal stall there would hand the lease to a standby
                # while this replica eventually starts reconciling — the
                # split-brain the election exists to prevent
                threading.Thread(
                    target=self._run_callback,
                    args=(self.on_started_leading, "on_started_leading"),
                    daemon=True,
                    name=f"leader-started-{self.identity}",
                ).start()
        elif was and not leading:
            logger.warning("lost leadership: %s (%s)", self.lease_name,
                           self.identity)
            if self.on_stopped_leading is not None:
                # synchronous ON PURPOSE: stop() must not release the lease
                # until the deposed reconciler has actually stopped
                self._run_callback(
                    self.on_stopped_leading, "on_stopped_leading"
                )

    @staticmethod
    def _run_callback(cb, label: str) -> None:
        try:
            cb()
        except Exception:  # noqa: BLE001 — a dead callback must not kill
            # the campaign thread silently; the embedder's callback should
            # do its own fatal handling (main.py cancels the process)
            logger.exception("leader-election %s callback raised", label)

    # ------------------------------------------------------------- campaign
    def _try_acquire_or_renew(self) -> bool:
        """One campaign step; returns True iff we hold the lease now."""
        import time

        try:
            lease = self.store.get(Lease.KIND, self.namespace, self.lease_name)
        except NotFoundError:
            fresh = Lease(
                metadata=ObjectMeta(
                    name=self.lease_name, namespace=self.namespace
                ),
                holder_identity=self.identity,
                lease_duration_seconds=int(self.lease_duration),
                acquire_time=_now_str(),
                renew_time=_now_str(),
                lease_transitions=0,
            )
            try:
                self.store.create(fresh, field_manager=self.identity)
                return True
            except ConflictError:
                return False  # lost the create race; retry next tick

        if lease.holder_identity == self.identity:
            # we hold it: renew
            lease.renew_time = _now_str()
            try:
                self.store.update(lease, field_manager=self.identity)
                return True
            except (ConflictError, NotFoundError):
                # someone moved it under us → we no longer hold it
                return False

        if not lease.holder_identity:
            # released lease (graceful leader shutdown): claim immediately
            lease.holder_identity = self.identity
            lease.acquire_time = _now_str()
            lease.renew_time = _now_str()
            lease.lease_transitions += 1
            try:
                self.store.update(lease, field_manager=self.identity)
                return True
            except (ConflictError, NotFoundError):
                return False

        # someone else holds it: expired from OUR observation clock?
        if lease.renew_time != self._observed_renew:
            self._observed_renew = lease.renew_time
            self._observed_at = time.monotonic()
            return False  # saw a fresh renewal; holder is alive
        held_for = time.monotonic() - self._observed_at
        duration = float(
            lease.lease_duration_seconds or self.lease_duration
        )
        if self._observed_at == 0.0 or held_for < duration:
            return False  # not yet expired (or first observation)
        # expired: take over (optimistic concurrency arbitrates races)
        lease.holder_identity = self.identity
        lease.acquire_time = _now_str()
        lease.renew_time = _now_str()
        lease.lease_transitions += 1
        try:
            self.store.update(lease, field_manager=self.identity)
            logger.info(
                "took over expired lease %s (transitions=%d)",
                self.lease_name, lease.lease_transitions,
            )
            return True
        except (ConflictError, NotFoundError):
            return False  # another standby won; observe its renewals

    def _run(self) -> None:
        import time

        last_renewed = 0.0
        while not self._stop.is_set():
            got = False
            try:
                got = self._try_acquire_or_renew()
            except Exception:  # noqa: BLE001 — API unavailability != crash
                logger.exception("leader-election step failed; retrying")
            now = time.monotonic()
            if got:
                last_renewed = now
                self._set_leading(True)
            elif self.is_leading():
                # FENCE: we could not renew; tolerate transient failures
                # only until the lease would have expired for observers
                if now - last_renewed >= self.lease_duration:
                    self._set_leading(False)
            self._stop.wait(
                self.renew_period if got or self.is_leading()
                else self.retry_period
            )

    # ------------------------------------------------------------ lifecycle
    def run(self) -> "LeaderElector":
        """Start campaigning in a background thread."""
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"leader-elect-{self.lease_name}-{self.identity}",
        )
        self._thread.start()
        return self

    def stop(self, release: bool = True) -> None:
        """Stop campaigning; optionally release the lease (zero the holder
        so a standby takes over immediately instead of after expiry).

        Order matters: the reconciler is stopped (``on_stopped_leading``,
        synchronous) BEFORE the lease is released — releasing first would
        let a standby start reconciling while this replica's workers are
        still draining, the concurrent-writer race the election exists to
        prevent."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, self.retry_period * 2))
        was_leading = self.is_leading()
        self._set_leading(False)  # runs on_stopped_leading synchronously
        if release and was_leading:
            try:
                lease = self.store.get(
                    Lease.KIND, self.namespace, self.lease_name
                )
                if lease.holder_identity == self.identity:
                    lease.holder_identity = ""
                    lease.renew_time = ""
                    self.store.update(lease, field_manager=self.identity)
            except Exception:  # noqa: BLE001 — best-effort release
                logger.warning("could not release lease on stop",
                               exc_info=True)
