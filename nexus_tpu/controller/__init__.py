"""Reconciliation core: workqueue, rate limiting, events, controller.

Equivalent of the reference's L4 layer (``controller.go``) plus the client-go
workqueue machinery it builds on (SURVEY.md §1, §2a).
"""

from nexus_tpu.controller.ratelimit import (
    BucketRateLimiter,
    ItemExponentialFailureRateLimiter,
    MaxOfRateLimiter,
)
from nexus_tpu.controller.workqueue import RateLimitingQueue, WorkQueue
from nexus_tpu.controller.events import EventRecorder, FakeRecorder, Event
from nexus_tpu.controller.controller import (
    Controller,
    Element,
    TYPE_TEMPLATE,
    TYPE_WORKGROUP,
)

__all__ = [
    "BucketRateLimiter",
    "ItemExponentialFailureRateLimiter",
    "MaxOfRateLimiter",
    "RateLimitingQueue",
    "WorkQueue",
    "EventRecorder",
    "FakeRecorder",
    "Event",
    "Controller",
    "Element",
    "TYPE_TEMPLATE",
    "TYPE_WORKGROUP",
]
