"""Rate-limited work queue with client-go semantics.

The contract to preserve exactly (SURVEY.md §7 "hard parts (a)"; reference
comment controller.go:123-128):
  * **dedup**: adding a key already waiting is a no-op;
  * **per-key serialization**: a key being processed is never handed to a
    second worker; re-adds during processing are parked in the dirty set and
    re-queued when ``done`` is called;
  * ``add_after`` for delayed requeue, ``add_rate_limited`` consulting the
    rate limiter, ``forget`` on success resetting backoff;
  * ``shut_down`` drains blocked getters.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, List, Optional, Set, Tuple

from nexus_tpu.controller.ratelimit import RateLimiter


class WorkQueue:
    """FIFO queue with dirty/processing sets (client-go workqueue.Type)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._queue: List[Any] = []  # guarded-by: _cond
        self._dirty: Set[Any] = set()  # guarded-by: _cond
        self._processing: Set[Any] = set()  # guarded-by: _cond
        self._shutting_down = False  # guarded-by: _cond
        # burst coalescing bookkeeping: every add absorbed by the dirty-set
        # dedup is a duplicate key coalesced into the one already waiting
        self._coalesced_total = 0  # guarded-by: _cond
        # delayed adds
        self._delay_heap: List[Tuple[float, int, Any]] = []  # guarded-by: _cond
        self._delay_seq = 0  # guarded-by: _cond
        self._delay_thread: Optional[threading.Thread] = None  # guarded-by: _cond

    # -------------------------------------------------------------- core API
    def add(self, item: Any) -> None:
        with self._cond:
            if self._shutting_down:
                return
            if item in self._dirty:
                self._coalesced_total += 1
                return
            self._dirty.add(item)
            if item in self._processing:
                return
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Tuple[Any, bool]:
        """Block for the next item. Returns ``(item, shutdown)``; when
        ``shutdown`` is True the worker must exit."""
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue and not self._shutting_down:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None, False
                self._cond.wait(remaining)
            if not self._queue:
                return None, True
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item, False

    def done(self, item: Any) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    def depth(self) -> int:
        """Current waiting depth — the ``workqueue_depth`` gauge."""
        return len(self)

    def coalesced_total(self) -> int:
        """Duplicate keys absorbed by dedup since construction — the
        ``coalesced_total`` gauge. A burst of M events for N distinct keys
        coalesces into N reconciles and M-N counted duplicates."""
        with self._cond:
            return self._coalesced_total

    def shutting_down(self) -> bool:
        with self._cond:
            return self._shutting_down

    def shut_down(self) -> None:
        with self._cond:
            self._shutting_down = True
            self._cond.notify_all()

    # ---------------------------------------------------------- delayed adds
    def add_after(self, item: Any, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutting_down:
                return
            self._delay_seq += 1
            heapq.heappush(
                self._delay_heap, (time.monotonic() + delay, self._delay_seq, item)
            )
            # the delivery thread clears _delay_thread (under this lock)
            # before exiting, so this check cannot race its shutdown
            if self._delay_thread is None:
                self._delay_thread = threading.Thread(
                    target=self._delay_loop, daemon=True
                )
                self._delay_thread.start()
            else:
                self._cond.notify_all()

    def _delay_loop(self) -> None:
        while True:
            with self._cond:
                if self._shutting_down or not self._delay_heap:
                    self._delay_thread = None
                    return
                ready_at, _, item = self._delay_heap[0]
                now = time.monotonic()
                if ready_at <= now:
                    heapq.heappop(self._delay_heap)
                else:
                    self._cond.wait(min(ready_at - now, 0.05))
                    continue
            self.add(item)


class RateLimitingQueue(WorkQueue):
    """WorkQueue + rate limiter (client-go TypedRateLimitingInterface).

    The reconcile loop's failure protocol (reference controller.go:373-426):
    error → ``add_rate_limited`` (exponential per-item backoff bounded by the
    global bucket); success → ``forget`` + ``done``.
    """

    def __init__(self, rate_limiter: RateLimiter):
        super().__init__()
        self.rate_limiter = rate_limiter

    def add_rate_limited(self, item: Any) -> None:
        self.add_after(item, self.rate_limiter.when(item))

    def forget(self, item: Any) -> None:
        self.rate_limiter.forget(item)

    def num_requeues(self, item: Any) -> int:
        return self.rate_limiter.num_requeues(item)
