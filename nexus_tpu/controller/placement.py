"""Topology-aware shard placement (BASELINE config #5).

The reference syncs every template to every shard unconditionally
(controller.go:790 — ``for _, shard := range c.nexusShards``); its
``WorkgroupRef`` is carried on the spec but never consulted for placement.
This build keeps that behavior as the default (no workgroup resolvable → all
shards) and adds the TPU-native extension the north star asks for: a
template's ``workgroup_ref`` resolves to a ``NexusAlgorithmWorkgroup`` whose
``cluster`` / ``capabilities`` select the subset of shard clusters (TPU slice
pools) that should receive the template.

Matching rules, applied in order:
  1. workgroup is None (no ref, or referenced workgroup not found in the
     controller cluster) → all shards (reference parity).
  2. ``spec.cluster`` non-empty → only shards whose name equals it.
  3. ``spec.capabilities`` entries with value True → only shards advertising
     every required capability (``Shard.capabilities``).
  4. Constraints that match no connected shard are a placement error — the
     sync fails and requeues until a matching shard connects, rather than
     silently running the workload on the wrong pool.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

from nexus_tpu.api.template import NexusAlgorithmTemplate
from nexus_tpu.api.workgroup import NexusAlgorithmWorkgroup
from nexus_tpu.shards.shard import Shard


class PlacementError(RuntimeError):
    """Workgroup constraints matched zero connected shards."""


def required_capabilities(workgroup: NexusAlgorithmWorkgroup) -> List[str]:
    return sorted(k for k, v in workgroup.spec.capabilities.items() if v)


def select_shards(
    template: NexusAlgorithmTemplate,
    workgroup: Optional[NexusAlgorithmWorkgroup],
    shards: Sequence[Shard],
) -> List[Shard]:
    """Shards that should receive ``template`` given its resolved workgroup."""
    selected = list(shards)
    if workgroup is None:
        return selected

    cluster = workgroup.spec.cluster
    if cluster:
        selected = [s for s in selected if s.name == cluster]
        if not selected:
            raise PlacementError(
                f"workgroup {workgroup.name!r} pins cluster {cluster!r} "
                "but no connected shard has that name"
            )

    required = required_capabilities(workgroup)
    if required:
        selected = [
            s
            for s in selected
            if all(s.capabilities.get(c, False) for c in required)
        ]
        if not selected:
            scope = (
                f"pinned cluster {cluster!r}" if cluster else "connected shards"
            )
            raise PlacementError(
                f"workgroup {workgroup.name!r} requires capabilities "
                f"{required} but no shard among the {scope} advertises "
                "all of them"
            )
    return selected


def rendezvous_pick(key: str, shards: Sequence[Shard]) -> Shard:
    """Highest-random-weight (rendezvous) choice of one shard for ``key``.

    The churn-minimal single-home placement rule: every (template, shard)
    pair gets a stable pseudo-random weight, and the template lands on its
    max-weight shard. Removing a shard (failure) only moves the templates
    that were homed on it; every other assignment is unchanged — the
    placement-under-churn property the failover planner relies on so one
    shard outage doesn't reshuffle the whole fleet. Exactly
    ``rendezvous_rank(key, shards)[0]`` — single-home and N-home
    placement share ONE weight formula by construction.
    """
    return rendezvous_rank(key, shards)[0]


def rendezvous_rank(key: str, shards: Sequence[Shard]) -> List[Shard]:
    """All shards ordered by descending rendezvous weight for ``key`` —
    the multi-home generalization of :func:`rendezvous_pick` (rank[0]
    is exactly its answer). Taking the top N gives the churn-minimal
    N-replica placement: removing one shard promotes the former rank
    N+1 into the set and moves ONLY the replica that was homed on the
    removed shard; every other assignment is unchanged."""
    if not shards:
        raise PlacementError("rendezvous placement over zero shards")

    def weight(shard: Shard) -> bytes:
        return hashlib.blake2b(
            f"{key}\x00{shard.name}".encode(), digest_size=8
        ).digest()

    return sorted(shards, key=weight, reverse=True)


def select_replica_homes(
    template: NexusAlgorithmTemplate,
    workgroup: Optional[NexusAlgorithmWorkgroup],
    shards: Sequence[Shard],
    replicas: int,
    current: Optional[Sequence[str]] = None,
    avoid: Optional[str] = None,
) -> List[Shard]:
    """N-replica placement for a fleet serve workload (``ServeSpec
    .replicas``): constraint-filter via :func:`select_shards`, then pick
    ``replicas`` DISTINCT shards with the same three rules
    :func:`select_home` applies per replica:

      1. stickiness — shards in ``current`` that are still eligible (and
         not ``avoid``) keep their replicas, in their existing order: a
         healthy running engine is never migrated by a placement
         recomputation (its HBM pool + host tier hold the warm prefix
         cache the router's affinity hashing points traffic at);
      2. ``avoid`` — the shard a replica just died on is skipped when
         any alternative exists;
      3. remaining slots fill from the rendezvous rank over the
         survivors, so churn moves only the replicas that lost their
         home.

    Fewer eligible shards than ``replicas`` degrades to one replica per
    eligible shard (the sync model places at most one engine per shard)
    — the caller observes the shortfall through the returned length;
    zero eligible shards is a PlacementError like every placement."""
    if replicas < 1:
        raise PlacementError(f"replicas must be >= 1, got {replicas}")
    eligible = select_shards(template, workgroup, shards)
    if not eligible:
        raise PlacementError("replica placement over zero eligible shards")
    by_name = {s.name: s for s in eligible}
    homes: List[Shard] = []
    for name in current or ():
        # avoid beats stickiness (the select_home rule): a racing
        # reconcile must not write a replica back onto its corpse
        if name != avoid and name in by_name and len(homes) < replicas:
            if all(h.name != name for h in homes):
                homes.append(by_name[name])
    ranked = rendezvous_rank(
        template.metadata.uid or template.key(), eligible
    )
    pool = [s for s in ranked if s.name != avoid] or ranked
    for s in pool:
        if len(homes) >= replicas:
            break
        if all(h.name != s.name for h in homes):
            homes.append(s)
    return homes


def select_home(
    template: NexusAlgorithmTemplate,
    workgroup: Optional[NexusAlgorithmWorkgroup],
    shards: Sequence[Shard],
    current: Optional[str] = None,
    avoid: Optional[str] = None,
) -> Shard:
    """Single-home placement (workgroup ``scheduling: any``).

    Constraint-filter via :func:`select_shards`, then:
      1. stickiness — keep ``current`` while it is still eligible (a healthy
         running workload is never migrated by a placement recomputation);
      2. ``avoid`` — the shard the workload just failed on is skipped when
         any alternative exists (failover must not hand the job back);
      3. rendezvous hash over the survivors.
    """
    eligible = select_shards(template, workgroup, shards)
    # avoid beats stickiness: if the current assignment IS the shard the
    # workload just died on (a reconcile raced the eviction and wrote it
    # back), honoring it would hand the job straight back to the corpse
    if current is not None and current != avoid:
        for s in eligible:
            if s.name == current:
                return s
    pool = [s for s in eligible if s.name != avoid] or eligible
    return rendezvous_pick(template.metadata.uid or template.key(), pool)
