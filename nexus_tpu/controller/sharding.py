"""Parallel shard fan-out primitives for the reconcile hot path.

The reference controller converges every template onto its shards strictly
sequentially (controller.go:790-831 — one ``for _, shard := range shards``
per stage). That is fine for two kind clusters; under burst load against
many shards the per-shard round trips serialize and template-to-running
latency degrades linearly with shard count (BENCH_r05: burst p50 37x the
steady-state p50). Placement-at-scale systems treat per-target fan-out
parallelism as table stakes; this module provides the two pieces the
controller uses to get there without changing reference semantics:

  * :class:`ShardSyncExecutor` — a bounded ``concurrent.futures`` pool that
    runs one closure per shard, preserving fail-fast → requeue semantics:
    the first shard error cooperatively cancels not-yet-started siblings,
    every error is aggregated into one exception, and results come back in
    input-shard order so status bookkeeping stays deterministic.
  * :class:`WriteSkipCache` — a content-hash cache keyed
    ``(shard, kind, namespace, name, owner_uid)`` that lets a reconcile
    skip the per-shard deep-compare/write entirely when both the source
    content hash and the shard-side ``resourceVersion`` are unchanged since
    the last converged sync. Any shard-side write (drift, rogue adoption,
    out-of-band edit) bumps the resourceVersion and therefore invalidates
    the entry automatically; deletes invalidate explicitly.
"""

from __future__ import annotations

import hashlib
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import fields, is_dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


class ShardFanOutError(RuntimeError):
    """One or more per-shard tasks failed during a fan-out.

    ``errors`` holds ``(shard_name, exception)`` pairs in input-shard order;
    the first entry is the error the sequential path would have raised.
    """

    def __init__(self, errors: List[Tuple[str, BaseException]]):
        self.errors = errors
        super().__init__(
            "; ".join(f"shard {name}: {err}" for name, err in errors)
        )

    @property
    def first(self) -> BaseException:
        return self.errors[0][1]


_SKIPPED = object()  # sentinel: task cancelled by a sibling's failure


class ShardSyncExecutor:
    """Bounded per-controller executor for per-shard reconcile work.

    ``max_workers <= 1`` (or a single-shard fan-out) degrades to the exact
    sequential reference behavior: shards processed in order, the first
    error raised immediately with later shards untouched. With more
    workers, per-shard closures run concurrently; the first error sets a
    cooperative cancel flag so queued-but-unstarted siblings skip their
    work (fail-fast), and all observed errors are aggregated into one
    :class:`ShardFanOutError`.

    The pool is shared by all reconcile workers of one controller — the
    bound caps total concurrent shard I/O, not per-reconcile concurrency.
    """

    def __init__(self, max_workers: int = 0, name: str = "nexus-shard-sync"):
        self.max_workers = int(max_workers)
        self._name = name
        self._pool: Optional[ThreadPoolExecutor] = None
        self._lock = threading.Lock()

    def _ensure_pool(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.max_workers, thread_name_prefix=self._name
                )
            return self._pool

    def map_shards(
        self,
        shards: Sequence[Any],
        fn: Callable[[Any], Any],
        fail_fast: bool = True,
    ) -> List[Any]:
        """Run ``fn(shard)`` for every shard; return results in shard order.

        Raises :class:`ShardFanOutError` when any task failed (after every
        started task finished — no silently abandoned in-flight writes).
        When ``fail_fast`` is False every shard is attempted even after a
        failure (the delete fan-out wants maximal coverage)."""
        shards = list(shards)
        if self.max_workers <= 1 or len(shards) <= 1:
            results: List[Any] = []
            errors: List[Tuple[str, BaseException]] = []
            for shard in shards:
                try:
                    results.append(fn(shard))
                except Exception as e:  # noqa: BLE001 — aggregated below
                    errors.append((getattr(shard, "name", "?"), e))
                    if fail_fast:
                        break
                    results.append(_SKIPPED)
            if errors:
                raise ShardFanOutError(errors) from errors[0][1]
            return results

        pool = self._ensure_pool()
        failed = threading.Event()

        def run_one(shard: Any) -> Any:
            if fail_fast and failed.is_set():
                return _SKIPPED  # sibling already failed: don't start
            try:
                return fn(shard)
            except BaseException:
                failed.set()
                raise

        futures: List[Tuple[Any, Future]] = [
            (shard, pool.submit(run_one, shard)) for shard in shards
        ]
        results = []
        errors = []
        for shard, fut in futures:
            try:
                results.append(fut.result())
            except Exception as e:  # noqa: BLE001 — aggregated below
                errors.append((getattr(shard, "name", "?"), e))
                results.append(_SKIPPED)
        if errors:
            raise ShardFanOutError(errors) from errors[0][1]
        return results

    @staticmethod
    def skipped(result: Any) -> bool:
        return result is _SKIPPED

    def shutdown(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)


# --------------------------------------------------------------------- hashing

def stable_hash(value: Any) -> str:
    """Deterministic content hash of specs/data, consistent with
    ``api.types.deep_equal``: two values that are deep-equal hash
    identically, and dataclass type identity participates (so a Secret's
    data and a ConfigMap's identical dict still collide only within one
    cache key, which carries the kind)."""
    h = hashlib.blake2b(digest_size=16)
    _feed(h, value)
    return h.hexdigest()


def _feed(h, value: Any) -> None:
    if is_dataclass(value) and not isinstance(value, type):
        h.update(b"@")
        h.update(type(value).__name__.encode())
        for f in fields(value):
            h.update(f.name.encode())
            _feed(h, getattr(value, f.name))
    elif isinstance(value, dict):
        h.update(b"{")
        for k in sorted(value, key=repr):
            h.update(repr(k).encode())
            h.update(b":")
            _feed(h, value[k])
        h.update(b"}")
    elif isinstance(value, (list, tuple)):
        h.update(b"[")
        for item in value:
            _feed(h, item)
        h.update(b"]")
    else:
        h.update(repr(value).encode())
        h.update(b";")


# ----------------------------------------------------------------- skip cache

class WriteSkipCache:
    """Content-hash write-skip cache for shard syncs.

    An entry ``(shard, kind, ns, name, owner_uid) -> (content_hash, shard_rv)``
    asserts: *the shard object at resourceVersion ``shard_rv`` was verified
    converged (content + ownership) for source content ``content_hash`` on
    behalf of the owning template ``owner_uid``*. A hit therefore allows
    skipping the deep-compare, the ownership walk, and the write.

    Invalidation:
      * source content change → hash mismatch → miss;
      * any shard-side write (drift repair by us, rogue adoption by another
        controller, manual edit) → resourceVersion mismatch → miss;
      * shard-side delete → :meth:`invalidate_object` /
        :meth:`invalidate_owner` (called by the controller's delete paths).

    ``owner_uid`` is part of the key so two templates sharing one secret
    each verify (and cache) their own ownership — a hit for template A must
    not let template B skip appending its owner reference.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str, str, str, str], Tuple[str, str]] = {}
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    @staticmethod
    def _key(shard: str, kind: str, namespace: str, name: str,
             owner_uid: str = "") -> Tuple[str, str, str, str, str]:
        return (shard, kind, namespace, name, owner_uid)

    def check(self, shard: str, kind: str, namespace: str, name: str,
              content_hash: str, shard_rv: str, owner_uid: str = "") -> bool:
        key = self._key(shard, kind, namespace, name, owner_uid)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry == (content_hash, shard_rv):
                self.hits += 1
                return True
            self.misses += 1
            return False

    def store(self, shard: str, kind: str, namespace: str, name: str,
              content_hash: str, shard_rv: str, owner_uid: str = "") -> None:
        key = self._key(shard, kind, namespace, name, owner_uid)
        with self._lock:
            self._entries[key] = (content_hash, shard_rv)

    def invalidate_object(self, shard: str, kind: str, namespace: str,
                          name: str) -> None:
        """Drop every owner's entry for one shard object (delete/rogue)."""
        with self._lock:
            stale = [
                k for k in self._entries
                if k[0] == shard and k[1] == kind and k[2] == namespace
                and k[3] == name
            ]
            for k in stale:
                del self._entries[k]
            self.invalidations += len(stale)

    def invalidate_shard(self, shard: str) -> None:
        """Drop EVERY entry for one shard — the unhealthy→healthy transition
        hook: a shard that reconnects after an outage may have been
        restored/rebuilt and lost writes this cache still believes are
        converged, so every skip decision for it is suspect until re-verified
        by a full compare (the next reconcile repopulates the entries)."""
        with self._lock:
            stale = [k for k in self._entries if k[0] == shard]
            for k in stale:
                del self._entries[k]
            self.invalidations += len(stale)

    def invalidate_owner(self, owner_uid: str,
                         shard: Optional[str] = None) -> None:
        """Drop every entry verified on behalf of one template (template
        deleted / removed from a shard)."""
        with self._lock:
            stale = [
                k for k in self._entries
                if k[4] == owner_uid and (shard is None or k[0] == shard)
            ]
            for k in stale:
                del self._entries[k]
            self.invalidations += len(stale)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "entries": len(self._entries),
            }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
