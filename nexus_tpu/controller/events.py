"""Event recording — the user-facing surface for sync outcomes.

Equivalent of the reference's event broadcaster → Kubernetes Events wiring
(controller.go:252-256) and the test-side ``record.FakeRecorder``
(controller_test.go:540-544). Event reasons/messages match the reference
constants (controller.go:60-81).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

logger = logging.getLogger("nexus_tpu.events")

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"

# Reasons (reference: controller.go:60-70)
REASON_SYNCED = "Synced"
REASON_ERR_RESOURCE_EXISTS = "ErrResourceExists"
REASON_ERR_RESOURCE_MISSING = "ErrResourceMissing"
REASON_ERR_RESOURCE_SYNC = "ErrResourceSyncError"
# Placement could not match any (healthy) shard — surfaced as a template
# status condition + Event instead of a silent requeue loop.
REASON_ERR_PLACEMENT = "ErrPlacement"

# Message formats (reference: controller.go:72-84)
MSG_RESOURCE_EXISTS = (
    'Resource "{0}" already exists and is not managed by any Machine Learning Algorithm'
)
MSG_RESOURCE_SYNCED = "Resource of type {0} synced successfully"
MSG_RESOURCE_MISSING = (
    'Resource "{0}" referenced by NexusAlgorithmTemplate "{1}" is missing in the '
    "controller cluster"
)
MSG_RESOURCE_OPERATION_FAILED = (
    'Synchronization/update of a resource "{0}" referenced by NexusAlgorithmTemplate '
    '"{1}" failed with a fatal error {2}'
)

# FieldManager distinguishes this controller from other writers
# (reference: controller.go:83).
FIELD_MANAGER = "nexus-configuration-controller"


@dataclass
class Event:
    type: str
    reason: str
    message: str
    object_kind: str = ""
    object_name: str = ""
    object_namespace: str = ""
    component: str = ""


class EventRecorder:
    """Records events against objects; logs them and keeps a bounded list.

    ``sink(obj, event)`` — optional callable posting the event to an
    external system (the Kubernetes Events API on real clusters, mirroring
    the reference's broadcaster wiring, controller.go:252-256). Sink errors
    are swallowed: event delivery must never fail a reconcile."""

    def __init__(
        self,
        component: str = "nexus-configuration-controller",
        sink: Optional[Callable[[Any, Event], None]] = None,
    ):
        self.component = component
        self.sink = sink
        self._lock = threading.Lock()
        self.events: List[Event] = []

    def event(self, obj: Any, event_type: str, reason: str, message: str) -> None:
        meta = getattr(obj, "metadata", None)
        ev = Event(
            type=event_type,
            reason=reason,
            message=message,
            object_kind=getattr(obj, "KIND", ""),
            object_name=getattr(meta, "name", "") if meta else "",
            object_namespace=getattr(meta, "namespace", "") if meta else "",
            component=self.component,
        )
        with self._lock:
            self.events.append(ev)
            if len(self.events) > 1000:
                self.events = self.events[-1000:]
        log = logger.info if event_type == EVENT_TYPE_NORMAL else logger.warning
        log(
            "event component=%s kind=%s object=%s/%s reason=%s: %s",
            self.component,
            ev.object_kind,
            ev.object_namespace,
            ev.object_name,
            reason,
            message,
        )
        if self.sink is not None:
            try:
                self.sink(obj, ev)
            except Exception:
                logger.exception("event sink failed (event already recorded)")


class FakeRecorder(EventRecorder):
    """Test recorder exposing events as formatted strings, mirroring the
    reference's ``record.FakeRecorder`` channel contents."""

    def formatted(self) -> List[str]:
        with self._lock:
            return [f"{e.type} {e.reason} {e.message}" for e in self.events]
