"""jax_xla workload runtime: template → TPU Job materialization → execution.

This is the plane that makes synced templates *run* (BASELINE north star):
the materializer turns a template's runtime block into a Kubernetes Job
manifest with ``google.com/tpu`` resources and ``gke-tpu-*`` nodeSelectors;
the launcher watches a shard for runnable templates and executes them (in
process for local shards, via the cluster API for real ones); entrypoints
build the mesh/model/trainer from the spec.
"""

from nexus_tpu.runtime.materializer import materialize_job
from nexus_tpu.runtime.entrypoints import run_template_runtime
from nexus_tpu.runtime.launcher import LocalLauncher

__all__ = ["materialize_job", "run_template_runtime", "LocalLauncher"]
