"""jax_xla workload runtime: template → TPU Job materialization → execution.

This is the plane that makes synced templates *run* (BASELINE north star):
the materializer turns a template's runtime block into a Kubernetes Job
manifest with ``google.com/tpu`` resources and ``gke-tpu-*`` nodeSelectors;
the launcher watches a shard for runnable templates and executes them (in
process for local shards, via the cluster API for real ones); entrypoints
build the mesh/model/trainer from the spec.

Submodules load lazily (PEP 562): the controller's reconcile path touches
only the materializer, and importing ``entrypoints`` eagerly here dragged
the whole JAX/orbax stack (~30 s cold on this image — orbax's
google-cloud-logging dependency scans every installed distribution) into
the first template sync, which is exactly the template-to-running p50 the
control-plane bench measures.
"""

from typing import TYPE_CHECKING

__all__ = ["materialize_job", "run_template_runtime", "LocalLauncher"]

if TYPE_CHECKING:  # pragma: no cover — static-analysis imports only
    from nexus_tpu.runtime.entrypoints import run_template_runtime
    from nexus_tpu.runtime.launcher import LocalLauncher
    from nexus_tpu.runtime.materializer import materialize_job

_EXPORTS = {
    "materialize_job": ("nexus_tpu.runtime.materializer", "materialize_job"),
    "run_template_runtime": (
        "nexus_tpu.runtime.entrypoints", "run_template_runtime",
    ),
    "LocalLauncher": ("nexus_tpu.runtime.launcher", "LocalLauncher"),
}


def __getattr__(name: str):
    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(module_name), attr)
