"""Pretrained-weight ingestion: HF safetensors checkpoints → param trees.

Makes BASELINE config #3 ("Llama-3-8B JAX inference") literal: a template
can point ``model.weights`` at a HuggingFace-format checkpoint — Llama,
GPT-NeoX, or Mixtral — (single ``model.safetensors``, a sharded set with
``model.safetensors.index.json``, or a directory of ``*.safetensors``) and
``_run_infer`` decodes with those weights instead of random init.

The reference has no model weights at all (SURVEY.md: it syncs config
objects, never tensors); this subsystem exists for the TPU workload plane
the north star adds. TPU-first design points:
  * the safetensors container is parsed with the stdlib (8-byte little-
    endian header length + JSON header + raw buffer) and tensors are read
    through ``np.memmap`` slices — no full-file load, so an 8B checkpoint
    streams layer-by-layer instead of doubling host RAM;
  * bf16 tensors decode via ``ml_dtypes.bfloat16`` (numpy itself has no
    bf16) and stay bf16 end-to-end — the MXU-native dtype;
  * each converted leaf is ``jax.device_put`` straight onto its target
    NamedSharding when one is given, so no host ever materializes more
    than one stacked tensor beyond the current one and the device-side
    layout matches the model's FSDP/TP logical axes from the start.

HF→nexus mapping notes: our RoPE is the rotate-half convention
(ops/rope.py), the same convention HF Llama checkpoints are stored in, so
q/k projections transfer without the head-permutation some ports need.
HF stores projections as (out, in); our params are (in, out) — transposed
on ingest. Per-layer tensors stack along a leading layer dim (the
lax.scan layout, models/llama.py).
"""

from __future__ import annotations

import json
import logging
import os
import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("nexus_tpu.runtime.weights")

_DTYPES: Dict[str, Any] = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
}


def _bf16():
    import ml_dtypes

    return ml_dtypes.bfloat16


def _np_dtype(st_dtype: str):
    if st_dtype == "BF16":
        return _bf16()
    try:
        return _DTYPES[st_dtype]
    except KeyError:
        raise ValueError(f"unsupported safetensors dtype {st_dtype!r}")


class SafetensorsFile:
    """Zero-copy reader for one ``.safetensors`` file (stdlib parsing,
    np.memmap-backed tensor views)."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            (header_len,) = struct.unpack("<Q", f.read(8))
            header = json.loads(f.read(header_len))
        self._data_start = 8 + header_len
        header.pop("__metadata__", None)
        self.tensors: Dict[str, Dict[str, Any]] = header
        self._mmap: Optional[np.memmap] = None

    def keys(self) -> List[str]:
        return list(self.tensors)

    def _buffer(self) -> np.memmap:
        if self._mmap is None:
            self._mmap = np.memmap(self.path, dtype=np.uint8, mode="r")
        return self._mmap

    def tensor(self, name: str) -> np.ndarray:
        """A read-only view onto the mapped file (copy before mutating)."""
        info = self.tensors[name]
        start, end = info["data_offsets"]
        dt = _np_dtype(info["dtype"])
        raw = self._buffer()[self._data_start + start:self._data_start + end]
        return raw.view(dt).reshape(info["shape"])

    def close(self) -> None:
        self._mmap = None


class CheckpointReader:
    """Uniform tensor access over the three HF checkpoint layouts:
    one file, an index.json shard map, or a directory of shards."""

    def __init__(self, path: str):
        self.files: Dict[str, SafetensorsFile] = {}
        self.name_to_file: Dict[str, str] = {}
        if os.path.isfile(path) and path.endswith(".safetensors"):
            self._add_file(path)
            return
        if os.path.isdir(path):
            index = os.path.join(path, "model.safetensors.index.json")
            single = os.path.join(path, "model.safetensors")
            if os.path.isfile(index):
                with open(index) as f:
                    weight_map = json.load(f).get("weight_map") or {}
                for name, fname in weight_map.items():
                    fpath = os.path.join(path, fname)
                    if fpath not in self.files:
                        self.files[fpath] = SafetensorsFile(fpath)
                    self.name_to_file[name] = fpath
                return
            if os.path.isfile(single):
                self._add_file(single)
                return
            shards = sorted(
                os.path.join(path, p)
                for p in os.listdir(path)
                if p.endswith(".safetensors")
            )
            if shards:
                for s in shards:
                    self._add_file(s)
                return
        raise FileNotFoundError(
            f"{path!r} is not a .safetensors file, a directory containing "
            "model.safetensors(.index.json), or a directory of shards"
        )

    def _add_file(self, fpath: str) -> None:
        sf = SafetensorsFile(fpath)
        self.files[fpath] = sf
        for name in sf.keys():
            self.name_to_file[name] = fpath

    def __contains__(self, name: str) -> bool:
        return name in self.name_to_file

    def keys(self) -> List[str]:
        return list(self.name_to_file)

    def tensor(self, name: str) -> np.ndarray:
        try:
            fpath = self.name_to_file[name]
        except KeyError:
            raise KeyError(
                f"tensor {name!r} not in checkpoint "
                f"(have {len(self.name_to_file)} tensors)"
            )
        return self.files[fpath].tensor(name)

    def close(self) -> None:
        for sf in self.files.values():
            sf.close()


# --------------------------------------------------------------- conversion


def _put(x: np.ndarray, dtype, sharding=None):
    """Cast + (optionally) place a host array onto its target sharding."""
    import jax

    arr = np.asarray(x, dtype=dtype)
    if sharding is not None:
        return jax.device_put(arr, sharding)
    return jax.numpy.asarray(arr)


def _fetch(reader: CheckpointReader, name: str, shape: Tuple[int, ...],
           transpose: bool = False) -> np.ndarray:
    """One tensor, shape-checked against the target config."""
    t = reader.tensor(name)
    if transpose:
        t = t.T
    if tuple(t.shape) != shape:
        raise ValueError(
            f"{name}: shape {tuple(t.shape)} != expected {shape}"
        )
    return t


def _stack_layers(
    reader: CheckpointReader,
    n_layers: int,
    template: str,
    transpose: bool,
    dtype,
    out_shape: Tuple[int, ...],
    sharding=None,
):
    """Stack ``template.format(i)`` for all layers into one leading-dim
    array, verifying the per-layer shape."""
    per_shape = out_shape[1:]
    out = np.empty(out_shape, dtype=dtype)
    for i in range(n_layers):
        t = reader.tensor(template.format(i))
        if transpose:
            t = t.T
        if tuple(t.shape) != per_shape:
            raise ValueError(
                f"{template.format(i)}: shape {tuple(t.shape)} != expected "
                f"{per_shape} (config/checkpoint mismatch)"
            )
        out[i] = np.asarray(t, dtype=dtype)
    return _put(out, dtype, sharding)


# name templates in HF Llama checkpoints (transformers LlamaForCausalLM)
_HF_LLAMA_LAYERS: Dict[str, Tuple[str, bool]] = {
    # ours -> (HF template, transpose?)
    "wq": ("model.layers.{}.self_attn.q_proj.weight", True),
    "wk": ("model.layers.{}.self_attn.k_proj.weight", True),
    "wv": ("model.layers.{}.self_attn.v_proj.weight", True),
    "wo": ("model.layers.{}.self_attn.o_proj.weight", True),
    "w_gate": ("model.layers.{}.mlp.gate_proj.weight", True),
    "w_up": ("model.layers.{}.mlp.up_proj.weight", True),
    "w_down": ("model.layers.{}.mlp.down_proj.weight", True),
    "ln_attn": ("model.layers.{}.input_layernorm.weight", False),
    "ln_mlp": ("model.layers.{}.post_attention_layernorm.weight", False),
}


def convert_hf_llama(
    path: str,
    cfg,
    shardings: Optional[Dict[str, Any]] = None,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict[str, Any]:
    """HF-format Llama safetensors checkpoint → our param tree
    (models/llama.py layout: stacked layers, (in, out) projections).

    ``shardings``: optional tree matching the param tree (NamedShardings —
    e.g. from ``sharding_tree(llama.logical_axes(cfg), mesh)``); each leaf
    is placed as it is built. Tied-embedding checkpoints (no
    ``lm_head.weight``, e.g. Llama-3.2-1B) reuse the embedding transposed.
    Raises ValueError on any shape/layer-count mismatch with ``cfg``."""
    reader = CheckpointReader(path)
    note = progress or (lambda msg: logger.info("%s", msg))
    try:
        d, f, v, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers
        hq = cfg.n_heads * cfg.head_dim
        hkv = cfg.n_kv_heads * cfg.head_dim
        dt = cfg.dtype

        expected_last = f"model.layers.{L - 1}.input_layernorm.weight"
        if expected_last not in reader:
            extra = [
                n for n in reader.keys()
                if n.startswith(f"model.layers.{L}.")
            ]
            raise ValueError(
                f"checkpoint does not match n_layers={L}: "
                + (
                    f"has layers past {L - 1}"
                    if extra
                    else f"missing {expected_last!r}"
                )
            )

        sh = shardings or {}
        layer_sh = sh.get("layers") or {}
        shapes = {
            "wq": (L, d, hq),
            "wk": (L, d, hkv),
            "wv": (L, d, hkv),
            "wo": (L, hq, d),
            "w_gate": (L, d, f),
            "w_up": (L, d, f),
            "w_down": (L, f, d),
            "ln_attn": (L, d),
            "ln_mlp": (L, d),
        }
        layers: Dict[str, Any] = {}
        for ours, (tmpl, transpose) in _HF_LLAMA_LAYERS.items():
            note(f"converting {ours} ({L} layers)")
            layers[ours] = _stack_layers(
                reader, L, tmpl, transpose, dt, shapes[ours],
                sharding=layer_sh.get(ours),
            )

        note("converting embed / final_norm / lm_head")
        embed = _fetch(reader, "model.embed_tokens.weight", (v, d))
        if "lm_head.weight" in reader:
            lm_head = _fetch(reader, "lm_head.weight", (d, v), transpose=True)
        else:
            # tied word embeddings (Llama-3.2 style)
            lm_head = embed.T
        params = {
            "embed": _put(embed, dt, sh.get("embed")),
            "layers": layers,
            "final_norm": _put(
                _fetch(reader, "model.norm.weight", (d,)), dt, sh.get("final_norm")
            ),
            "lm_head": _put(lm_head, dt, sh.get("lm_head")),
        }
        return params
    finally:
        reader.close()


def export_hf_llama(params: Dict[str, Any], cfg, path: str) -> str:
    """Our param tree → an HF-format single-file safetensors checkpoint
    (the inverse mapping of :func:`convert_hf_llama`). Test/interop tool:
    round-tripping through this is how conversion parity is proven without
    network access to real checkpoints."""
    from safetensors.numpy import save_file

    out: Dict[str, np.ndarray] = {}

    def host(x) -> np.ndarray:
        return np.asarray(x)

    out["model.embed_tokens.weight"] = host(params["embed"])
    out["model.norm.weight"] = host(params["final_norm"])
    out["lm_head.weight"] = host(params["lm_head"]).T.copy()
    for ours, (tmpl, transpose) in _HF_LLAMA_LAYERS.items():
        stacked = host(params["layers"][ours])
        for i in range(cfg.n_layers):
            t = stacked[i]
            out[tmpl.format(i)] = (t.T if transpose else t).copy()
    save_file(out, path)
    return path


# ------------------------------------------------------------ gptneox


def _deinterleave_neox_qkv(w: np.ndarray, n_heads: int, head_dim: int):
    """HF NeoX fuses query_key_value with PER-HEAD interleaving on the
    output dim (head-major: [h0:q k v, h1:q k v, ...]); our wqkv splits
    into contiguous thirds (all-q | all-k | all-v). (3d, ...) → (3d, ...)
    reordered."""
    rest = w.shape[1:]
    w = w.reshape(n_heads, 3, head_dim, *rest)
    w = np.moveaxis(w, 1, 0)  # (3, H, hd, ...)
    return w.reshape(3 * n_heads * head_dim, *rest)


def _interleave_neox_qkv(w: np.ndarray, n_heads: int, head_dim: int):
    """Inverse of :func:`_deinterleave_neox_qkv` (export path)."""
    rest = w.shape[1:]
    w = w.reshape(3, n_heads, head_dim, *rest)
    w = np.moveaxis(w, 0, 1)  # (H, 3, hd, ...)
    return w.reshape(3 * n_heads * head_dim, *rest)


_HF_NEOX_PLAIN: Dict[str, Tuple[str, bool]] = {
    # ours -> (HF template, transpose?) — everything except the fused qkv
    "wo": ("gpt_neox.layers.{}.attention.dense.weight", True),
    "b_o": ("gpt_neox.layers.{}.attention.dense.bias", False),
    "w_in": ("gpt_neox.layers.{}.mlp.dense_h_to_4h.weight", True),
    "b_in": ("gpt_neox.layers.{}.mlp.dense_h_to_4h.bias", False),
    "w_out": ("gpt_neox.layers.{}.mlp.dense_4h_to_h.weight", True),
    "b_out": ("gpt_neox.layers.{}.mlp.dense_4h_to_h.bias", False),
    "ln1": ("gpt_neox.layers.{}.input_layernorm.weight", False),
    "ln1_b": ("gpt_neox.layers.{}.input_layernorm.bias", False),
    "ln2": ("gpt_neox.layers.{}.post_attention_layernorm.weight", False),
    "ln2_b": ("gpt_neox.layers.{}.post_attention_layernorm.bias", False),
}


def convert_hf_gptneox(
    path: str,
    cfg,
    shardings: Optional[Dict[str, Any]] = None,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict[str, Any]:
    """HF GPTNeoXForCausalLM safetensors checkpoint → our param tree.

    Handles the fused ``query_key_value`` head-interleaved layout (see
    :func:`_deinterleave_neox_qkv`) and the untied ``embed_out`` head."""
    reader = CheckpointReader(path)
    note = progress or (lambda msg: logger.info("%s", msg))
    try:
        d, f, v, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers
        hq, hd = cfg.n_heads, cfg.head_dim
        dt = cfg.dtype
        last = f"gpt_neox.layers.{L - 1}.input_layernorm.weight"
        if last not in reader:
            raise ValueError(
                f"checkpoint does not match n_layers={L}: missing {last!r}"
            )
        sh = shardings or {}
        layer_sh = sh.get("layers") or {}

        shapes = {
            "wo": (L, d, d), "b_o": (L, d),
            "w_in": (L, d, f), "b_in": (L, f),
            "w_out": (L, f, d), "b_out": (L, d),
            "ln1": (L, d), "ln1_b": (L, d),
            "ln2": (L, d), "ln2_b": (L, d),
        }
        layers: Dict[str, Any] = {}
        for ours, (tmpl, transpose) in _HF_NEOX_PLAIN.items():
            note(f"converting {ours} ({L} layers)")
            layers[ours] = _stack_layers(
                reader, L, tmpl, transpose, dt, shapes[ours],
                sharding=layer_sh.get(ours),
            )
        note("converting fused qkv")
        wqkv = np.empty((L, d, 3 * d), dtype=dt)
        b_qkv = np.empty((L, 3 * d), dtype=dt)
        for i in range(L):
            w = np.asarray(
                reader.tensor(
                    f"gpt_neox.layers.{i}.attention.query_key_value.weight"
                )
            )
            b = np.asarray(
                reader.tensor(
                    f"gpt_neox.layers.{i}.attention.query_key_value.bias"
                )
            )
            if w.shape != (3 * d, d):
                raise ValueError(
                    f"query_key_value.weight shape {w.shape} != {(3 * d, d)}"
                )
            wqkv[i] = _deinterleave_neox_qkv(w, hq, hd).T.astype(dt)
            b_qkv[i] = _deinterleave_neox_qkv(b, hq, hd).astype(dt)
        layers["wqkv"] = _put(wqkv, dt, layer_sh.get("wqkv"))
        layers["b_qkv"] = _put(b_qkv, dt, layer_sh.get("b_qkv"))

        note("converting embed / final norm / head")
        return {
            "embed": _put(
                _fetch(reader, "gpt_neox.embed_in.weight", (v, d)), dt,
                sh.get("embed"),
            ),
            "layers": layers,
            "final_norm": _put(
                _fetch(reader, "gpt_neox.final_layer_norm.weight", (d,)), dt,
                sh.get("final_norm"),
            ),
            "final_norm_b": _put(
                _fetch(reader, "gpt_neox.final_layer_norm.bias", (d,)), dt,
                sh.get("final_norm_b"),
            ),
            "lm_head": _put(
                _fetch(reader, "embed_out.weight", (d, v), transpose=True), dt,
                sh.get("lm_head"),
            ),
        }
    finally:
        reader.close()


def export_hf_gptneox(params: Dict[str, Any], cfg, path: str) -> str:
    """Our gptneox tree → HF-format safetensors (test/interop inverse)."""
    from safetensors.numpy import save_file

    hq, hd = cfg.n_heads, cfg.head_dim
    out: Dict[str, np.ndarray] = {
        "gpt_neox.embed_in.weight": np.asarray(params["embed"]),
        "gpt_neox.final_layer_norm.weight": np.asarray(params["final_norm"]),
        "gpt_neox.final_layer_norm.bias": np.asarray(params["final_norm_b"]),
        "embed_out.weight": np.asarray(params["lm_head"]).T.copy(),
    }
    for ours, (tmpl, transpose) in _HF_NEOX_PLAIN.items():
        stacked = np.asarray(params["layers"][ours])
        for i in range(cfg.n_layers):
            t = stacked[i]
            out[tmpl.format(i)] = (t.T if transpose else t).copy()
    for i in range(cfg.n_layers):
        w = np.asarray(params["layers"]["wqkv"][i]).T  # (3d, d)
        b = np.asarray(params["layers"]["b_qkv"][i])
        out[f"gpt_neox.layers.{i}.attention.query_key_value.weight"] = (
            _interleave_neox_qkv(w, hq, hd).copy()
        )
        out[f"gpt_neox.layers.{i}.attention.query_key_value.bias"] = (
            _interleave_neox_qkv(b, hq, hd).copy()
        )
    save_file(out, path)
    return path


# ------------------------------------------------------------ mixtral


_HF_MIXTRAL_ATTN: Dict[str, Tuple[str, bool]] = {
    "wq": ("model.layers.{}.self_attn.q_proj.weight", True),
    "wk": ("model.layers.{}.self_attn.k_proj.weight", True),
    "wv": ("model.layers.{}.self_attn.v_proj.weight", True),
    "wo": ("model.layers.{}.self_attn.o_proj.weight", True),
    "ln_attn": ("model.layers.{}.input_layernorm.weight", False),
    "ln_mlp": ("model.layers.{}.post_attention_layernorm.weight", False),
}
# HF expert naming: w1 = gate, w2 = down, w3 = up (all stored (out, in))
_HF_MIXTRAL_EXPERTS: Dict[str, str] = {
    "w_gate": "model.layers.{}.block_sparse_moe.experts.{}.w1.weight",
    "w_down": "model.layers.{}.block_sparse_moe.experts.{}.w2.weight",
    "w_up": "model.layers.{}.block_sparse_moe.experts.{}.w3.weight",
}


def convert_hf_mixtral(
    path: str,
    cfg,
    shardings: Optional[Dict[str, Any]] = None,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict[str, Any]:
    """HF MixtralForCausalLM safetensors checkpoint → our param tree
    (per-layer expert-stacked (L, E, in, out) FFN weights, fp32 router)."""
    reader = CheckpointReader(path)
    note = progress or (lambda msg: logger.info("%s", msg))
    try:
        d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
        L, E = cfg.n_layers, cfg.n_experts
        hq = cfg.n_heads * cfg.head_dim
        hkv = cfg.n_kv_heads * cfg.head_dim
        dt = cfg.dtype
        last = f"model.layers.{L - 1}.input_layernorm.weight"
        if last not in reader:
            raise ValueError(
                f"checkpoint does not match n_layers={L}: missing {last!r}"
            )
        sh = shardings or {}
        layer_sh = sh.get("layers") or {}

        shapes = {
            "wq": (L, d, hq), "wk": (L, d, hkv), "wv": (L, d, hkv),
            "wo": (L, hq, d), "ln_attn": (L, d), "ln_mlp": (L, d),
        }
        layers: Dict[str, Any] = {}
        for ours, (tmpl, transpose) in _HF_MIXTRAL_ATTN.items():
            note(f"converting {ours} ({L} layers)")
            layers[ours] = _stack_layers(
                reader, L, tmpl, transpose, dt, shapes[ours],
                sharding=layer_sh.get(ours),
            )
        # router: HF gate.weight is (E, d); ours (L, d, E) fp32
        note("converting router")
        router = np.empty((L, d, E), dtype=np.float32)
        for i in range(L):
            g = reader.tensor(
                f"model.layers.{i}.block_sparse_moe.gate.weight"
            )
            if tuple(g.shape) != (E, d):
                raise ValueError(
                    f"gate.weight shape {tuple(g.shape)} != {(E, d)}"
                )
            router[i] = np.asarray(g, dtype=np.float32).T
        layers["router"] = _put(
            router, np.float32, layer_sh.get("router")
        )
        exp_shapes = {
            "w_gate": (d, f), "w_up": (d, f), "w_down": (f, d),
        }
        for ours, tmpl in _HF_MIXTRAL_EXPERTS.items():
            note(f"converting {ours} ({L} layers x {E} experts)")
            per = exp_shapes[ours]
            stacked = np.empty((L, E) + per, dtype=dt)
            for i in range(L):
                for e in range(E):
                    t = reader.tensor(tmpl.format(i, e)).T
                    if tuple(t.shape) != per:
                        raise ValueError(
                            f"{tmpl.format(i, e)}: shape {tuple(t.shape)} "
                            f"!= expected {per}"
                        )
                    stacked[i, e] = np.asarray(t, dtype=dt)
            layers[ours] = _put(stacked, dt, layer_sh.get(ours))

        note("converting embed / final_norm / lm_head")
        return {
            "embed": _put(
                _fetch(reader, "model.embed_tokens.weight", (v, d)), dt,
                sh.get("embed"),
            ),
            "layers": layers,
            "final_norm": _put(
                _fetch(reader, "model.norm.weight", (d,)), dt, sh.get("final_norm")
            ),
            "lm_head": _put(
                _fetch(reader, "lm_head.weight", (d, v), transpose=True), dt,
                sh.get("lm_head"),
            ),
        }
    finally:
        reader.close()


def export_hf_mixtral(params: Dict[str, Any], cfg, path: str) -> str:
    """Our mixtral tree → HF-format safetensors (test/interop inverse)."""
    from safetensors.numpy import save_file

    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"]),
        "model.norm.weight": np.asarray(params["final_norm"]),
        "lm_head.weight": np.asarray(params["lm_head"]).T.copy(),
    }
    for ours, (tmpl, transpose) in _HF_MIXTRAL_ATTN.items():
        stacked = np.asarray(params["layers"][ours])
        for i in range(cfg.n_layers):
            t = stacked[i]
            out[tmpl.format(i)] = (t.T if transpose else t).copy()
    router = np.asarray(params["layers"]["router"])
    for i in range(cfg.n_layers):
        out[f"model.layers.{i}.block_sparse_moe.gate.weight"] = (
            router[i].T.copy().astype(np.float32)
        )
    for ours, tmpl in _HF_MIXTRAL_EXPERTS.items():
        stacked = np.asarray(params["layers"][ours])
        for i in range(cfg.n_layers):
            for e in range(cfg.n_experts):
                out[tmpl.format(i, e)] = stacked[i, e].T.copy()
    save_file(out, path)
    return path


CONVERTERS: Dict[str, Callable[..., Dict[str, Any]]] = {
    "llama": convert_hf_llama,
    "gptneox": convert_hf_gptneox,
    "mixtral": convert_hf_mixtral,
}


def load_pretrained(
    family_name: str,
    path: str,
    cfg,
    mesh=None,
    logical_tree=None,
) -> Dict[str, Any]:
    """Entry point the runtime uses: convert ``path`` for ``family_name``,
    placing leaves onto ``mesh`` shardings when given."""
    try:
        converter = CONVERTERS[family_name]
    except KeyError:
        raise ValueError(
            f"no safetensors converter for family {family_name!r} "
            f"(have: {sorted(CONVERTERS)})"
        )
    shardings = None
    if mesh is not None and logical_tree is not None:
        from nexus_tpu.parallel.sharding import sharding_tree

        shardings = sharding_tree(logical_tree, mesh)
    return converter(path, cfg, shardings=shardings)
