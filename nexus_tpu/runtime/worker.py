"""Pod-side worker entrypoint: ``python -m nexus_tpu.runtime.worker``.

This is what actually runs inside a materialized Job's container (the
launched TPU pod). The materializer (materializer.py) wires the contract as
env vars; this module is their single consumer:

  NEXUS_RUNTIME_SPEC       — compact-JSON JaxXlaRuntime block
  NEXUS_SLICE_INDEX        — which slice this Job serves (multislice)
  NEXUS_SLICE_COUNT        — total slices
  NEXUS_SHARD_NAME         — provenance, echoed into the result
  JAX_COORDINATOR_ADDRESS  — pod 0 of slice 0 (host:port)
  JOB_COMPLETION_INDEX     — Indexed-Job host index within this slice
  NEXUS_RESULT_PATH        — optional path to also write the metrics JSON
  NEXUS_RESTORE_STEP       — failover: pin resume to this exact durable
                             checkpoint step (the planner's restore-step
                             annotation, stamped by the materializer)
  NEXUS_HB_KUBECONFIG      — failover: when set (+ template name/namespace
                             below), process 0 renews the heartbeat lease
                             (ha/lease.py) against this shard API at every
                             step boundary
  NEXUS_HB_TEMPLATE / NEXUS_HB_NAMESPACE / NEXUS_HB_TTL_SECONDS

Flow (SURVEY.md §7.2): derive (process_id, num_processes) from the slice /
host indices → ``jax.distributed.initialize`` when multi-process → build the
mesh and execute the runtime (entrypoints.py) → emit ONE metrics JSON line
on stdout. The reference has no workload plane at all (SURVEY.md §2c); this
file is the TPU-native addition that turns a synced template into a running
JAX job.
"""

from __future__ import annotations

import json
import logging
import os
import sys
from dataclasses import dataclass
from typing import Any, Dict, Optional

from nexus_tpu.api.runtime_spec import JaxXlaRuntime

logger = logging.getLogger("nexus_tpu.worker")


@dataclass(frozen=True)
class WorkerIdentity:
    """Where this process sits in the (slice, host) grid."""

    slice_index: int
    slice_count: int
    host_index: int
    hosts_per_slice: int

    @property
    def process_id(self) -> int:
        """Global JAX process id: slices are contiguous blocks of hosts, so
        coordinator (slice 0, host 0) is always process 0."""
        return self.slice_index * self.hosts_per_slice + self.host_index

    @property
    def num_processes(self) -> int:
        return self.slice_count * self.hosts_per_slice


def identity_from_env(
    runtime: JaxXlaRuntime, environ: Optional[Dict[str, str]] = None
) -> WorkerIdentity:
    env = os.environ if environ is None else environ
    return WorkerIdentity(
        slice_index=int(env.get("NEXUS_SLICE_INDEX", "0") or 0),
        slice_count=int(
            env.get("NEXUS_SLICE_COUNT", "") or runtime.tpu.slice_count
        ),
        host_index=int(env.get("JOB_COMPLETION_INDEX", "0") or 0),
        hosts_per_slice=runtime.tpu.hosts_per_slice,
    )


def maybe_initialize_distributed(
    identity: WorkerIdentity, environ: Optional[Dict[str, str]] = None
) -> bool:
    """Call ``jax.distributed.initialize`` iff this is a multi-process job.

    Single-process jobs (1 host × 1 slice — incl. every local/test run) skip
    initialization entirely: jax.distributed requires a coordinator service
    that a lone process has no use for. Returns True if initialized.
    """
    if identity.num_processes <= 1:
        return False
    env = os.environ if environ is None else environ
    coordinator = env.get("JAX_COORDINATOR_ADDRESS", "")
    if not coordinator:
        raise RuntimeError(
            "multi-process runtime but JAX_COORDINATOR_ADDRESS is not set "
            "(materializer wires it on every pod — see materializer.py)"
        )
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=identity.num_processes,
        process_id=identity.process_id,
    )
    logger.info(
        "jax.distributed initialized: process %d/%d (slice %d host %d) "
        "coordinator=%s",
        identity.process_id, identity.num_processes,
        identity.slice_index, identity.host_index, coordinator,
    )
    return True


def run_from_env(environ: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """Parse the materializer contract from env and execute the runtime."""
    env = os.environ if environ is None else environ
    spec_json = env.get("NEXUS_RUNTIME_SPEC", "")
    if not spec_json:
        raise RuntimeError(
            "NEXUS_RUNTIME_SPEC is not set — this entrypoint only runs "
            "inside a materialized Job (or with the env contract replicated)"
        )
    runtime = JaxXlaRuntime.from_dict(json.loads(spec_json))
    errs = runtime.validate()
    if errs:
        raise RuntimeError(f"invalid runtime spec: {'; '.join(errs)}")

    from nexus_tpu.utils.hw import honor_env_platforms

    honor_env_platforms()

    identity = identity_from_env(runtime, env)
    distributed = maybe_initialize_distributed(identity, env)

    from nexus_tpu.runtime.entrypoints import run_template_runtime
    from nexus_tpu.utils.signals import setup_signal_handler

    # SIGTERM (slice preemption / node drain) → graceful stop + final
    # checkpoint, so the Job's retry resumes instead of restarting
    try:
        cancel = setup_signal_handler()
    except ValueError:  # not on the main thread (tests drive run_from_env)
        cancel = None

    restore_step: Optional[int] = None
    if env.get("NEXUS_RESTORE_STEP", "") != "":
        restore_step = int(env["NEXUS_RESTORE_STEP"])

    heartbeat = None
    renewer = None
    if env.get("NEXUS_HB_KUBECONFIG") and identity.process_id == 0:
        # process 0 heartbeats for the whole job (one lease per template —
        # detecting any wedged host is the Job's backoff policy's problem;
        # the lease answers "is this workload making step progress")
        from nexus_tpu.cluster.kube import KubeClusterStore
        from nexus_tpu.ha.lease import LeaseRenewer

        hb_template = env.get("NEXUS_HB_TEMPLATE", "unknown")
        if runtime.mode == "serve":
            # serving engines renew ``hb-serve-<template>`` on the pod
            # path too — the same name LocalLauncher uses, so the
            # freeze_engine chaos hook and the failover planners' serve
            # lease detection hold for real pods (ha/serve_failover.py).
            # A FLEET replica (NEXUS_SERVE_REPLICA_ID, stamped by the
            # controller's replica-homes placement) renews its own
            # ``hb-serve-<template>--<id>`` lease instead, so the fleet
            # monitor confirms deaths per replica — N engines on one
            # shared lease would mask any single replica's death
            from nexus_tpu.ha.serve_failover import (
                serve_heartbeat_template,
                serve_replica_template,
            )

            replica_id = env.get("NEXUS_SERVE_REPLICA_ID", "").strip()
            if replica_id:
                hb_template = serve_replica_template(
                    hb_template, replica_id
                )
            else:
                hb_template = serve_heartbeat_template(hb_template)
        renewer = LeaseRenewer(
            KubeClusterStore("hb", env["NEXUS_HB_KUBECONFIG"]),
            namespace=env.get("NEXUS_HB_NAMESPACE", "default"),
            template_name=hb_template,
            holder=f"{env.get('NEXUS_SHARD_NAME', '')}"
                   f"-p{identity.process_id}-{os.getpid()}",
            ttl_seconds=float(env.get("NEXUS_HB_TTL_SECONDS", "15") or 15),
        )
        heartbeat = renewer.renew

    metrics = run_template_runtime(
        runtime, cancel=cancel, heartbeat=heartbeat,
        restore_step=restore_step,
        serve_replica_id=env.get("NEXUS_SERVE_REPLICA_ID", "").strip(),
    )
    if renewer is not None and not metrics.get("interrupted"):
        renewer.complete(int(metrics.get("steps", -1) or -1))
    metrics["shard"] = env.get("NEXUS_SHARD_NAME", "")
    metrics["process_id"] = identity.process_id
    metrics["num_processes"] = identity.num_processes
    metrics["distributed"] = distributed
    return metrics


def main() -> int:
    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    try:
        metrics = run_from_env()
    except Exception as e:  # noqa: BLE001 — the Job's backoffLimit handles retry
        logger.exception("worker failed")
        print(json.dumps({"phase": "Failed", "error": str(e)}), flush=True)
        return 1
    from nexus_tpu.api.runtime_spec import EXIT_PREEMPTED

    # The preemption exit code (→ reschedule via the standing Ignore rule)
    # is only legitimate when a rerun can actually resume — otherwise an
    # unkillable zero-progress loop: reschedule, restart from 0, repeat.
    preempted = bool(metrics.get("interrupted")) and bool(
        metrics.get("checkpoint_saved")
    )
    phase = "Preempted" if preempted else "Succeeded"
    line = json.dumps({"phase": phase, **metrics}, default=str)
    print(line, flush=True)
    result_path = os.environ.get("NEXUS_RESULT_PATH", "")
    if result_path:
        with open(result_path, "w") as f:
            f.write(line)
    return EXIT_PREEMPTED if preempted else 0


if __name__ == "__main__":
    sys.exit(main())
