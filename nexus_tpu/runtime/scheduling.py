"""Admission-ordering policies for the serving engine.

The wait-queue used to be strictly FIFO inside ``ServingEngine.serve``
itself. Round 9 extracts the ORDERING decision into a small policy
object — the first slice of the ROADMAP scheduler/executor split: the
engine stays the executor (dispatch, leases, pool), and *which* queued
request a freed row takes next becomes a pluggable policy instead of
further surgery on runtime/serving.py.

Two policies ship:

  * ``FifoAdmission`` — arrival order, the pre-round-9 behavior and the
    A/B baseline.
  * ``CacheAwareAdmission`` — order admissible requests to maximize
    reuse of prefixes currently RESIDENT in the radix prefix cache
    (longest-resident-match-first, SGLang RadixAttention's cache-aware
    scheduling): a request whose whole preamble is parked right now
    admits before a cold one, converting parked blocks into hits before
    pool pressure evicts them and keeping same-subtree requests
    together so their shared runs stay hot. Starvation is bounded by an
    AGING rule: a request passed over ``aging_waves`` times is promoted
    ahead of every non-aged request (aged requests among themselves are
    FIFO), so the worst case is a bounded delay, never a livelock.

The engine's exactness contract is untouched by construction: ordering
changes WHEN a request is scheduled, never what is computed — proven
token-for-token in tests/test_serving.py across policies.

Pool-full semantics carry over from FIFO: when the policy's chosen head
cannot reserve its blocks, the wave stops and that request waits for
refunds (it is never overtaken *within* the policy order), which
combined with aging preserves the no-starvation guarantee.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence, Tuple, Union

ADMISSION_POLICIES = ("fifo", "cache-aware")


class AdmissionPolicy:
    """Order the wait queue for one admission wave.

    ``order`` receives the pending request indices in ARRIVAL order, the
    per-request passed-over counts (how many admission waves have
    overtaken each request so far), and a ``resident_match`` callback
    returning the prompt tokens currently matchable against cache
    content — either a plain int (resident tokens, the round-9
    signature) or a ``(resident, spilled)`` pair once the host spill
    tier is attached (round 10): a SPILLED hit still needs a restore
    upload, so it ranks below a resident hit of any depth but above a
    cold miss — tiers compare lexicographically. It returns the indices
    in the order admission should try them. Policies must be
    deterministic and pure (no clocks — aging is counted in waves, so
    scheduling replays exactly under the injectable-clock test
    discipline).

    Cost note: the engine calls ``order`` once per admission wave over
    the whole pending queue (cache-aware additionally re-matches each
    pending request against the radix tree — an O(prefix) walk on
    host-cached chain keys). That re-ranking is what lets deferred
    groups and freshly-parked completion chains re-rank honestly, but
    it prices each wave O(queue): serve configs should bound the
    backlog with ``maxQueueDepth`` (the example config does), and an
    incremental ranker is follow-up work under the ROADMAP
    scheduler/executor split."""

    name = "custom"  # subclasses name themselves for the metrics ledger

    def __init__(self) -> None:
        # observability surface (round 12): a policy MAY refresh this
        # dict inside ``order`` with cheap facts about the wave it just
        # ranked (``pending`` size, how many aged requests jumped the
        # queue, ...); the engine copies it into the flight recorder's
        # admission event, so chaos postmortems show WHY the queue was
        # ordered the way it was. Never read by scheduling logic —
        # purely a telemetry export. INSTANCE-owned (assigned here, not
        # a class default): two engines' policies in one process must
        # never report each other's wave meta, even if a subclass
        # mutates the dict in place.
        self.last_wave_meta: Dict[str, int] = {}

    def order(
        self,
        pending: Sequence[int],
        passed_over: Dict[int, int],
        resident_match: Callable[[int], int],
    ) -> List[int]:
        raise NotImplementedError


class FifoAdmission(AdmissionPolicy):
    """Strict arrival order — the pre-round-9 engine behavior."""

    name = "fifo"

    def order(self, pending, passed_over, resident_match):
        self.last_wave_meta = {"pending": len(pending), "aged": 0}
        return list(pending)


class CacheAwareAdmission(AdmissionPolicy):
    """Longest-resident-match-first with a bounded aging guarantee.

    Aged requests (passed over >= ``aging_waves`` admission waves) go
    first, in arrival order; everyone else is sorted by descending
    resident match length — with the host spill tier attached, by the
    ``(resident, spilled)`` pair lexicographically, so a spilled hit
    (which costs a restore upload) outranks a miss but never a resident
    hit — with arrival order as the tie-break. A cache-cold queue
    degrades to exact FIFO, and a request can be overtaken at most
    ``aging_waves`` times before it outranks every fresher arrival."""

    name = "cache-aware"

    def __init__(self, aging_waves: int = 8) -> None:
        super().__init__()
        if aging_waves < 1:
            raise ValueError(
                f"aging_waves must be >= 1, got {aging_waves}"
            )
        self.aging_waves = int(aging_waves)

    @staticmethod
    def _tiers(match) -> Tuple[int, int]:
        """Normalize the ranking signal: a plain int is resident-only
        (the round-9 signature and every custom callback written
        against it); a pair is (resident, spilled)."""
        if isinstance(match, tuple):
            return match
        return (match, 0)

    def order(self, pending, passed_over, resident_match):
        pending = list(pending)
        pos = {idx: i for i, idx in enumerate(pending)}
        aged = [
            i for i in pending
            if passed_over.get(i, 0) >= self.aging_waves
        ]
        fresh = [
            i for i in pending
            if passed_over.get(i, 0) < self.aging_waves
        ]

        def key(i):
            resident, spilled = self._tiers(resident_match(i))
            return (-resident, -spilled, pos[i])

        fresh.sort(key=key)
        self.last_wave_meta = {"pending": len(pending), "aged": len(aged)}
        return aged + fresh


def make_admission_policy(
    spec: Union[str, AdmissionPolicy], aging_waves: int = 8
) -> AdmissionPolicy:
    """Resolve a policy name (``ServeSpec.admissionPolicy``) or pass an
    already-built policy through (the pluggable-interface path)."""
    if isinstance(spec, AdmissionPolicy):
        return spec
    if spec == "fifo":
        return FifoAdmission()
    if spec == "cache-aware":
        return CacheAwareAdmission(aging_waves=aging_waves)
    raise ValueError(
        f"admission_policy must be one of {ADMISSION_POLICIES} (or an "
        f"AdmissionPolicy instance), got {spec!r}"
    )
