"""Template → Kubernetes Job manifest with TPU slice scheduling.

This is the concrete realization of the BASELINE north star: fan-out emits
``google.com/tpu`` resource requests and ``cloud.google.com/gke-tpu-topology``
nodeSelectors instead of ``nvidia.com/gpu`` + NCCL env. One Job per slice;
``completions = parallelism = hosts_per_slice`` with ``completion-mode:
Indexed`` so each pod knows its host index; JAX multi-host init is wired via
env (coordinator = pod 0 of slice 0).

The manifest is a plain dict — appliable via the Kubernetes API on real
shards, and interpretable by the LocalLauncher on in-process shards.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from nexus_tpu.api.template import NexusAlgorithmTemplate
from nexus_tpu.api.types import GROUP, LABEL_CONTROLLER_APP, CONTROLLER_APP_NAME
from nexus_tpu.api.workgroup import NexusAlgorithmWorkgroup

LABEL_TEMPLATE = f"{GROUP}/template"
LABEL_SLICE_INDEX = f"{GROUP}/slice-index"
ANNOTATION_RUNTIME = f"{GROUP}/runtime"
# Failover (ha/failover.py): the planner stamps the latest durable
# checkpoint step on the template; the materializer turns it into the
# worker's NEXUS_RESTORE_STEP env so the re-placed Job resumes from that
# exact step. Carried in template *metadata* (not spec) — it is
# controller-operational state, not user intent.
ANNOTATION_RESTORE_STEP = f"{GROUP}/restore-step"


def _slice_job_name(template: NexusAlgorithmTemplate, slice_count: int,
                    slice_idx: int) -> str:
    """Canonical per-slice Job name — also the pods' subdomain and the
    headless-Service name, so coordinator DNS ('<job>-0.<job>') resolves.
    Single source of truth: materialize_job, the coordinator address, and
    materialize_headless_service must all agree."""
    return template.metadata.name + (
        f"-s{slice_idx}" if slice_count > 1 else ""
    )


def materialize_job(
    template: NexusAlgorithmTemplate,
    workgroup: Optional[NexusAlgorithmWorkgroup] = None,
    shard_name: str = "",
    replica_id: str = "",
) -> List[Dict[str, Any]]:
    """Build one Job manifest per TPU slice for a template's runtime block.

    ``replica_id`` (fleet serve placement, round 15): when the
    controller placed this template on N shards as a serve FLEET
    (``ServeSpec.replicas > 1``), each shard's copy carries its replica
    identity — the launched engine renews the per-replica
    ``hb-serve-<template>--<id>`` lease and tags its live gauges
    ``engine:<id>`` (the signals the fleet router/autoscaler consume),
    instead of N untagged engines all claiming the template's one
    lease. Emitted as ``NEXUS_SERVE_REPLICA_ID``; empty for single-home
    and training workloads (env omitted, manifests bit-identical to
    round 14's).

    Raises ValueError if the template has no runtime or the runtime is
    invalid (axes don't tile the slice, unknown accelerator, ...)."""
    rt = template.spec.runtime
    if rt is None:
        raise ValueError(f"template {template.key()} has no jax_xla runtime block")
    errs = rt.validate()
    if errs:
        raise ValueError(
            f"invalid runtime for template {template.key()}: {'; '.join(errs)}"
        )

    tpu = rt.tpu
    env = [
        {"name": e.name, "value": e.value}
        for e in template.spec.runtime_environment.environment_variables
    ]
    env_from = []
    for src in template.spec.runtime_environment.mapped_environment_variables:
        if src.secret_ref:
            env_from.append({"secretRef": {"name": src.secret_ref}})
        if src.config_map_ref:
            env_from.append({"configMapRef": {"name": src.config_map_ref}})

    node_selector = {
        "cloud.google.com/gke-tpu-accelerator": tpu.gke_accelerator,
        "cloud.google.com/gke-tpu-topology": tpu.topology,
    }
    tolerations = [
        {"key": "google.com/tpu", "operator": "Exists", "effect": "NoSchedule"}
    ]
    if workgroup is not None:
        for t in workgroup.spec.tolerations:
            tolerations.append(t.to_dict())

    jobs: List[Dict[str, Any]] = []
    for slice_idx in range(tpu.slice_count):
        job_name = _slice_job_name(template, tpu.slice_count, slice_idx)
        # Indexed-Job pods are hostnamed "<job>-<index>" under the pod
        # subdomain "<job>" (a headless Service with that name must exist —
        # materialize_headless_service). The coordinator is pod 0 of slice 0,
        # whose job is "<template>-s0" in multislice, so its FQDN component is
        # "<template>-s0-0.<template>-s0" — NOT "<template>-s0-0.<template>"
        # (that subdomain has no DNS record).
        slice0_job = _slice_job_name(template, tpu.slice_count, 0)
        coordinator = f"{slice0_job}-0.{slice0_job}"
        runtime_env = env + [
            {"name": "NEXUS_RUNTIME_SPEC", "value": _compact_json(rt.to_dict())},
            {"name": "NEXUS_SLICE_INDEX", "value": str(slice_idx)},
            {"name": "NEXUS_SLICE_COUNT", "value": str(tpu.slice_count)},
            {"name": "NEXUS_SHARD_NAME", "value": shard_name},
            # jax.distributed.initialize() wiring: coordinator + process ids
            # derive from the Indexed-Job pod index (JOB_COMPLETION_INDEX)
            {"name": "JAX_COORDINATOR_ADDRESS", "value": f"{coordinator}:8476"},
            {"name": "TPU_WORKER_HOSTNAMES", "value": ""},
            # heartbeat lease identity (ha/lease.py); the shard-API
            # credential (NEXUS_HB_KUBECONFIG) is deployment-provided via
            # the template's environment variables
            {"name": "NEXUS_HB_TEMPLATE", "value": template.metadata.name},
            {"name": "NEXUS_HB_NAMESPACE", "value": template.metadata.namespace},
        ]
        if replica_id:
            runtime_env.append(
                {"name": "NEXUS_SERVE_REPLICA_ID", "value": replica_id}
            )
        restore_step = (template.metadata.annotations or {}).get(
            ANNOTATION_RESTORE_STEP, ""
        )
        if restore_step:
            runtime_env.append(
                {"name": "NEXUS_RESTORE_STEP", "value": restore_step}
            )
        pod_spec: Dict[str, Any] = {
            "serviceAccountName": template.spec.container.service_account_name or None,
            "restartPolicy": "Never",
            "nodeSelector": dict(node_selector),
            "tolerations": tolerations,
            "subdomain": job_name,  # stable DNS for the coordinator
            "containers": [
                {
                    "name": "jax-worker",
                    "image": template.spec.container.full_image,
                    # default to the framework's pod entrypoint (worker.py —
                    # the NEXUS_RUNTIME_SPEC consumer) only when the template
                    # specifies neither command nor args; args without a
                    # command target the image's own ENTRYPOINT
                    "command": [template.spec.command]
                    if template.spec.command
                    else (
                        None
                        if template.spec.args
                        else ["python", "-m", "nexus_tpu.runtime.worker"]
                    ),
                    "args": list(template.spec.args) or None,
                    "env": runtime_env,
                    "envFrom": env_from or None,
                    "resources": {
                        "limits": _resources(template, tpu),
                        "requests": _resources(template, tpu),
                    },
                    "ports": [{"containerPort": 8476}],
                }
            ],
        }
        backoff = template.spec.runtime_environment.maximum_retries
        # ErrorHandlingBehaviour → Kubernetes podFailurePolicy: fatal exit
        # codes fail the whole Job immediately; transient codes don't count
        # against backoffLimit (the pod is simply retried). This executes
        # the CRD's declared retry semantics in-cluster — the reference
        # carries the same fields but defers execution to its ecosystem
        # (reference shape: controller_test.go:318-321).
        eh = template.spec.error_handling_behaviour
        failure_rules = []
        # exit code 0 is success — the apiserver rejects it in onExitCodes
        # values (operator In), which would fail creation of the whole Job.
        # EXIT_PREEMPTED (worker.py) is always transient: a SIGTERM-
        # interrupted run checkpoints and must be rescheduled without
        # burning backoffLimit (fatal wins if a template lists it there).
        from nexus_tpu.api.runtime_spec import EXIT_PREEMPTED

        fatal = sorted({c for c in eh.fatal_exit_codes if c != 0})
        transient = sorted(
            ({c for c in eh.transient_exit_codes} | {EXIT_PREEMPTED})
            - set(fatal)
            - {0}
        )
        if fatal:
            failure_rules.append(
                {
                    "action": "FailJob",
                    "onExitCodes": {
                        "containerName": "jax-worker",
                        "operator": "In",
                        "values": fatal,
                    },
                }
            )
        if transient:
            failure_rules.append(
                {
                    "action": "Ignore",
                    "onExitCodes": {
                        "containerName": "jax-worker",
                        "operator": "In",
                        "values": transient,
                    },
                }
            )
        job = {
            "apiVersion": "batch/v1",
            "kind": "Job",
            "metadata": {
                "name": job_name,
                "namespace": template.metadata.namespace,
                "labels": {
                    LABEL_CONTROLLER_APP: CONTROLLER_APP_NAME,
                    LABEL_TEMPLATE: template.metadata.name,
                    LABEL_SLICE_INDEX: str(slice_idx),
                },
                "annotations": dict(
                    template.spec.runtime_environment.annotations
                ),
                "ownerReferences": [
                    {
                        "apiVersion": f"{GROUP}/v1",
                        "kind": template.KIND,
                        "name": template.metadata.name,
                        "uid": template.metadata.uid,
                    }
                ],
            },
            "spec": {
                "completions": tpu.hosts_per_slice,
                "parallelism": tpu.hosts_per_slice,
                "completionMode": "Indexed",
                "backoffLimit": backoff if backoff is not None else 3,
                "podFailurePolicy": {"rules": failure_rules}
                if failure_rules
                else None,
                "activeDeadlineSeconds": template.spec.runtime_environment.deadline_seconds,
                "template": {
                    "metadata": {
                        "labels": {
                            LABEL_TEMPLATE: template.metadata.name,
                            LABEL_SLICE_INDEX: str(slice_idx),
                        }
                    },
                    "spec": pod_spec,
                },
            },
        }
        jobs.append(job)
    return jobs


def materialize_headless_service(
    template: NexusAlgorithmTemplate,
) -> List[Dict[str, Any]]:
    """Headless Services backing the per-slice pod subdomains.

    Pod-subdomain DNS records only exist when a headless Service with the
    subdomain's name selects the pods; real-cluster appliers must apply these
    alongside the Jobs from :func:`materialize_job`."""
    rt = template.spec.runtime
    if rt is None:
        return []
    names = [
        _slice_job_name(template, rt.tpu.slice_count, i)
        for i in range(rt.tpu.slice_count)
    ]
    return [
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": n,
                "namespace": template.metadata.namespace,
                "labels": {
                    LABEL_CONTROLLER_APP: CONTROLLER_APP_NAME,
                    LABEL_TEMPLATE: template.metadata.name,
                },
                "ownerReferences": [
                    {
                        "apiVersion": f"{GROUP}/v1",
                        "kind": template.KIND,
                        "name": template.metadata.name,
                        "uid": template.metadata.uid,
                    }
                ],
            },
            "spec": {
                "clusterIP": "None",
                # publish hostname records before pods pass readiness: all
                # slice pods start together and workers must resolve the
                # coordinator during startup (the JobSet pattern)
                "publishNotReadyAddresses": True,
                # scope each subdomain to its own slice's pods — selecting on
                # the template label alone would resolve cross-slice
                "selector": {
                    LABEL_TEMPLATE: template.metadata.name,
                    LABEL_SLICE_INDEX: str(i),
                },
                "ports": [{"port": 8476, "name": "jax-coordinator"}],
            },
        }
        for i, n in enumerate(names)
    ]


def _resources(template: NexusAlgorithmTemplate, tpu) -> Dict[str, str]:
    res: Dict[str, str] = {}
    cr = template.spec.compute_resources
    if cr.cpu_limit:
        res["cpu"] = cr.cpu_limit
    if cr.memory_limit:
        res["memory"] = cr.memory_limit
    res.update(cr.custom_resources)
    # the TPU request: chips per host on this slice (GKE schedules whole hosts)
    res["google.com/tpu"] = str(tpu.chips_per_host)
    return res


def _compact_json(obj) -> str:
    import json

    return json.dumps(obj, separators=(",", ":"), sort_keys=True)
