"""Host-RAM spill tier for the paged serving KV cache (round 10).

Under pool pressure the ref-counted :class:`BlockAllocator`
(runtime/serving.py) reclaims parked (refcount-0) prefix blocks. Before
round 10 reclaim DESTROYED the content — a warm system prompt or a
multi-turn conversation's history, exactly what the radix prefix tree
was built to re-match, was recomputed from scratch the moment HBM ran
tight. This module is the second storage tier that turns eviction into
DEMOTION: the evicted block's K/V planes are downloaded into a bounded,
byte-budgeted host-side store keyed by the block's chain digest, the
radix-tree entry is marked *spilled* instead of removed
(runtime/prefix_cache.py), and a later prefix match PROMOTES the block
back — the allocator maps a fresh pool block and the engine uploads the
host copy in one fixed-shape dispatch per admission wave
(models/decoding.py::write_kv_blocks). The effective prefix cache is
bounded by host RAM, not HBM (Prompt Cache's modular reuse, PAPERS.md).

The store is a plain LRU over digests with exact byte accounting:

  * ``put`` charges every plane's ``nbytes``; when the budget is
    exceeded the CALLER (the allocator) evicts — leaf-first through
    ``PrefixCacheIndex.evict_spilled_lru`` — so the tree and the store
    can never disagree about what is restorable (the invariant the
    sanitizer's host-cache audit asserts: store keys == spilled tree
    entries, bit for bit).
  * ``dtype="int8"`` DEMOTES floating-point payloads on spill: K/V are
    quantized per (layer, position, head) vector to int8 with an f32
    scale plane — the same max-abs/127 rule the device-side int8 cache
    uses (models/decoding.py::_quantize_kv), so a restored block's
    worst-case per-element error is ``max|x| / 254`` of its vector's
    magnitude (half a quantization step). Roughly 2x more spilled
    blocks per host byte, at the documented precision cost; restores of
    an ALREADY-int8 pool's blocks are byte-identical (nothing to
    demote), as are ``dtype="native"`` restores of any pool.

Pure numpy + stdlib — no jax, no clocks (LRU order is operation order,
so spill/restore schedules replay exactly under the injectable-clock
test discipline)."""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Tuple

import numpy as np

HOST_CACHE_DTYPES = ("native", "int8")


def quantize_kv_host(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(..., D) float → (int8 values, (...) f32 per-vector scales) — the
    host mirror of models/decoding.py::_quantize_kv (max-abs/127 per
    trailing vector), so int8 demotion and the device int8 cache share
    ONE documented error model: |x - dequant(x)| <= scale/2 =
    max|x|/254 per vector."""
    scale = (
        np.abs(x.astype(np.float32)).max(axis=-1) / 127.0
    ).astype(np.float32)
    safe = np.where(scale == 0.0, 1.0, scale)
    q = np.clip(
        np.round(x.astype(np.float32) / safe[..., None]), -127, 127
    ).astype(np.int8)
    return q, scale


def dequantize_kv_host(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """Inverse of :func:`quantize_kv_host` (f32 output; the engine casts
    to the pool dtype at upload)."""
    return q.astype(np.float32) * scale[..., None].astype(np.float32)


class HostBlockStore:
    """Bounded LRU store of spilled KV blocks, keyed by chain digest.

    An entry is the full plane dict of ONE pool block as downloaded by
    the engine — ``{"k", "v"}`` for an fp pool, plus ``{"k_scale",
    "v_scale"}`` for an int8 pool (or after int8 demotion; demoted
    entries reuse the quantized pool's plane names so promotion has one
    layout to reason about). ``budget_bytes`` bounds the SUM of plane
    nbytes; the store never evicts on its own — ``over_budget`` tells
    the allocator to reclaim through the radix tree's leaf-first
    spilled-LRU so tree and store stay in lockstep."""

    def __init__(self, budget_bytes: int, dtype: str = "native") -> None:
        if budget_bytes < 0:
            raise ValueError(
                f"budget_bytes must be >= 0, got {budget_bytes}"
            )
        if dtype not in HOST_CACHE_DTYPES:
            raise ValueError(
                f"host cache dtype must be one of {HOST_CACHE_DTYPES}, "
                f"got {dtype!r}"
            )
        self.budget_bytes = int(budget_bytes)
        self.dtype = dtype
        # digest → plane dict; insertion order == LRU → MRU
        self._entries: "OrderedDict[bytes, Dict[str, np.ndarray]]" = (
            OrderedDict()
        )
        self._demoted: set = set()  # keys stored int8-demoted from fp
        self._bytes = 0
        self.bytes_peak = 0
        self.puts = 0
        self.takes = 0
        self.drops = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: bytes) -> bool:
        return key in self._entries

    @property
    def bytes(self) -> int:
        return self._bytes

    def over_budget(self) -> bool:
        return self._bytes > self.budget_bytes

    def keys(self) -> List[bytes]:
        """Digests held, LRU → MRU (the audit's view)."""
        return list(self._entries)

    @staticmethod
    def _nbytes(planes: Dict[str, np.ndarray]) -> int:
        return sum(int(a.nbytes) for a in planes.values())

    def put(self, key: bytes, planes: Dict[str, np.ndarray]) -> None:
        """Store one spilled block's planes under ``key`` (MRU end).

        ``dtype="int8"`` demotes floating-point K/V on the way in;
        payloads that are ALREADY int8 (a quantized pool's blocks) pass
        through byte-identical. One entry per digest — the tree marks a
        digest spilled exactly once, so a duplicate put is a
        bookkeeping bug, not a cache policy decision."""
        if key in self._entries:
            raise ValueError("digest already spilled — tree/store "
                             "bookkeeping diverged")
        planes = {k: np.asarray(v) for k, v in planes.items()}
        if self.dtype == "int8" and planes["k"].dtype != np.int8:
            kq, ks = quantize_kv_host(planes["k"])
            vq, vs = quantize_kv_host(planes["v"])
            planes = {"k": kq, "v": vq, "k_scale": ks, "v_scale": vs}
            self._demoted.add(key)
        self._entries[key] = planes
        self._bytes += self._nbytes(planes)
        self.bytes_peak = max(self.bytes_peak, self._bytes)
        self.puts += 1

    def take(self, key: bytes) -> Tuple[Dict[str, np.ndarray], bool]:
        """Remove and return ``(planes, demoted)`` for a restore —
        ``demoted`` tells the engine to dequantize before uploading
        into an fp pool. The entry leaves the store: once resident the
        pool block is the content's one home again (re-spilling later
        re-downloads, so the host copy can never go stale)."""
        planes = self._entries.pop(key)
        self._bytes -= self._nbytes(planes)
        self.takes += 1
        demoted = key in self._demoted
        self._demoted.discard(key)
        return planes, demoted

    def drop(self, key: bytes) -> None:
        """Discard an entry (host-budget eviction — the caller already
        removed the tree's spilled marker via evict_spilled_lru)."""
        planes = self._entries.pop(key)
        self._bytes -= self._nbytes(planes)
        self._demoted.discard(key)
        self.drops += 1

    def stats(self) -> Dict[str, int]:
        """One JSON-safe snapshot of the store's vitals (round 12):
        the engine's end-of-run host-cache ledger reads this, and it is
        the documented read surface for external tooling
        (docs/observability.md) — the per-wave hot path still reads
        ``.bytes`` directly, one attribute being cheaper than a dict
        per wave."""
        return {
            "entries": len(self._entries),
            "bytes": self._bytes,
            "bytes_peak": self.bytes_peak,
            "budget_bytes": self.budget_bytes,
            "puts": self.puts,
            "takes": self.takes,
            "drops": self.drops,
        }

    def audit(self) -> None:
        """Byte-accounting coherence: the running total equals the sum
        over live entries, and demotion markers track live keys only —
        asserted by the sanitizer's host-cache audit next to the
        tree/store key cross-check."""
        actual = sum(self._nbytes(p) for p in self._entries.values())
        if actual != self._bytes:
            raise AssertionError(
                f"host cache byte accounting diverged: tracked "
                f"{self._bytes}, live entries hold {actual}"
            )
        stray = self._demoted - set(self._entries)
        if stray:
            raise AssertionError(
                f"demotion markers for {len(stray)} dropped entr"
                "(y/ies) were never cleared"
            )
