"""Runtime entrypoints: execute a template's jax_xla block on this process's
devices. This is what runs inside the launched TPU pod (and, for local
shards, inside the LocalLauncher thread).

Flow: resolve model family → build mesh (declared parallelism when it tiles
the local device count, otherwise re-planned for the available devices — the
local/dry-run case) → init sharded train state → train or infer → return a
metrics dict (tokens/sec, MFU, loss history, …).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from nexus_tpu.api.runtime_spec import JaxXlaRuntime
from nexus_tpu.models.registry import get_family
from nexus_tpu.parallel.mesh import (
    MeshPlan,
    build_mesh,
    plan_for_devices,
)
from nexus_tpu.parallel.sharding import batch_spec
from nexus_tpu.train.checkpoint import make_checkpointer
from nexus_tpu.train.data import (
    Prefetcher,
    corpus_batches,
    synthetic_lm_batches,
    synthetic_mlp_batches,
)
from nexus_tpu.train.metrics import (
    mfu,
    model_flops_per_token,
)
from nexus_tpu.train.trainer import (
    Trainer,
    build_optimizer,
    init_train_state,
    make_train_step,
)

logger = logging.getLogger("nexus_tpu.runtime")


def _resolve_mesh(runtime: JaxXlaRuntime, devices: Optional[Sequence] = None):
    devices = list(devices) if devices is not None else jax.devices()
    plan = MeshPlan.from_parallelism(runtime.parallelism)
    if plan.total() != len(devices):
        logger.info(
            "declared parallelism %s targets %d chips but %d devices are "
            "local; re-planning for local execution",
            plan.shape, plan.total(), len(devices),
        )
        plan = plan_for_devices(len(devices))
        return build_mesh(plan, devices)
    # multislice: lay slice boundaries onto the outermost (DCN-tolerant)
    # axes — build_mesh reads real slice_index attributes when the backend
    # exposes them, and n_slices drives the same hybrid layout under the
    # CPU emulation (slice-contiguous process blocks)
    return build_mesh(plan, devices, n_slices=runtime.tpu.slice_count)


def run_template_runtime(
    runtime: JaxXlaRuntime,
    devices: Optional[Sequence] = None,
    max_steps: Optional[int] = None,
    cancel=None,
    heartbeat=None,
    restore_step: Optional[int] = None,
    serve_replica_id: str = "",
) -> Dict[str, Any]:
    """Execute a runtime block; returns a JSON-serializable metrics dict.

    ``cancel``: a utils.signals.CancelToken — set on SIGTERM (slice
    preemption); training stops at the next step boundary with a final
    checkpoint so the requeued job resumes (``cancel.hard`` skips the
    final save — the chaos "kill worker" / no-grace preemption path).

    ``heartbeat``: step-boundary liveness callback (the failover lease
    renewer — ha/lease.py); called with the host-side completed-step count.

    ``restore_step``: pin the resume point to an exact durable checkpoint
    step (the failover planner's restore-step annotation → the
    materializer's ``NEXUS_RESTORE_STEP`` env) instead of latest.

    ``serve_replica_id``: this engine's fleet replica identity (the
    controller's replica-homes placement → the materializer's
    ``NEXUS_SERVE_REPLICA_ID`` env) — tags the serve engine's live
    gauges ``engine:<id>``. Empty for single-home serving."""
    family = get_family(runtime.model.family)
    overrides = dict(runtime.model.overrides)
    # train.remat is the spec-level knob; model.overrides.remat (with
    # remat_policy) is the fine-grained one and wins when both are set
    # (mlp has no remat — its two layers don't warrant recompute)
    if (
        runtime.train.remat
        and runtime.model.family != "mlp"
        and "remat" not in overrides
    ):
        overrides["remat"] = True
    mesh = _resolve_mesh(runtime, devices)
    if (
        dict(mesh.shape).get("sequence", 1) > 1
        and runtime.model.family != "mlp"
        and "attn_impl" not in overrides
    ):
        # a sequence mesh axis means context parallelism: attention must be
        # the ring kernel (exact over sequence shards) unless overridden
        overrides["attn_impl"] = "ring"
    if runtime.model.family == "mixtral" and "dispatch_impl" not in overrides:
        # MoE dispatch auto-resolution: scatter where it was measured —
        # a single-device program (2.45× at step level, docs/PERF.md) —
        # and einsum's known-good SPMD partitionings on ANY sharded mesh
        # (EP or not: a sharded scatter's layout is compiler-dependent
        # and unprofiled multi-chip). An explicit dispatch_impl override
        # always wins.
        overrides["dispatch_impl"] = (
            "scatter" if mesh.devices.size == 1 else "einsum"
        )
    cfg = family.config(runtime.model.preset, **overrides)
    n_devices = mesh.devices.size

    if runtime.mode == "infer":
        return _run_infer(runtime, family, cfg, mesh)
    if runtime.mode == "serve":
        # the serve engine honors the same liveness/cancel contract as
        # training: heartbeat at wave boundaries (→ hb-serve-<template>
        # lease), cancel → drain at the next boundary (failover requeue)
        return _run_serve(
            runtime, family, cfg, mesh, cancel=cancel, heartbeat=heartbeat,
            replica_id=serve_replica_id,
        )
    return _run_train(
        runtime, family, cfg, mesh, n_devices, max_steps, cancel,
        heartbeat=heartbeat, restore_step=restore_step,
    )


def _schedule_bubble(schedule: str, n_micro: int, n_stages: int) -> float:
    """Idle fraction the pipeline schedule imposes (schedule arithmetic,
    not a measurement): 1F1B runs M + 2S - 2 fwd+bwd ticks for M
    microbatches of work; GPipe 2*(M + S - 1) half-ticks for 2M halves."""
    if schedule == "1f1b":
        return (2 * n_stages - 2) / (n_micro + 2 * n_stages - 2)
    return (n_stages - 1) / (n_micro + n_stages - 1)


def _run_train(runtime, family, cfg, mesh, n_devices, max_steps, cancel=None,
               heartbeat=None, restore_step=None):
    tr = runtime.train
    steps = min(tr.steps, max_steps) if max_steps else tr.steps
    optimizer = build_optimizer(
        learning_rate=tr.learning_rate,
        warmup_steps=tr.warmup_steps,
        total_steps=steps,
        weight_decay=tr.weight_decay,
    )
    key = jax.random.PRNGKey(tr.seed)

    n_stages = dict(mesh.shape).get("pipeline", 1)
    rules = None
    if n_stages > 1:
        # Pipeline parallelism (VERDICT r1 item 3): layers shard over the
        # 'pipeline' mesh axis from init (each stage holds its contiguous
        # layer slice) and the loss routes through the configured schedule —
        # 1F1B by default (stage-bounded activation memory), GPipe as the
        # autodiff-scheduled fallback (parallel/pipeline.py).
        from nexus_tpu.parallel.pipeline import (
            PIPELINE_FAMILIES,
            pipeline_1f1b_loss_and_grads,
            pipeline_loss,
        )
        from nexus_tpu.parallel.sharding import DEFAULT_LOGICAL_RULES

        schedule = runtime.parallelism.pipeline_schedule
        pp_families = PIPELINE_FAMILIES[schedule]
        if runtime.model.family not in pp_families:
            raise ValueError(
                f"pipeline parallelism ({schedule}) supports the "
                f"{'/'.join(pp_families)} families "
                f"(got {runtime.model.family!r})"
            )
        if tr.gradient_accumulation > 1:
            raise ValueError(
                "gradient_accumulation > 1 with pipeline > 1 is not "
                "supported: the GPipe schedule already microbatches; use "
                "parallelism.pipelineMicrobatches"
            )
        if cfg.n_layers % n_stages:
            raise ValueError(
                f"n_layers {cfg.n_layers} not divisible by "
                f"{n_stages} pipeline stages"
            )
        dp = dict(mesh.shape).get("data", 1) * dict(mesh.shape).get("fsdp", 1)
        n_micro = runtime.parallelism.pipeline_microbatches
        if n_micro <= 0:
            # auto: the largest feasible microbatch count up to 2× stages
            # (more microbatches → smaller GPipe bubble)
            feasible = [
                m
                for m in range(min(2 * n_stages, tr.batch_size), 0, -1)
                if tr.batch_size % m == 0 and (tr.batch_size // m) % dp == 0
            ]
            n_micro = feasible[0] if feasible else n_stages
            if n_micro < n_stages:
                logger.warning(
                    "pipeline auto-microbatching degenerated to %d "
                    "microbatches for %d stages (batchSize=%d, dp=%d): "
                    "stages will idle %d%% of each step; raise batchSize or "
                    "set parallelism.pipelineMicrobatches",
                    n_micro, n_stages, tr.batch_size, dp,
                    round(100 * _schedule_bubble(
                        runtime.parallelism.pipeline_schedule,
                        n_micro, n_stages,
                    )),
                )
        if tr.batch_size % n_micro or (tr.batch_size // n_micro) % dp:
            raise ValueError(
                f"batchSize {tr.batch_size} must split into {n_micro} "
                f"pipeline microbatches whose size tiles the data axes ({dp})"
            )
        rules = dict(DEFAULT_LOGICAL_RULES, layer="pipeline")

    logical_tree = family.logical_axes(cfg)
    if n_stages > 1:
        # Layer-stacked params shard over 'pipeline' ONLY, exactly matching
        # pipeline_apply's shard_map in_specs (P('pipeline')) — specs that
        # promise replication on other dims would force a per-step weight
        # all-gather inside the GPipe scan. Embed/lm_head sit outside the
        # shard_map and keep their fsdp/tensor sharding under plain SPMD.
        logical_tree = jax.tree_util.tree_map(
            lambda dims: ("layer",) + (None,) * (len(dims) - 1)
            if dims and dims[0] == "layer"
            else dims,
            logical_tree,
            is_leaf=lambda x: isinstance(x, tuple),
        )

    with mesh:
        state = init_train_state(
            lambda: family.init(key, cfg),
            optimizer,
            mesh=mesh,
            logical_tree=logical_tree,
            rules=rules,
        )
        # NOTE: the (B, S+1) token batch itself stays unsharded on the
        # sequence axis (S+1 doesn't tile it); with attn_impl="ring" the
        # per-layer shard_map in_specs reshard activations onto it
        loss_fn = grads_fn = None
        if n_stages > 1 and runtime.parallelism.pipeline_schedule == "1f1b":
            fam_name = runtime.model.family

            def grads_fn(params, batch):
                loss, metrics, grads = pipeline_1f1b_loss_and_grads(
                    fam_name, params, cfg, batch, mesh, n_micro
                )
                return grads, metrics
        elif n_stages > 1:
            loss_fn = lambda params, batch: pipeline_loss(
                runtime.model.family, params, cfg, batch, mesh, n_micro
            )
        else:
            loss_fn = lambda params, batch: family.loss_fn(params, cfg, batch)
        step_fn = make_train_step(
            loss_fn, optimizer, mesh=mesh,
            grad_accum=tr.gradient_accumulation, grads_fn=grads_fn,
        )

        # batchSize is GLOBAL (across all processes/hosts): each process
        # assembles batch_size/process_count local rows and the Prefetcher
        # stitches them into one globally-sharded array
        # (make_array_from_process_local_data). tokens_per_batch therefore
        # stays global and tokens/sec/chip divides by global device count —
        # unambiguous multi-host accounting (VERDICT r1 weak #8).
        procs = jax.process_count()
        if tr.batch_size % procs:
            raise ValueError(
                f"train.batchSize {tr.batch_size} is global and must be "
                f"divisible by the process count {procs}"
            )
        if procs > 1 and runtime.data.prefetch <= 0:
            # the prefetcher is where process-local rows become ONE global
            # sharded array (make_array_from_process_local_data); without it
            # each process would feed its local array as if global — silent
            # wrong results or a collective hang
            raise ValueError(
                "multi-process training requires data.prefetch >= 1 "
                "(the prefetcher assembles the global batch across hosts)"
            )
        local_batch = tr.batch_size // procs
        if runtime.model.family == "mlp":
            data = synthetic_mlp_batches(
                local_batch, cfg.in_dim, cfg.out_dim,
                seed=tr.seed + jax.process_index(),
            )
            tokens_per_batch = 0
        elif runtime.data.kind == "tokens":
            data = corpus_batches(
                runtime.data.path,
                local_batch,
                tr.seq_len,
                dtype=runtime.data.dtype,
                seed=tr.seed,
                shard_index=jax.process_index(),
                num_shards=procs,
                vocab_size=cfg.vocab_size,
            )
            tokens_per_batch = tr.batch_size * tr.seq_len
        else:
            data = synthetic_lm_batches(
                local_batch, tr.seq_len, cfg.vocab_size,
                seed=tr.seed + jax.process_index(),
            )
            tokens_per_batch = tr.batch_size * tr.seq_len
        prefetcher = None
        if runtime.data.prefetch > 0:
            # device_put in the prefetch thread overlaps H2D transfer with
            # the device step; sharding matches make_train_step's batch spec
            batch_sharding = NamedSharding(mesh, batch_spec())
            data = prefetcher = Prefetcher(
                data, depth=runtime.data.prefetch, sharding=batch_sharding
            )

        checkpointer = None
        start_step = 0
        if runtime.checkpoint.enabled and runtime.checkpoint.directory:
            checkpointer = make_checkpointer(
                runtime.checkpoint.directory, keep=runtime.checkpoint.keep,
                fmt=runtime.checkpoint.format,
            )
            if runtime.checkpoint.resume and checkpointer.latest_step() is not None:
                # restore_step pins the resume point to an exact durable
                # step (the failover planner's choice); default is latest
                state = checkpointer.restore(state, step=restore_step)
                start_step = int(state.step)
                logger.info("resumed from checkpoint step %d", start_step)

        # heartbeat steps must be GLOBAL (comparable with checkpoint step
        # numbers — failover_steps_lost subtracts them): the Trainer only
        # knows its run-local completed count, so offset by the resume point
        hb = heartbeat
        if heartbeat is not None and start_step:
            hb = lambda completed: heartbeat(start_step + completed)  # noqa: E731

        prof = runtime.profile
        trainer = Trainer(
            step_fn,
            state,
            data,
            tokens_per_batch=tokens_per_batch,
            checkpointer=checkpointer,
            checkpoint_interval=runtime.checkpoint.interval_steps
            if checkpointer
            else 0,
            profile_dir=prof.directory if prof.enabled else "",
            profile_start=prof.start_step,
            profile_steps=prof.num_steps,
            cancel=cancel,
            # dispatch-depth override for profiling sweeps
            # (tools/sweep_levers.py); unset → Trainer's platform default
            run_ahead=int(os.environ.get("NEXUS_RUN_AHEAD", "0") or 0)
            or None,
            on_step=hb,
        )
        try:
            # 2 untimed warmup steps: the first execution is the compile, and
            # the second still pays one-time program-load/cache effects on
            # the remote-tunnel TPU path — with short bench runs (15 steps)
            # either one inside the timed window visibly skews tokens/sec.
            # Clamped so ultra-short runs still time at least one step.
            n_run = max(steps - start_step, 1)
            result = trainer.run(n_run, warmup_steps=min(2, n_run - 1))
        finally:
            if prefetcher is not None:
                prefetcher.close()
        checkpoint_saved = False
        if checkpointer is not None:
            if getattr(cancel, "hard", False):
                # hard kill (chaos / no-grace preemption): no final save —
                # recovery must come from the last INTERVAL checkpoint, the
                # case the failover steps_lost metric measures
                checkpointer.close()
            else:
                # final save — doubles as the preemption save when the run
                # was interrupted (resume point for the rescheduled pod)
                jax.block_until_ready(trainer.state)
                checkpointer.save(trainer.state, wait=True)
                checkpointer.close()
                checkpoint_saved = True

    metrics: Dict[str, Any] = {
        "mode": "train",
        "family": runtime.model.family,
        "preset": runtime.model.preset,
        "steps": result.steps,
        "final_loss": result.final_metrics.get("loss"),
        "loss_history": result.loss_history[:64],
        "steps_per_sec": result.steps_per_sec,
        "tokens_per_sec": result.tokens_per_sec,
        "n_devices": n_devices,
        "resumed_from_step": start_step,
        "interrupted": result.interrupted,
        "checkpoint_saved": checkpoint_saved,
    }
    if n_stages > 1:
        metrics["pipeline_schedule"] = runtime.parallelism.pipeline_schedule
        metrics["pipeline_microbatches"] = n_micro
        metrics["pipeline_schedule_bubble_fraction"] = round(
            _schedule_bubble(
                runtime.parallelism.pipeline_schedule, n_micro, n_stages
            ),
            4,
        )
    if result.profiled:
        metrics["profile_dir"] = runtime.profile.directory
    elif runtime.profile.enabled and runtime.profile.directory:
        steps_run = max(steps - start_step, 1)  # what trainer.run() received
        logger.warning(
            "profiling was enabled but the capture window never opened "
            "(start_step=%d >= %d timed steps this run)",
            runtime.profile.start_step, max(steps_run - 1, 0),
        )
    if hasattr(cfg, "dispatch_impl"):
        # the RESOLVED MoE dispatch (auto → scatter/einsum off the mesh)
        metrics["moe_dispatch"] = cfg.dispatch_impl
    if hasattr(cfg, "param_count"):
        fpt = model_flops_per_token(cfg, tr.seq_len)
        metrics["param_count"] = cfg.param_count()
        metrics["tokens_per_sec_per_chip"] = result.tokens_per_sec / n_devices
        metrics["model_flops_per_token"] = fpt
        metrics["mfu"] = mfu(result.tokens_per_sec, fpt, n_chips=n_devices)
    return metrics


def _load_infer_params(runtime, family, cfg, mesh):
    """Params for inference, by precedence:
      1. ``model.weights`` — a pretrained HF safetensors checkpoint,
         converted + placed shard-by-shard (runtime/weights.py; the
         literal "Llama-3-8B inference" path, BASELINE config #3);
      2. the template's Orbax checkpoint block (train -> checkpoint ->
         infer roundtrip);
      3. fresh random init (timing runs).

    Params-only restore for (2): the checkpoint's own metadata supplies
    the optimizer-state skeleton, so the infer template does NOT need to
    repeat the training run's hyperparameters (a warmup schedule changes
    the opt_state pytree; mismatches used to fail the restore)."""
    w = runtime.model.weights
    if w is not None and w.path:
        from nexus_tpu.runtime.weights import load_pretrained

        params = load_pretrained(
            runtime.model.family, w.path, cfg,
            mesh=mesh, logical_tree=family.logical_axes(cfg),
        )
        logger.info(
            "inference params converted from %s checkpoint %s",
            w.format, w.path,
        )
        return params, True, -1
    key = jax.random.PRNGKey(runtime.train.seed)
    ck = runtime.checkpoint
    checkpointer = None
    if ck.enabled and ck.directory:
        # restore is layout-sniffed ("auto"): an infer template must load
        # whatever format the training run actually wrote
        checkpointer = make_checkpointer(ck.directory, keep=ck.keep, fmt="auto")
        if checkpointer.latest_step() is None:
            checkpointer = None
    if checkpointer is None:
        params = jax.jit(lambda: family.init(key, cfg))()
        return params, False, -1

    step = checkpointer.latest_step()
    params = checkpointer.restore_params(
        _sharded_abstract_params(family, cfg, mesh, key), step=step
    )
    checkpointer.close()
    logger.info("inference params restored from checkpoint step %d", step)
    return params, True, step


def _sharded_abstract_params(family, cfg, mesh, key):
    """Abstract param structs carrying the family's FSDP/TP shardings —
    the restore target for params-only checkpoint loads."""
    from nexus_tpu.parallel.sharding import sharding_tree

    abstract = jax.eval_shape(lambda: family.init(key, cfg))
    spec_tree = sharding_tree(family.logical_axes(cfg), mesh)
    return jax.tree_util.tree_map(
        lambda st, sh: jax.ShapeDtypeStruct(st.shape, st.dtype, sharding=sh),
        abstract,
        spec_tree,
    )


def _load_draft_params(runtime, draft_family, draft_cfg, mesh, key,
                       ck_dir=None):
    """Draft weights for speculative decoding: params-only restore from
    ``ck_dir`` (defaults to ``infer.draftCheckpointDirectory``; the
    serve path passes ``serve.draftCheckpointDirectory``) when set (the
    checkpoint's own metadata supplies the rest of the restore
    skeleton, so the draft may have been trained with ANY optimizer
    schedule), else random init. Returns (params, loaded)."""
    if ck_dir is None:
        ck_dir = runtime.infer.draft_checkpoint_directory
    if ck_dir:
        import os

        # existence probe BEFORE constructing a (writable) Checkpointer: a
        # typo'd path must not be mkdir'd, and a read-only inference mount
        # must reach the random-init fallback rather than an OSError
        if os.path.isdir(ck_dir):
            checkpointer = make_checkpointer(ck_dir, fmt="auto")
            step = checkpointer.latest_step()
            if step is not None:
                params = checkpointer.restore_params(
                    _sharded_abstract_params(
                        draft_family, draft_cfg, mesh, key
                    ),
                    step=step,
                )
                checkpointer.close()
                logger.info(
                    "draft params restored from %s step %d", ck_dir, step
                )
                return params, True
            checkpointer.close()
        logger.warning(
            "infer.draftCheckpointDirectory %s has no checkpoint; the "
            "draft runs with RANDOM weights (acceptance will be ~0)",
            ck_dir,
        )
    return jax.jit(lambda: draft_family.init(key, draft_cfg))(), False


def _run_infer(runtime, family, cfg, mesh):
    """Timed autoregressive decode (BASELINE config #3): load weights, shard
    the KV cache (kv-heads over 'tensor', batch over 'data'/'fsdp'), decode
    ``infer.max_new_tokens`` new tokens ``infer.iterations`` timed times."""
    gen = getattr(family, "generate", None)
    if gen is None:
        raise ValueError(
            f"model family {runtime.model.family!r} does not support "
            "mode='infer' (no generate()); use mode='train'"
        )
    import time

    tr = runtime.train  # batch + seed
    inf = runtime.infer
    # resolve the draft model up front: the speculation cache spans BOTH
    # models, so shape clamps must respect min(target, draft) context
    draft_family = draft_cfg = None
    if inf.draft is not None:
        from nexus_tpu.models.registry import get_family as _get_family

        draft_family = _get_family(inf.draft.family)
        draft_cfg = draft_family.config(
            inf.draft.preset, **dict(inf.draft.overrides)
        )
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                "speculative draft must share the target vocab: "
                f"{draft_cfg.vocab_size} != {cfg.vocab_size}"
            )
    ctx = cfg.max_seq_len if draft_cfg is None else min(
        cfg.max_seq_len, draft_cfg.max_seq_len
    )
    prompt_len = min(inf.prompt_length, ctx - 1)
    # the speculative paths (draft model OR prompt lookup) need
    # num_speculative+1 scratch slots past the last committed token (one
    # overshooting round) — reserve them here so a cache-filling config
    # doesn't fail only when speculation is enabled
    speculating = inf.draft is not None or inf.prompt_lookup_ngram > 0
    reserve = (inf.num_speculative + 1) if speculating else 0
    max_new = min(inf.max_new_tokens, ctx - prompt_len - reserve)
    if max_new <= 0:
        raise ValueError(
            f"infer shapes don't fit: prompt {prompt_len} + new tokens "
            f"{inf.max_new_tokens}"
            + (f" + speculation reserve {reserve}" if reserve else "")
            + f" vs effective max_seq_len {ctx}"
        )
    key = jax.random.PRNGKey(tr.seed)
    # literal text prompt: tokenized with the checkpoint's own tokenizer,
    # broadcast across the batch (same prompt each row)
    tokenizer = None
    w = runtime.model.weights
    if inf.prompt and w is not None and w.tokenizer:
        from nexus_tpu.utils.tokenizer import load_tokenizer

        tokenizer = load_tokenizer(w.tokenizer)
    elif inf.prompt:
        raise ValueError(
            "infer.prompt (text) requires model.weights.tokenizer "
            "(a tokenizer.json path) so it can be tokenized"
        )
    # tokenize + validate fit BEFORE loading any weights: a prompt that
    # doesn't fit must fail in milliseconds, not after minutes of
    # checkpoint conversion/placement
    ids = None
    if tokenizer is not None:
        ids = tokenizer.encode(inf.prompt)
        if not ids:
            raise ValueError("infer.prompt tokenized to zero tokens")
    elif inf.prompt_token_ids:
        # explicit ids (no tokenizer) — natural-text prompts for the
        # speculation benches (e.g. a slice of the training corpus)
        ids = [int(t) for t in inf.prompt_token_ids]
        bad = [t for t in ids if not 0 <= t < cfg.vocab_size]
        if bad:
            raise ValueError(
                f"infer.promptTokenIds outside vocab {cfg.vocab_size}: "
                f"{bad[:5]}"
            )
    if ids is not None:
        ids = ids[: ctx - 1]
        prompt_len = len(ids)
        max_new = min(inf.max_new_tokens, ctx - prompt_len - reserve)
        if max_new <= 0:
            raise ValueError(
                f"infer prompt ({prompt_len} tokens) leaves no room "
                f"for new tokens within max_seq_len {ctx}"
            )
    with mesh:
        params, weights_loaded, restored_step = _load_infer_params(
            runtime, family, cfg, mesh
        )
        if ids is not None:
            prompt = jnp.broadcast_to(
                jnp.asarray(ids, dtype=jnp.int32)[None, :],
                (tr.batch_size, prompt_len),
            )
        else:
            prompt = jax.random.randint(
                key, (tr.batch_size, prompt_len), 0, cfg.vocab_size,
                dtype=jnp.int32,
            )
        # cache layout (L, B, S, Hkv, D): batch over data axes, kv heads
        # over the tensor axis — decode attention then runs tensor-parallel
        # with zero cache resharding. Axes that don't tile the dim (small
        # decode batches, few kv heads) fall back to replication.
        shape = dict(mesh.shape)
        dp, d_only = shape["data"] * shape["fsdp"], shape["data"]
        if dp > 1 and tr.batch_size % dp == 0:
            batch_axes = ("data", "fsdp")
        elif d_only > 1 and tr.batch_size % d_only == 0:
            batch_axes = "data"
        else:
            batch_axes = None
        tp = shape["tensor"]

        def _cache_sharding_for(n_kv_heads):
            kv_axis = "tensor" if tp > 1 and n_kv_heads % tp == 0 else None
            return NamedSharding(
                mesh, P(None, batch_axes, None, kv_axis, None)
            )

        cache_sharding = _cache_sharding_for(cfg.n_kv_heads)
        sampling = dict(cache_sharding=cache_sharding)
        if inf.temperature > 0:
            sampling.update(
                temperature=inf.temperature, key=jax.random.fold_in(key, 7)
            )
        if inf.stop_token_id >= 0 and not speculating:
            # the EOS FREEZE is plain-decode only (the speculative loops
            # have their own commit structure); the completion-TEXT trim
            # below applies to all paths — greedy speculative output
            # equals plain greedy, so the trimmed text is identical
            sampling["stop_token_id"] = inf.stop_token_id

        spec_extra = {}
        if inf.prompt_lookup_ngram > 0:
            # draft-free speculation: n-gram copying from the committed
            # text proposes, the target verifies (greedy-exact); no draft
            # weights, no draft cache
            from nexus_tpu.models.decoding import prompt_lookup_generate

            spec_extra = {
                "speculative": True,
                "speculative_kind": "prompt_lookup",
                "prompt_lookup_ngram": inf.prompt_lookup_ngram,
                "num_speculative": inf.num_speculative,
            }

            def gen(params, cfg, prompt, max_new, **kw):
                return prompt_lookup_generate(
                    family.forward_decode, params, cfg, prompt, max_new,
                    num_speculative=inf.num_speculative,
                    ngram=inf.prompt_lookup_ngram,
                    cache_sharding=kw.get("cache_sharding"),
                )
        elif inf.draft is not None:
            # speculative decoding: draft weights from its checkpoint (or
            # random init for timing runs). Batched — each row accepts its
            # own prefix length per round (vector-length caches); greedy
            # by default, rejection-sampled when temperature > 0
            from nexus_tpu.models.decoding import speculative_generate

            draft_params, draft_loaded = _load_draft_params(
                runtime, draft_family, draft_cfg, mesh,
                jax.random.fold_in(key, 99),
            )
            spec_extra = {
                "draft_weights_loaded": draft_loaded,
                "speculative": True,
                "draft_family": inf.draft.family,
                "draft_preset": inf.draft.preset,
                "num_speculative": inf.num_speculative,
            }

            def gen(params, cfg, prompt, max_new, **kw):
                # returns (tokens, stats) — pure, so it stays jit-safe;
                # the timing loop below unpacks it
                return speculative_generate(
                    family.forward_decode, params, cfg,
                    draft_family.forward_decode, draft_params, draft_cfg,
                    prompt, max_new,
                    num_speculative=inf.num_speculative,
                    cache_sharding=kw.get("cache_sharding"),
                    # the draft's kv-head count may not tile the tensor
                    # axis even when the target's does (cross-family
                    # drafts) — its cache gets its own layout
                    draft_cache_sharding=_cache_sharding_for(
                        draft_cfg.n_kv_heads
                    ),
                    temperature=kw.get("temperature", 0.0),
                    key=kw.get("key"),
                )

        spec_stats = {}

        def run_once():
            res = gen(params, cfg, prompt, max_new, **sampling)
            if spec_extra:  # speculative gen returns (tokens, stats)
                res, stats = res
                spec_stats.update(stats)  # scalars; last timed run wins
            return res

        from nexus_tpu.utils.hw import sync_host

        out = run_once()  # compile + warm
        sync_host(out)
        times = []
        for _ in range(max(1, inf.iterations)):
            t0 = time.monotonic()
            out = run_once()
            # close the window with a host fetch: block_until_ready alone
            # is unreliable on the axon platform (docs/PERF.md)
            sync_host(out)
            times.append(time.monotonic() - t0)
    new_tokens = tr.batch_size * max_new
    best = min(times)
    if spec_extra:
        rounds = int(spec_stats.get("rounds", 0) or 0)
        drafted = int(spec_stats.get("drafted", 0) or 0)
        accepted = int(spec_stats.get("accepted", 0) or 0)
        spec_extra.update(
            rounds=rounds,
            acceptance_rate=round(accepted / drafted, 4) if drafted else 0.0,
            # target forwards per committed token PER ROW: the speedup
            # driver (1.0 == plain greedy; lower is better). Each round is
            # one batched target forward, so the per-row basis is max_new —
            # dividing by batch*max_new would claim a batch-size 'speedup'
            # that plain greedy decoding gets identically
            target_forwards_per_token=round(
                (rounds + 1) / max(max_new, 1), 4
            ),
        )
        if "lookup_hits" in spec_stats:  # prompt-lookup: rows-with-match
            spec_extra["lookup_hit_rounds"] = int(spec_stats["lookup_hits"])
    text_extra = {}
    if tokenizer is not None:
        import numpy as _np

        new_ids = [int(t) for t in _np.asarray(out)[0, prompt_len:]]
        text_extra = {
            "prompt_tokens": prompt_len,
            "completion": _decode_completion(
                tokenizer, new_ids, inf.stop_token_id
            ),
        }
    return {
        **spec_extra,
        **text_extra,
        "mode": "infer",
        "family": runtime.model.family,
        "preset": runtime.model.preset,
        "weights_loaded": weights_loaded,
        "restored_step": restored_step,
        "decode_tokens_per_sec": new_tokens / best,
        "decode_tokens_per_sec_mean": new_tokens * len(times) / sum(times),
        "iteration_seconds": [round(t, 4) for t in times],
        "batch_size": tr.batch_size,
        "prompt_len": prompt_len,
        "new_tokens": max_new,
        "n_devices": mesh.devices.size,
    }


def _decode_completion(tokenizer, new_ids, stop_token_id: int) -> str:
    """Generated ids -> text, trimmed at the first stop token (shared by
    the infer `completion` and serve `completions` fields so their EOS
    semantics cannot drift apart)."""
    if stop_token_id >= 0 and stop_token_id in new_ids:
        new_ids = new_ids[: new_ids.index(stop_token_id)]
    return tokenizer.decode(new_ids)


def _run_serve(runtime, family, cfg, mesh, cancel=None, heartbeat=None,
               replica_id=""):
    """Continuous-batching serving (mode='serve'): a synthetic request
    queue — deterministic from train.seed — decodes through
    runtime/serving.py's fixed-row engine; finished rows are refilled
    between chunks. Weights load exactly like mode='infer' (checkpoint or
    safetensors). The headline metrics are aggregate tokens/sec and
    slot utilization under uneven request lengths — the two numbers
    static batching sacrifices.

    ``heartbeat`` renews the engine's liveness lease at wave boundaries
    (the launcher names it ``hb-serve-<template>``); ``cancel`` drains
    the engine at the next boundary with committed tokens preserved —
    the serve-failover requeue path (ha/serve_failover.py)."""
    if getattr(family, "forward_decode", None) is None:
        raise ValueError(
            f"model family {runtime.model.family!r} does not support "
            "mode='serve' (no forward_decode incremental path); "
            "use mode='train'"
        )
    import numpy as _np

    from nexus_tpu.runtime.serving import (
        STATUS_OK,
        ServeRequest,
        ServingEngine,
    )
    from nexus_tpu.utils.telemetry import percentile_nearest_rank

    sv = runtime.serve
    tr = runtime.train
    pmax = min(sv.prompt_length_max, cfg.max_seq_len // 2)
    pmin = min(sv.prompt_length_min, pmax)
    # resolve the serve draft model up front (mirrors _run_infer): a
    # bad draft spec must fail before any weights load, and the vocab
    # check is a hard engine precondition (acceptance compares token ids)
    draft_family = draft_cfg = None
    if sv.draft is not None:
        from nexus_tpu.models.registry import get_family as _get_family

        draft_family = _get_family(sv.draft.family)
        draft_cfg = draft_family.config(
            sv.draft.preset, **dict(sv.draft.overrides)
        )
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                "speculative serve draft must share the target vocab: "
                f"{draft_cfg.vocab_size} != {cfg.vocab_size}"
            )
        if draft_cfg.max_seq_len < cfg.max_seq_len:
            # the engine runs the draft cache at the TARGET's max_len
            # (the infer path clamps to min(target, draft) instead) —
            # a shorter draft would propose garbage past its rope range
            raise ValueError(
                "speculative serve draft must cover the target "
                f"context: draft max_seq_len {draft_cfg.max_seq_len} < "
                f"target {cfg.max_seq_len} (override the draft's "
                "max_seq_len)"
            )
    # literal prompts: tokenize BEFORE loading weights (a prompt that
    # doesn't fit must fail fast), mirroring _run_infer's ordering
    tokenizer = None
    literal_ids = []
    if sv.prompts:
        w = runtime.model.weights
        if w is None or not w.tokenizer:
            raise ValueError(
                "serve.prompts requires model.weights.tokenizer"
            )
        from nexus_tpu.utils.tokenizer import load_tokenizer

        tokenizer = load_tokenizer(w.tokenizer)
        for i, text in enumerate(sv.prompts):
            ids = tokenizer.encode(text)
            if not ids:
                raise ValueError(f"serve.prompts[{i}] tokenized to zero tokens")
            # the engine's own rule: budget = max_len - 1 - p - slack,
            # rejected when < 1 — fail fast on exactly that boundary
            # (slack > chunk under prompt-lookup speculation)
            if len(ids) > cfg.max_seq_len - 2 - sv.serve_slack():
                raise ValueError(
                    f"serve.prompts[{i}] ({len(ids)} tokens) leaves no "
                    f"decode budget within max_seq_len {cfg.max_seq_len}"
                )
            literal_ids.append(ids)
    with mesh:
        params, weights_loaded, restored_step = _load_infer_params(
            runtime, family, cfg, mesh
        )
        rng = _np.random.RandomState(tr.seed)
        requests = []
        trace = None
        if literal_ids:
            for i, ids in enumerate(literal_ids):
                requests.append(ServeRequest(
                    prompt=ids,
                    max_new_tokens=sv.max_new_max,
                    temperature=sv.temperature,
                    seed=i,
                    deadline_s=sv.request_deadline_s,
                ))
        elif sv.arrival != "closed":
            # open-loop trace-driven load (round 16): synthesize the
            # versioned arrival trace from the template seed and STREAM
            # it into the running engine — queue time and the goodput
            # ledger anchor at trace arrival, not serve() entry
            from nexus_tpu.runtime.traffic import synthesize_trace

            prefix_tokens = (
                min(sv.shared_prefix_length, max(1, pmax - 2))
                if sv.shared_prefix_length > 0
                else min(32, max(1, pmin))
            )
            tail_tokens = max(4, min(16, pmax - prefix_tokens))
            # feasibility at the spec boundary, mirroring the literal-
            # prompt check: multi-turn histories accrete the prior
            # turns' completions, so the WORST trace prompt must still
            # leave decode budget
            worst = prefix_tokens + tail_tokens
            if sv.trace_multi_turn_frac > 0:
                worst += (sv.trace_turns - 1) * (
                    sv.max_new_max + tail_tokens
                )
            elif sv.trace_branch_frac > 0:
                worst += sv.max_new_max + tail_tokens
            if worst > cfg.max_seq_len - 2 - sv.serve_slack():
                raise ValueError(
                    f"serve.arrival trace's worst prompt ({worst} "
                    f"tokens across {sv.trace_turns} turns) leaves no "
                    f"decode budget within max_seq_len "
                    f"{cfg.max_seq_len}; shrink sharedPrefixLength / "
                    "maxNewMax / traceTurns"
                )
            trace = synthesize_trace(
                name=f"serve-{sv.arrival}-{tr.seed}",
                seed=tr.seed,
                vocab_size=cfg.vocab_size,
                requests=sv.num_requests,
                duration_s=sv.arrival_duration_s,
                arrival=sv.arrival,
                burst_duty=sv.arrival_burst_duty,
                n_prefixes=sv.trace_prefix_pool,
                zipf_a=sv.trace_zipf_a,
                prefix_tokens=prefix_tokens,
                tail_tokens=tail_tokens,
                max_new_tokens=sv.max_new_max,
                multi_turn_frac=sv.trace_multi_turn_frac,
                turns=sv.trace_turns,
                think_s=sv.trace_think_s,
                branch_frac=sv.trace_branch_frac,
                fanout=sv.trace_fanout,
                temperature=sv.temperature,
            )
        else:
            # sharedPrefixLength: one common preamble (system-prompt
            # shape), drawn once, heads every synthetic prompt — the
            # shared-prefix serving workload the prefix cache dedupes.
            # Drawn FIRST so the 0-length default consumes exactly the
            # rng stream the PR 2 queue did (deterministic replays).
            common = None
            if sv.shared_prefix_length > 0:
                common = rng.randint(
                    0, cfg.vocab_size,
                    size=min(sv.shared_prefix_length, max(0, pmax - 1)),
                ).astype(_np.int32)
            for _ in range(sv.num_requests):
                p = int(rng.randint(pmin, pmax + 1))
                n = int(rng.randint(sv.max_new_min, sv.max_new_max + 1))
                ids = rng.randint(
                    0, cfg.vocab_size, size=p
                ).astype(_np.int32)
                if common is not None:
                    s = min(len(common), p - 1)
                    ids[:s] = common[:s]
                requests.append(ServeRequest(
                    prompt=ids.tolist(),
                    max_new_tokens=n,
                    temperature=sv.temperature,
                    seed=len(requests),  # per-request stream, deterministic
                    deadline_s=sv.request_deadline_s,
                ))
        # serving cache layout mirrors the infer path: kv heads over the
        # tensor axis, rows over the data axes (replicated when they don't
        # tile) — without this the 8B example's multi-GB cache replicates
        # per chip and OOMs a v5e
        shape = dict(mesh.shape)
        dp, d_only = shape["data"] * shape["fsdp"], shape["data"]
        if dp > 1 and tr.batch_size % dp == 0:
            batch_axes = ("data", "fsdp")
        elif d_only > 1 and tr.batch_size % d_only == 0:
            batch_axes = "data"
        else:
            batch_axes = None
        tp = shape["tensor"]
        kv_axis = "tensor" if tp > 1 and cfg.n_kv_heads % tp == 0 else None
        if sv.kv_block_size > 0:
            # paged pool layout (L, num_blocks, block_size, Hkv, D): any
            # row can read any block, so the pool axis stays unsharded —
            # only kv heads ride the tensor axis (batch sharding of a
            # shared pool would make every gather a cross-chip reshuffle)
            cache_sharding = NamedSharding(
                mesh, P(None, None, None, kv_axis, None)
            )
        else:
            cache_sharding = NamedSharding(
                mesh, P(None, batch_axes, None, kv_axis, None)
            )
        draft_kw = {}
        if draft_family is not None:
            # the draft rides a DENSE cache (runtime/serving.py): kv
            # heads over tensor when they tile, rows over the data axes
            d_kv_axis = (
                "tensor" if tp > 1 and draft_cfg.n_kv_heads % tp == 0
                else None
            )
            draft_params, draft_loaded = _load_draft_params(
                runtime, draft_family, draft_cfg, mesh,
                jax.random.fold_in(jax.random.PRNGKey(tr.seed), 99),
                ck_dir=sv.draft_checkpoint_directory,
            )
            draft_kw = dict(
                draft_forward=draft_family.forward_decode,
                draft_params=draft_params,
                draft_cfg=draft_cfg,
                draft_cache_sharding=NamedSharding(
                    mesh, P(None, batch_axes, None, d_kv_axis, None)
                ),
            )
        # NEXUS_SERVE_TRACE=<path>: attach a span tracer to this run
        # and persist the timeline dump as JSON — the entrypoint-level
        # hook for `tools/trace_summary.py <path>` without code changes
        # (nexus_tpu/obs/; flight recorder and live gauges ride the
        # engine defaults)
        trace_path = os.environ.get("NEXUS_SERVE_TRACE", "").strip()
        tracer = None
        if trace_path:
            from nexus_tpu.obs import ServeTracer

            tracer = ServeTracer()
        def make_engine(gauge_tags=None, engine_tracer=None):
            return ServingEngine(
                family.forward_decode, params, cfg,
                tracer=engine_tracer,
                batch_size=tr.batch_size,
                max_len=cfg.max_seq_len,
                stop_token_id=sv.stop_token_id,
                chunk=sv.chunk,
                cache_sharding=cache_sharding,
                lookup_ngram=sv.prompt_lookup_ngram,
                num_speculative=sv.num_speculative,
                **draft_kw,
                prefill_chunk=sv.prefill_chunk,
                kv_block_size=sv.kv_block_size,
                # the ONE sizing formula validate()'s HBM gate also
                # uses — pool capacity and admission can't drift from
                # the spec
                kv_num_blocks=sv.kv_pool_blocks(
                    tr.batch_size, cfg.max_seq_len
                ),
                prefix_cache=sv.prefix_cache,
                max_queue_depth=sv.max_queue_depth,
                max_queue_delay_s=sv.max_queue_delay_s,
                attention_path=sv.attention_path,
                admission_policy=sv.admission_policy,
                admission_aging_waves=sv.admission_aging_waves,
                # tiered KV cache (round 10): the quantized block pool
                # and the host-RAM spill tier under it
                kv_pool_dtype=sv.kv_pool_dtype,
                host_cache_bytes=sv.host_cache_bytes,
                host_cache_dtype=sv.host_cache_dtype,
                gauge_tags=gauge_tags,
            )

        if sv.replicas > 1:
            # fleet serving (round 14, docs/fleet.md): N engine
            # replicas — each its own rows + pool, the in-template
            # stand-in for N placed shards — behind the prefix-affinity
            # router; served deterministically (thread-free), with the
            # template's heartbeat renewed at every replica's wave
            # boundaries and the fleet-aggregate ledger returned
            from nexus_tpu.fleet import (
                PrefixAffinityRouter,
                serve_fleet_local,
            )

            if trace is not None:
                # the in-template fleet drive is one-shot and
                # deterministic (thread-free); it cannot pace a live
                # stream, so the trace replays as a closed queue in
                # arrival order with the ARRIVAL stamps kept — queue
                # time still anchors at trace arrival. True open-loop
                # streaming acts on the single-engine template path
                # and the ServeFleet live harness (docs/fleet.md).
                logger.warning(
                    "serve.arrival=%s with replicas=%d: the template "
                    "fleet drive replays the trace as a closed queue "
                    "(arrival-stamped); live streaming needs the "
                    "ServeFleet harness", sv.arrival, sv.replicas,
                )
                requests = trace.to_requests(
                    deadline_s=sv.request_deadline_s, arrivals=True,
                )
            engines = {
                f"r{i}": make_engine(gauge_tags=[f"engine:r{i}"])
                for i in range(sv.replicas)
            }
            fleet_router = PrefixAffinityRouter(
                list(engines),
                # affinity hashes radix chain keys; the dense layout
                # has no blocks, so hash at the default paged width
                block_size=sv.kv_block_size or 32,
                affinity_depth=sv.affinity_depth,
                spill_candidates=sv.spill_candidates,
                spill_threshold=sv.spill_threshold,
                policy=sv.router_policy,
                seed=tr.seed,
            )
            # fleet observability (round 15): the local drive stitches
            # cross-replica journeys (one per request, journey ids
            # stamped by the planner) and records the route decision
            # log — the per-replica span files of round 12 are
            # superseded by ONE journey dump per run (request spans
            # from every replica, stitched; tools/trace_summary.py
            # renders it)
            results, metrics = serve_fleet_local(
                engines, fleet_router, requests,
                cancel=cancel, heartbeat=heartbeat,
            )
            if sv.autoscale_min:
                # the in-template drive serves one fixed batch queue to
                # completion, so declared autoscale bounds cannot act
                # here — they drive the supervised live harness
                # (nexus_tpu/fleet/ServeFleet; docs/fleet.md). Label
                # it loudly so capacity config is never silently
                # ignored.
                logger.warning(
                    "serve.autoscaleMin/Max declared but the template "
                    "drive runs a fixed fleet of %d replicas; "
                    "autoscaling acts in the ServeFleet harness "
                    "(docs/fleet.md)", sv.replicas,
                )
                metrics["fleet_autoscale_active"] = False
        else:
            # a controller-materialized fleet replica runs ONE engine
            # per shard (replicas > 1 only multiplexes in-template):
            # its identity arrives via the caller (worker/launcher) or
            # NEXUS_SERVE_REPLICA_ID and tags the live gauges
            # engine:<id> — the per-replica signal the fleet
            # router/autoscaler read across the fleet
            replica_id = (replica_id or os.environ.get(
                "NEXUS_SERVE_REPLICA_ID", ""
            )).strip()
            engine = make_engine(
                gauge_tags=[f"engine:{replica_id}"] if replica_id
                else None,
                engine_tracer=tracer,
            )
            if trace is not None:
                # stream the trace into the RUNNING engine: requests
                # admit as their wall-clock arrivals come due, and the
                # queue/ttft/goodput ledger anchors at trace arrival
                from nexus_tpu.runtime.traffic import TraceSource

                source = TraceSource(
                    trace, deadline_s=sv.request_deadline_s,
                )
                results, metrics = engine.serve(
                    [], cancel=cancel, heartbeat=heartbeat,
                    source=source,
                )
                metrics = dict(metrics)
                metrics["arrival"] = sv.arrival
                metrics["trace_version"] = trace.version
                metrics["trace_events"] = len(trace)
            else:
                results, metrics = engine.serve(
                    requests, cancel=cancel, heartbeat=heartbeat,
                )
            if replica_id:
                metrics = dict(metrics)
                metrics["serve_replica_id"] = replica_id
        # fleet obs dumps ride the metrics as FULL structures — summarize
        # them in the returned dict (the worker prints it as JSON) and
        # persist the structures themselves next to NEXUS_SERVE_TRACE
        journey_dump = metrics.pop("journeys", None)
        fleet_log_dump = metrics.pop("fleet_decision_log", None)
        if journey_dump is not None:
            metrics["fleet_journeys"] = len(journey_dump["journeys"])
        if fleet_log_dump is not None:
            metrics["fleet_decision_events"] = (
                fleet_log_dump["events_recorded"]
            )
        if trace_path:
            import json as _json

            # single-engine runs dump the span timeline; fleet runs
            # dump the stitched journey file (+ <path>.fleetlog.json,
            # the decision audit) — trace_summary auto-detects all
            dumps = [(trace_path, tracer.to_dict())] if tracer else []
            if journey_dump is not None:
                dumps = [(trace_path, journey_dump)]
            if fleet_log_dump is not None:
                dumps.append(
                    (f"{trace_path}.fleetlog.json", fleet_log_dump)
                )
            for path_, dump_ in dumps:
                try:
                    with open(path_, "w") as f:
                        _json.dump(dump_, f, indent=1)
                        f.write("\n")
                except OSError:  # telemetry is best-effort
                    pass
    finished = sum(1 for r in results if r is not None)
    # the latency rollups describe SERVED requests only — shed and
    # deadline-missed terminals would flatter the p50 with their
    # near-zero "latencies" (and an all-shed round reports NO rollup at
    # all rather than a perfect one)
    latencies = sorted(
        r.latency_s for r in results
        if r is not None and r.status == STATUS_OK
    )
    p50 = latencies[len(latencies) // 2] if latencies else 0.0
    p95 = percentile_nearest_rank(latencies, 0.95)
    text_extra = {}
    if tokenizer is not None:
        text_extra = {"completions": [
            _decode_completion(
                tokenizer,
                list(res.tokens[len(req_ids):]) if res else [],
                sv.stop_token_id,
            )
            for req_ids, res in zip(literal_ids, results)
        ]}
    out = {
        **metrics,
        **text_extra,
        "mode": "serve",
        "family": runtime.model.family,
        "preset": runtime.model.preset,
        "weights_loaded": weights_loaded,
        "restored_step": restored_step,
        "finished_requests": finished,
        "batch_rows": tr.batch_size,
        "n_devices": mesh.devices.size,
    }
    if draft_family is not None:
        out["draft_family"] = sv.draft.family
        out["draft_preset"] = sv.draft.preset
        out["draft_weights_loaded"] = draft_loaded
    if latencies:  # omitted when nothing was served (all shed/expired)
        out["request_latency_p50_s"] = round(p50, 4)
        out["request_latency_p95_s"] = round(p95, 4)
    return out
