"""Continuous-batching serving engine (BASELINE config #3).

Static-batch decode (``autoregressive_generate``) holds every sequence
until the LAST one finishes: a batch mixing a 10-token reply with a
1000-token reply wastes ~half its step-slots, and new requests wait for
the whole batch to drain. This engine serves a REQUEST QUEUE through a
fixed-shape decode batch instead — iteration-level scheduling with
CHUNKED PREFILL:

  * the KV cache runs VECTOR lengths (per-row depths, the same
    models/decoding.py scaffold that batched speculation uses), so every
    row decodes at its own position with its own causal mask and rows
    never interact;
  * the cache is PAGED by default (kv_block_size > 0): K/V live in a
    static block pool read through per-row block tables, a host-side
    free-list allocator (BlockAllocator) maps blocks lazily as rows
    grow, and admission is HBM-AWARE — a request enters only when the
    pool can reserve its prompt + budget + slack in blocks (refundable
    headroom; eviction-free by construction), so admitted residency
    tracks actual sequence lengths instead of batch × max_len worst
    cases. One compiled program still serves every table state;
  * blocks are SHARED ACROSS REQUESTS (prefix cache, round 6; RADIX
    TREE + cache-aware admission, round 9): the allocator is
    ref-counted and carries a radix-tree content index of full-block
    hash chains (runtime/prefix_cache.py), admission matches each
    prompt's longest cached prefix — at ANY branching point, and
    through chains extended by a finished request's DECODED blocks, so
    multi-turn successors hit their prior turn's whole chain — and
    starts chunked prefill past it (skipping the shared region's
    compute AND K/V writes), full-prompt hits copy-on-write the tail
    block, released blocks park (refcount 0, LRU) for future hits
    until pool pressure evicts them LEAF-FIRST (a shared interior run
    outlives its cold tails), and the wait queue is ordered by a
    pluggable admission policy (runtime/scheduling.py; default:
    longest-resident-match-first with FIFO aging);
  * prompts are NOT prefilled in a separate dispatch. Admission writes
    the prompt into a per-row token buffer (one tiny scatter), and the
    decode chunk program itself streams it through the model at
    ``prefill_chunk`` tokens per step for that row while every other
    row keeps committing decode tokens — prefill never serializes with
    decode, the round-3 limitation this design replaces (the old
    bucketed-prefill engine measured 16 rows SLOWER than 4 because each
    admission stalled all rows for a full prompt forward + dispatch;
    docs/PERF.md "serve-row-scaling"). The mechanism is the per-row
    ``n_valid`` feed width of ``generic_forward_decode``: each step
    feeds a (B, T) window where decode rows carry 1 real token and
    admitting rows carry up to T prompt tokens — the extra slots ride
    the same weight reads a 1-token step already pays for (decode is
    HBM-bound on parameters, so a modest T is nearly free on TPU);
  * decode runs in chunks of ``chunk`` steps under one dispatch
    (``lax.scan``), the host inspects the emitted tokens at chunk
    boundaries — the scheduling granularity / dispatch overhead
    trade-off. Finished rows inside a chunk roll their cache pointer
    back each step (their write is overwritten next step), so a drained
    row idles safely at fixed depth regardless of how long it stays
    empty.

Exactness contract: a request's output is a function of the request
alone — never of its row, its batch co-residents, the engine's batch
size, or the prefill chunking. At temperature 0 that is EXACTLY the
model's greedy decode of the prompt in isolation (tests/test_serving.py
proves it against ``autoregressive_generate`` row for row — chunked
prefill computes each prompt query over the same keys with the same
mask as a monolithic prefill, so the numbers are identical); at
temperature > 0 the sampling key is (request seed, buffer position), so
the sampled stream is reproducible and batch-invariant (also tested).
Continuous batching changes only WHEN work is scheduled, never what is
computed.

TPU-shaped: ONE compiled decode-chunk program and ONE tiny insert
program for the whole serve loop (static shapes) — no per-prompt-length
bucket compiles, no admission-time forwards. Both cache layouts serve:
the int8 cache (cfg.kv_cache_quantized) rides the same scaffold —
chunked prefill means admission never touches K/V, so the scale planes
need no insert-time handling (the surface that blocked int8 serving in
the bucketed-prefill design).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from nexus_tpu.models.decoding import (
    constrain_kv_sharding,
    copy_kv_blocks,
    gather_kv_block,
    init_kv_cache,
    init_paged_kv_cache,
    write_kv_blocks,
)
from nexus_tpu.obs.gauges import LiveGauges
from nexus_tpu.obs.profiling import dispatch_annotation
from nexus_tpu.obs.recorder import FlightRecorder
from nexus_tpu.runtime.host_cache import (
    HOST_CACHE_DTYPES,
    HostBlockStore,
    dequantize_kv_host,
)
from nexus_tpu.runtime.prefix_cache import PrefixCacheIndex, chain_keys
from nexus_tpu.runtime.scheduling import make_admission_policy
from nexus_tpu.utils.telemetry import percentile_nearest_rank  # noqa: F401
# ^ re-exported: the nearest-rank helper moved to utils/telemetry.py in
# PR 12 (one shared estimator for the engine, the bench harness, and
# the obs layer's rolling gauges); existing importers keep working.

#: serve-level KV pool dtypes (ServeSpec.kvPoolDtype): "native" stores
#: K/V at the model dtype, "int8" runs the quantized block pool (the
#: int8-KV decode tier models/decoding.py already dequantizes in both
#: the fused and gather kernels) — roughly double the resident blocks
#: per HBM byte
KV_POOL_DTYPES = ("native", "int8")


class BlockAllocator:
    """Host-side REF-COUNTED free-list allocator over the paged KV pool.

    Reservation-based and EVICTION-FREE for admitted rows: ``admit``
    succeeds only when the pool can promise a row's whole worst-case
    PRIVATE block count up front (its prompt past any shared prefix plus
    its trimmed decode budget plus the dispatch slack — the refundable
    headroom), so an admitted row can ALWAYS grow to its cap without
    touching anyone else's blocks. Physical blocks are drawn lazily
    against that reservation (``_BlockLease.grow_to``, once per
    dispatch), so pool RESIDENCY tracks actual sequence lengths; the
    headroom a row never materializes — and everything it did — returns
    at ``release`` (stop-token finishes refund their unused budget).

    Round 6 adds CROSS-REQUEST SHARING: every mapped block carries a
    refcount (one per lease mapping it), and an optional content index
    (``prefix_index``, runtime/prefix_cache.py) lets admission map
    already-written prompt blocks into a new row instead of reserving
    fresh ones (``match_prefix`` → ``admit(shared=...)``). A released
    block whose content is indexed is PARKED (refcount 0, LRU) rather
    than freed; parked blocks are reclaimed lazily — LRU-first, and only
    under pool pressure (the free list running dry mid-``grow_to``) —
    so cached prefixes survive exactly as long as the pool has room.

    Round 10 adds the HOST TIER: with a ``host_cache``
    (runtime/host_cache.py) attached, pool pressure DEMOTES the
    eviction victim instead of destroying it — the engine-supplied
    ``spill_fn`` downloads the block's K/V planes, the store keeps them
    under the block's chain digest (byte-budgeted; over-budget drains
    leaf-first through the tree so store and tree never disagree), and
    the radix entry is marked *spilled*. ``match_prefix`` then reports
    the spilled span after the resident one, and ``admit(restore=...)``
    PROMOTES it: each spilled digest gets a freshly-allocated pool
    block (refcount 1, rebound in the tree) that the engine uploads the
    host copy into — the warm prefix swaps back instead of being
    recomputed.

    Invariant: ``len(_free) + parked >= _reserved`` at all times
    (admission gates on ``available_blocks`` and counts the parked
    blocks it revives plus the spilled blocks it restores), which is
    why an in-reservation ``grow_to`` can never fail mid-generation and
    eviction can only ever see refcount-0 blocks."""

    def __init__(self, num_blocks: int, block_size: int,
                 prefix_index: Optional[PrefixCacheIndex] = None,
                 host_cache: Optional[HostBlockStore] = None):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if host_cache is not None and prefix_index is None:
            raise ValueError(
                "a host cache needs the prefix index (spilled state "
                "lives in the radix tree)"
            )
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # pop() from the tail → blocks hand out in ascending id order
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._ref = [0] * self.num_blocks  # leases mapping each block
        self._reserved = 0  # promised to admitted rows, not yet allocated
        self.index = prefix_index
        self.host_cache = host_cache
        # engine-wired download: (block id, chain digest) → numpy plane
        # dict (the device half of a spill); spills are DISABLED until
        # set
        self.spill_fn: Optional[Callable[[int, bytes], dict]] = None
        self.peak_allocated = 0
        self.evictions = 0
        self.spills = 0  # evictions demoted to the host tier
        self.restores = 0  # spilled blocks promoted back into the pool
        self.host_evictions = 0  # spilled entries dropped (host budget)

    @property
    def scratch_block(self) -> int:
        """The one pool block the allocator NEVER hands out: the cache
        carries ``num_blocks + 1`` physical blocks and by convention the
        last one (id == ``num_blocks``) is scratch — unmapped table
        tails and released rows point there, so frozen-slot writes land
        harmlessly and the fused kernel's stale-entry redirect has a
        fixed target (ops/attention.py reads it as pool id N-1)."""
        return self.num_blocks

    def audit_scratch_tails(self, table, mapped_counts) -> None:
        """The unmapped-tail contract, asserted (NEXUS_SANITIZE path):
        every table entry past a row's mapped block count MUST be the
        scratch block — "may point anywhere in range" is no longer
        tolerated, because a stale entry aliasing a block another row
        owns would be one mask bug away from cross-request K/V reads.
        ``table``: the (B, M) host table; ``mapped_counts``: per-row
        mapped block counts (0 for free rows)."""
        scratch = self.scratch_block
        for r, n in enumerate(mapped_counts):
            tail = table[r, n:]
            if tail.size and not (tail == scratch).all():
                bad = int(tail[(tail != scratch).argmax()])
                raise AssertionError(
                    f"block-table row {r}: unmapped tail entry points at "
                    f"block {bad}, not the scratch block {scratch} — the "
                    "allocator's scratch-tail contract is broken"
                )

    def blocks_for(self, positions: int) -> int:
        """Blocks covering ``positions`` cache slots."""
        return max(0, -(-int(positions) // self.block_size))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def cached_blocks(self) -> int:
        """Parked blocks: refcount 0, content indexed, LRU-evictable."""
        return self.index.parked_count if self.index is not None else 0

    @property
    def allocated_blocks(self) -> int:
        """Blocks some row actually maps (excludes parked cache — parked
        content is reclaimable, so it isn't residency a request holds)."""
        return self.num_blocks - len(self._free) - self.cached_blocks

    @property
    def available_blocks(self) -> int:
        """Blocks admissible to NEW rows (free plus evictable-cached,
        minus outstanding reservations — the admission gate's
        currency)."""
        return len(self._free) + self.cached_blocks - self._reserved

    @property
    def reserved_blocks(self) -> int:
        """Blocks promised to admitted rows but not yet materialized."""
        return self._reserved

    def pool_partition(self) -> dict:
        """The leak audit: every pool block is in exactly ONE of three
        states — free (on the free list), parked (refcount 0, content
        indexed), or allocated (mapped by >= 1 row). The three must
        partition ``num_blocks`` at all times; after every lease has
        released (drain, cancellation, completion) ``reserved`` must be
        0 and ``allocated`` must be 0 too — anything else is a leaked
        block. tests/test_serve_failover.py asserts this after
        kill-mid-decode chaos."""
        return {
            "free": self.free_blocks,
            "parked": self.cached_blocks,
            "allocated": self.allocated_blocks,
            "reserved": self._reserved,
            "total": self.num_blocks,
        }

    def match_prefix(self, keys, prompt_len: int):
        """Longest cached prefix of a prompt whose full-block hash chain
        is ``keys`` → ``(shared_blocks, spilled_keys, matched_len,
        cow_src)``: the RESIDENT pool blocks first, then the digests of
        the contiguous SPILLED span extending them (restorable from the
        host tier via ``admit(restore=...)``; always empty without
        one).

        ``matched_len`` covers both spans and is capped at
        ``prompt_len - 1``: the row must still run >= 1 prompt position
        through the model to produce its first token's logits. On a
        FULL-prompt hit (block-aligned prompt entirely cached) that cap
        lands inside the last matched block — a RESIDENT last block is
        returned as ``cow_src`` for the engine to COPY into a private
        block (copy-on-write) so recomputing position p-1 never writes
        into a block other rows read; a SPILLED last block is simply
        dropped from the span (the row re-prefills that one block — a
        restore-then-recompute-into-the-copy dance buys one block of
        prefill at two dispatches' cost)."""
        if self.index is None or not keys:
            return [], [], 0, None
        blocks, skeys = self.index.match_tiered(keys)
        if self.host_cache is None:
            skeys = []  # unrestorable without a store (never happens:
            # spilled entries only exist when a host cache is attached)
        if not blocks and not skeys:
            return [], [], 0, None
        total = len(blocks) + len(skeys)
        matched = total * self.block_size
        cow_src = None
        if matched > prompt_len - 1:
            if skeys:
                skeys = skeys[:-1]
                matched = (total - 1) * self.block_size
            else:
                cow_src = blocks[-1]
                blocks = blocks[:-1]
                matched = prompt_len - 1
        return blocks, skeys, matched, cow_src

    def admit(self, need_blocks: int, shared=(),
              restore=()) -> Optional["_BlockLease"]:
        """Reserve ``need_blocks`` private blocks for one row, map the
        ``shared`` (already-written, indexed) blocks into it with a
        refcount bump each, and PROMOTE the ``restore`` spilled digests
        — each gets a freshly-allocated pool block (refcount 1, rebound
        in the radix tree) appended to the lease's shared span in chain
        order; the ENGINE uploads the host payloads into those blocks
        before the next chunk reads them. None when the pool can't
        promise the privates plus the parked blocks this admission
        would revive plus the restored blocks it must materialize (the
        caller keeps the request queued — a refusal stops the admission
        wave, so the refused request waits for refunds rather than
        being overtaken within the policy's order, whatever ordering
        the engine's admission policy chose). Nothing is mutated on
        refusal."""
        revive = sum(1 for b in shared if self._ref[b] == 0)
        if need_blocks + revive + len(restore) > self.available_blocks:
            return None
        for b in shared:
            if self._ref[b] == 0:
                self.index.unpark(b)  # leaves the evictable LRU set
            self._ref[b] += 1
        restored = []
        payloads = []
        for key in restore:
            # shared refs are bumped FIRST, so the pressure this
            # allocation may exert (evict/spill of parked blocks) can
            # never touch the span being admitted; restored blocks are
            # referenced immediately, so neither can later restores.
            # The host payload leaves the store HERE — tree and store
            # transition together, whatever the caller does next.
            # drain=False: a spill inside THIS loop may push the store
            # over budget, and draining now could drop a digest later
            # in ``restore`` (it is still a spilled full leaf until its
            # turn comes) — the drain runs once at the end instead,
            # when every pending digest is resident.
            blk = self._take_block(drain=False)
            self._ref[blk] += 1
            self.index.restore(key, blk)
            payload, demoted = self.host_cache.take(key)
            restored.append(blk)
            payloads.append((blk, payload, demoted))
            self.restores += 1
            self.peak_allocated = max(
                self.peak_allocated, self.allocated_blocks
            )
        if restore:
            self._drain_host_budget()
        self._reserved += need_blocks
        lease = _BlockLease(self, need_blocks, list(shared) + restored)
        # (block, planes, demoted) per restored block — the engine
        # drains this into its upload wave before the next chunk reads
        lease.restored_payloads = payloads
        return lease

    def register_block(self, key: bytes, blk: int,
                       parent: Optional[bytes] = None) -> bool:
        """Publish a fully-written block into the content index,
        attached under ``parent`` (the preceding digest of its chain;
        None = a chain root). No-op (False) when the key is already
        held — first writer wins; the duplicate block stays a plain
        private block — or when the parent digest was evicted (the
        radix tree refuses orphans)."""
        if self.index is not None:
            return self.index.insert(key, blk, parent=parent)
        return False

    def _drain_host_budget(self) -> None:
        """Bring the host store back under its byte budget, dropping
        spilled entries leaf-first through the tree (store and tree
        transition together). Runs at OPERATION boundaries — never
        mid-``admit``, where a drain could drop a digest the admission
        is still about to restore (the store may transiently exceed its
        budget inside one operation; by the boundary every pending
        restore is resident and therefore undroppable)."""
        if self.host_cache is None:
            return
        while (self.host_cache.over_budget()
                and self.index.spilled_count):
            self.host_cache.drop(self.index.evict_spilled_lru())
            self.host_evictions += 1

    def _take_block(self, drain: bool = True) -> int:
        """One physical block off the free list — or, under pool
        pressure, reclaimed from the least-recently-used refcount-0
        cached block (the ONLY evictable kind by construction). With a
        host tier attached the victim is DEMOTED, not destroyed: its
        planes are downloaded through the engine's ``spill_fn``, stored
        under its chain digest, and the radix entry is marked spilled
        (still matchable, restorable on a future hit); over-budget
        store bytes drain leaf-first through the tree so the two stay
        in lockstep — deferred to the caller's boundary when
        ``drain=False`` (``admit``'s restore loop, whose pending
        digests must not be dropped out from under it). Same victim
        either way (one selection rule,
        ``PrefixCacheIndex._pop_victim``)."""
        if self._free:
            return self._free.pop()
        if self.host_cache is not None and self.spill_fn is not None:
            blk, key = self.index.spill_lru()
            self.host_cache.put(key, self.spill_fn(blk, key))
            self.spills += 1
            if drain:
                self._drain_host_budget()
        else:
            blk = self.index.evict_lru()
        self.evictions += 1
        return blk

    def _alloc_one(self) -> int:
        blk = self._take_block()
        self._ref[blk] += 1
        self._reserved -= 1  # reservation converts to allocation
        self.peak_allocated = max(self.peak_allocated, self.allocated_blocks)
        return blk

    def _deref(self, blk: int) -> None:
        """Drop one reference; the last one parks indexed content (kept
        for future prefix hits, LRU-evictable) and frees the rest."""
        self._ref[blk] -= 1
        if self._ref[blk] == 0:
            if self.index is not None and self.index.holds(blk):
                self.index.park(blk)
            else:
                self._free.append(blk)


class _BlockLease:
    """One admitted row's slice of the pool: the SHARED prefix blocks it
    maps read-only (refcounts held at admit), its private reservation,
    and the private blocks physically mapped so far — all in
    virtual-position order (entry i of ``blocks`` backs positions
    [i*block_size, (i+1)*block_size))."""

    def __init__(self, allocator: BlockAllocator, reservation: int,
                 shared=None):
        self._a = allocator
        self.reservation = int(reservation)  # PRIVATE blocks promised
        self.shared: List[int] = list(shared or [])
        self._private: List[int] = []
        self._released = False
        # (block, host planes, demoted) per block the admitting
        # allocator RESTORED from the host tier — the engine uploads
        # these before the row's first chunk reads them
        self.restored_payloads: List[tuple] = []

    @property
    def blocks(self) -> List[int]:
        """Full mapping: shared prefix first, then private growth."""
        return self.shared + self._private

    def grow_to(self, n_blocks: int) -> List[int]:
        """Ensure at least ``n_blocks`` TOTAL blocks are mapped (clamped
        to shared + reservation — by construction callers never need
        more) and return the full mapping."""
        if self._released:
            raise RuntimeError("grow_to on a released lease")
        n = min(int(n_blocks) - len(self.shared), self.reservation)
        while len(self._private) < n:
            self._private.append(self._a._alloc_one())
        return self.blocks

    def release(self) -> None:
        """Refund everything: one refcount per mapped block (shared and
        private — the allocator parks indexed content, frees the rest)
        plus the never-materialized headroom back to the admission
        budget."""
        if self._released:
            return
        self._released = True
        for b in self.shared + self._private:
            self._a._deref(b)
        self._a._reserved -= self.reservation - len(self._private)
        self.shared, self._private = [], []


# ---- terminal request statuses (ServeResult.status) ----
STATUS_OK = "ok"
STATUS_DEADLINE_EXCEEDED = "deadline_exceeded"
STATUS_SHED = "shed"
STATUS_FAILED_OVER = "failed_over"


@dataclass
class ServeRequest:
    """One queued generation request.

    ``temperature > 0`` samples instead of argmax. The sampling key for
    the token at buffer position ``pos`` is
    ``fold_in(fold_in(engine_base_key, seed), pos)`` — a function of the
    request alone, NOT of scheduling — so a request's output is
    identical whatever row it lands in, whoever its batch co-residents
    are, and whatever the engine's batch size is (the same
    batch-invariance contract as greedy, tested in test_serving.py).
    Plain temperature only (top-k/top-p truncation stays on the static
    path).

    Fault-tolerance fields (round 7): ``deadline_s`` > 0 bounds the
    request's total time from enqueue (engine start) — the engine checks
    it at every wave boundary, cancelling the row (or dropping the
    queued request) with a terminal ``deadline_exceeded`` status instead
    of serving a result nobody is waiting for. ``priority`` orders two
    things, consistently HIGH-IS-FAVORED (the fleet-level contract,
    normative in docs/fleet.md): (1) LOAD SHEDDING — when the bounded
    queue overflows, the LOWEST-priority queued request is shed first;
    (2) FLEET DISPATCH — the round-14 router
    (nexus_tpu/fleet/router.py) routes higher-priority requests first,
    so when load forces spill-over it is the low-priority tail that
    migrates off warm affinity homes. It does NOT order admission
    within one engine: that is the engine's ``admission_policy``
    (round 9 — the default ``cache-aware`` may admit a request with a
    resident prefix match ahead of older cold arrivals, bounded by
    ``admission_aging_waves``; ``fifo`` keeps strict arrival order).
    ``retries`` counts requeue migrations — engine-death failovers AND
    fleet scale-down drains (stamped by the ServeFailoverPlanner,
    echoed into the result).

    ``journey`` (round 15, nexus_tpu/obs/journey.py) is the request's
    FLEET-stable identity: stamped once by the failover planner at
    generation 0 (``j<queue index>``), carried verbatim through every
    drain/requeue, and threaded into each engine's ServeTracer — the
    key that stitches a request's per-engine span timelines across
    every replica it touched into one cross-replica journey. Empty on
    single-engine runs (nothing to stitch).

    ``arrival_s`` (round 16, open-loop serving) is WHEN the request
    actually arrived, in seconds relative to the serve() call's clock
    start: 0 (the default) means "existed when serve() began" — the
    closed-loop behavior, bit-identical to before. Streamed admission
    (``serve(source=...)``) stamps each request's trace arrival here;
    the fleet stamps the instant a request entered the fleet (so a
    request that waited in a replica inbox carries a NEGATIVE offset
    into the engine call that finally serves it). Latency attribution
    (``ServeResult.queue_s``/``latency_s``, the ttft rollup, and
    ``goodput_under_slo``) anchors at arrival, never at serve() entry;
    ``deadline_s``/``max_queue_delay_s`` count from arrival too (from
    engine start when the request predates the call) so an open-loop
    deadline budgets the request's OWN wait, not the stream's."""

    prompt: Sequence[int]
    max_new_tokens: int = 128
    temperature: float = 0.0
    seed: int = 0
    deadline_s: float = 0.0
    priority: int = 0
    retries: int = 0
    journey: str = ""
    arrival_s: float = 0.0


@dataclass
class ServeResult:
    """Completed request: prompt + generated ids (stop token included when
    one was hit), plus per-request timing anchored at request ARRIVAL
    (``ServeRequest.arrival_s``; serve() start for closed-loop queues,
    where every request arrives at t0 and nothing changes) —
    ``latency_s`` (arrival → finished), ``queue_s`` (arrival →
    admission: the wait the HBM-aware gate, prefix-aware deferral, and
    — under streamed admission — the request's own late arrival
    impose), and ``ttft_s`` (admission → first committed token: the
    prefill cost the user actually feels, observed at chunk granularity
    — the number prefix caching attacks directly; the METRICS rollup
    ``ttft_p50/p95_s`` is arrival-anchored instead, so open-loop
    first-token latency includes the queue wait honestly).

    ``status`` is the request's TERMINAL disposition — ``ok`` (served to
    completion), ``deadline_exceeded`` (cancelled at a wave boundary;
    ``tokens`` carries whatever was committed), ``shed`` (refused by the
    bounded queue — never admitted, zero compute spent), or
    ``failed_over`` (completed on a replacement engine after its first
    engine died; stamped by the ServeFailoverPlanner). ``retries`` is
    the number of engine-death requeues the request survived."""

    tokens: List[int]
    new_tokens: int
    finished_by_stop: bool
    latency_s: float
    ttft_s: float = 0.0
    queue_s: float = 0.0
    status: str = STATUS_OK
    retries: int = 0


@dataclass
class DrainedRequest:
    """One request drained off a cancelled/dead engine: its index into
    the serve() queue, the tokens it had committed before death (exact
    greedy/sampled prefix of its full completion — the engine commits at
    chunk granularity, so the snapshot is always token-consistent),
    whether it ever held a row, and how long the dead engine had already
    been serving (``elapsed_s`` — charged against the request's deadline
    on requeue, so engine deaths can't extend a deadline indefinitely).
    The ServeFailoverPlanner folds ``committed`` into the requeued
    prompt so a replacement engine never re-decodes recovered work."""

    request_idx: int
    committed: List[int] = field(default_factory=list)
    admitted: bool = False
    elapsed_s: float = 0.0


@dataclass
class _RowState:
    request_idx: int
    budget: int
    emitted: List[int] = field(default_factory=list)
    stopped: bool = False
    admitted_t: float = 0.0  # monotonic stamp at admission
    first_tok_t: float = 0.0  # monotonic stamp at first committed token


class ServingEngine:
    def __init__(
        self,
        forward_decode: Callable,
        params: Any,
        cfg: Any,
        batch_size: int,
        max_len: Optional[int] = None,
        stop_token_id: int = -1,
        chunk: int = 8,
        cache_sharding: Optional[Any] = None,
        sample_seed: int = 0,
        lookup_ngram: int = 0,
        num_speculative: int = 4,
        prefill_chunk: int = 8,
        kv_block_size: int = 32,
        kv_num_blocks: int = 0,
        prefix_cache: bool = True,
        max_queue_depth: int = 0,
        max_queue_delay_s: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
        attention_path: str = "fused",
        admission_policy: Any = "cache-aware",
        admission_aging_waves: int = 8,
        prefix_completions: bool = True,
        kv_pool_dtype: str = "native",
        host_cache_bytes: int = 0,
        host_cache_dtype: str = "native",
        draft_forward: Optional[Callable] = None,
        draft_params: Any = None,
        draft_cfg: Any = None,
        draft_cache_sharding: Optional[Any] = None,
        tracer: Any = None,
        flight_recorder: Any = None,
        live_gauges: bool = True,
        gauge_tags: Optional[Sequence[str]] = None,
        storm_threshold: int = 8,
    ):
        """``prefill_chunk`` (T): prompt tokens an admitting row consumes
        per decode step. A T-slot feed costs every row T slots of matmul
        work, but decode steps are parameter-read-bound, so small T is
        nearly free while prefilling a P-token prompt in ceil(P/T) steps
        instead of P (sweepable on-chip; T=1 degrades to pure
        teacher-forcing admission).

        ``lookup_ngram > 0`` switches the decode chunks to SPECULATIVE
        rounds: each round proposes ``num_speculative`` tokens by n-gram
        prompt lookup from the row's own committed text (the engine keeps
        a device-side token buffer per row), verifies them in ONE
        ``k+1``-wide target forward, and commits the accepted prefix —
        models/decoding.py's draft-free speculation running under
        continuous batching. Greedy-exact: outputs equal the plain
        engine's token for token (tested); a chunk runs
        ``ceil(chunk / (k+1))`` rounds so its committed-token budget
        matches a plain chunk's. Prefilling rows ride the same rounds:
        their (k+1)-wide verify window carries prompt tokens instead of
        proposals. Greedy only (requests with temperature > 0 are
        rejected at admission).

        ``kv_block_size > 0`` (the default) runs the PAGED KV cache: K/V
        live in a static pool of ``kv_num_blocks`` blocks of
        ``kv_block_size`` positions per layer, each row reading/writing
        through a block table (models/decoding.py). Admission becomes
        HBM-aware: a request is admitted only when the pool can reserve
        its prompt + trimmed budget + dispatch slack in blocks
        (refundable headroom — eviction-free by construction; see
        BlockAllocator), and blocks are mapped lazily as the row actually
        grows, so pool residency tracks real sequence lengths.
        ``kv_num_blocks = 0`` sizes the pool capacity-equivalent to the
        dense layout (batch × ceil(max_len/block) + scratch) — identical
        admission behavior, paged mechanics; pass a smaller pool to
        actually cap HBM (the serve entrypoint sizes it to the queue
        envelope). ``kv_block_size = 0`` keeps the legacy dense
        ``batch × max_len`` rows (the A/B baseline).

        ``prefix_cache`` (paged layout only) enables CROSS-REQUEST KV
        reuse: admission hashes each prompt's full blocks
        (runtime/prefix_cache.py), matches the longest cached prefix,
        maps the matched blocks into the new row's table with refcount
        bumps, and starts chunked prefill AT the matched length — both
        the prefill compute and the K/V writes for the shared region are
        skipped. A full-prompt hit copies the final cached block
        (copy-on-write) so recomputing the last position never mutates a
        block other rows read; released rows' indexed blocks are parked
        (refcount 0, LRU) and evicted only under pool pressure.
        Admission is prefix-AWARE: a request whose next needed block is
        being prefilled by an active row right now is deferred (other
        requests may overtake it) until the leader publishes, so a burst
        of same-prefix requests prefills its preamble ONCE and the
        followers then admit together in one wave. Sharing is pure
        bookkeeping — outputs are token-for-token identical to
        ``prefix_cache=False`` (tested across the fp, int8-KV, and
        speculative tiers).

        Round 9 upgrades the content index to a RADIX TREE over block
        digests (runtime/prefix_cache.py): branching prefixes (one
        system prompt, different few-shot tails) share the preamble
        subtree physically, eviction is leaf-first (a shared interior
        run outlives its cold tails), and — with
        ``prefix_completions`` (default on) — a finished row's DECODED
        blocks are registered into the tree at release, so a
        multi-turn successor (prompt = a prior request's full prompt +
        completion) matches the prior turn's whole chain instead of
        missing past its prompt. ``admission_policy`` selects the
        wait-queue ordering (runtime/scheduling.py): ``"cache-aware"``
        (default) admits the request with the longest RESIDENT prefix
        match first, with an aging bound of ``admission_aging_waves``
        passed-over waves so nothing starves; ``"fifo"`` is strict
        arrival order (the pre-round-9 behavior — identical to
        cache-aware whenever the cache is cold or off). An
        AdmissionPolicy instance can be passed directly (the pluggable
        scheduler interface). Ordering changes only WHEN a request is
        scheduled, never its tokens (tested).

        ``max_queue_depth`` (round 7) bounds the wait queue: past it the
        LOWEST-priority queued requests are shed with an honest ``shed``
        status instead of queuing forever (0 = unbounded — the pre-7
        behavior). ``max_queue_delay_s`` sheds any request that has
        waited unadmitted longer than this (0 = no bound). Both are
        policed at every wave boundary, never mid-dispatch. ``clock`` is
        injectable (the detector's pattern) so deadline/shed paths
        unit-test without sleeps.

        ``attention_path`` (round 8, paged layout only) selects how the
        decode programs read K/V through the block table:

          * ``"fused"`` (default) — the fused block-table kernel
            (ops/attention.py::fused_paged_decode_attention): stream
            over table slots with an online softmax, trip count bounded
            by the max valid-block count across rows — per-step traffic
            tracks actual depths, never the table width. The engine
            also runs the HYDRAGEN shared-prefix decomposition on top:
            at every wave boundary the host detects the longest run of
            leading table entries shared by ALL live rows (prefix-cache
            hits alias the same physical blocks, so same-preamble waves
            share trivially), and the kernel computes that prefix's
            attention once per wave with the rows' queries batched,
            per-row attention over only the private tails, and combines
            the two via log-sum-exp. The run length and shared ids are
            TRACED operands — waves with no shared run fall through to
            the plain fused loop inside the SAME compiled program (the
            recompile sanitizer gates this).
          * ``"gather"`` — the round-6 gather-then-attend reference
            (materializes the (B, M·Bs, ...) virtual view every step):
            kept as the parity oracle and the A/B baseline
            (`bench-serve` measures both).

        Outputs are token-for-token identical across both paths and the
        dense layout (tested across the fp / int8-KV / speculative
        tiers with the prefix cache on and off).

        ``kv_pool_dtype`` (round 10, paged layout only): ``"int8"``
        runs the QUANTIZED block pool — K/V stored int8 with
        per-(position, head) f32 scales, the same layout
        ``cfg.kv_cache_quantized`` selects (either switch works; the
        serve-level knob exists so a spec can halve its pool bytes
        without a model override) — roughly double the resident blocks
        per HBM byte, dequantized in-kernel by both attention paths.

        ``host_cache_bytes`` (round 10) attaches the HOST-RAM SPILL
        TIER under the paged pool: when pool pressure must reclaim a
        parked prefix block, its K/V planes are downloaded into a
        byte-budgeted host store (runtime/host_cache.py) and the radix
        entry is marked *spilled* instead of removed — admission then
        matches resident AND spilled spans, restores the spilled one
        through freshly-allocated blocks + ONE fixed-shape upload
        dispatch per wave, and starts chunked prefill past the whole
        restored span. The effective prefix cache is bounded by host
        RAM instead of the pool. 0 disables (the pre-round-10
        discard-on-evict behavior); requires the prefix cache (inert
        without it). ``host_cache_dtype="int8"`` DEMOTES fp payloads to
        int8 + scales on spill (~2x more spilled blocks per host byte,
        at the documented max|x|/254 per-element error — restores of an
        int8 pool are byte-identical, nothing to demote). With
        ``"native"`` every restore is byte-identical and the exactness
        contract extends verbatim: spill/restore is scheduling, never
        semantics (tested cache-on == cache-off across fused/gather ×
        fp/int8 pools).

        ``draft_forward``/``draft_params``/``draft_cfg`` (round 11)
        attach the DRAFT-MODEL speculation tier: each round a cheap
        draft proposes ``num_speculative`` tokens through its own dense
        KV cache (a k+1-step width-1 scan inside the same dispatch) and
        the target verifies the window exactly like the prompt-lookup
        tier — the two proposers share one verify seam, so the
        commit/rollback invariants (accepted tokens commit blocks,
        rejected ones rewind the lease pointer, a partially-rejected
        block is never published to the radix tree or host tier) exist
        once. The draft has no prefix cache: it teacher-forces each
        admitted prompt from position 0 at k+1 tokens per round, so
        after a prefix-cache hit it LAGS the target and catches up
        through the committed text (proposals are fallback-garbage
        until then — exactness never depends on them, tested).
        Mutually exclusive with ``lookup_ngram``; greedy-exact only;
        the draft must share the target's vocabulary.
        ``draft_cache_sharding`` pins the draft cache's layout
        (dense (L, B, S, Hkv, D)) on a sharded mesh.

        Observability (round 12, nexus_tpu/obs/): ``tracer`` — an
        optional :class:`~nexus_tpu.obs.trace.ServeTracer` recording a
        span timeline per request (enqueued → admitted → prefill
        chunks → decode waves → terminal, with per-span cache
        attribution); None (default) records nothing and costs one
        branch per site. ``flight_recorder`` — a
        :class:`~nexus_tpu.obs.recorder.FlightRecorder` ring of recent
        wave events, dumped when a sanitizer trips, a deadline/shed
        storm terminates >= ``storm_threshold`` requests at one wave
        boundary, or a cancellation drains the engine (the failover
        postmortem); None (default) creates a private recorder, False
        disables. ``live_gauges`` (default on) publishes queue depth /
        running rows / free pool blocks / host-tier bytes / rolling
        ttft & queue percentiles into the in-process telemetry
        registry at every wave boundary (statsd rides along only when
        an address is configured — off by default), tagged with
        ``gauge_tags``. All of it is host-side dataclass/dict
        bookkeeping — no JAX ops; the serve bench budgets the whole
        layer at <= 2% tok/s (docs/bench_serve_r12.json)."""
        self._fwd = forward_decode
        self._params = params
        self._cfg = cfg
        self._b = int(batch_size)
        self._max_len = int(max_len or cfg.max_seq_len)
        if self._max_len > cfg.max_seq_len:
            raise ValueError(
                f"max_len {self._max_len} exceeds model max_seq_len "
                f"{cfg.max_seq_len}"
            )
        self._stop = int(stop_token_id)
        self._chunk = int(chunk)
        self._cache_sharding = cache_sharding
        # On a sharded mesh, host-built arrays (admission waves, block
        # tables, per-chunk done masks) must enter every dispatch with
        # the SAME committed sharding as the steady-state values the jit
        # programs return, or each commitment flavor compiles its own
        # program — the silent-recompile leak the NEXUS_SANITIZE audit
        # caught on the 8-device mesh (3 decode programs instead of 1).
        # ``_mint`` commits them replicated on the cache's mesh.
        mesh = getattr(cache_sharding, "mesh", None)
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            self._host_sharding = NamedSharding(mesh, P())
            # normalize the caller's spec to jax's canonical form (trailing
            # None axes trimmed; all-None == P()) — the eager constraint on
            # a fresh cache and the sharding the jit programs RETURN must
            # compare EQUAL, or the first dispatch after every fresh cache
            # compiles its own program
            spec = getattr(cache_sharding, "spec", None)
            if spec is not None:
                entries = list(spec)
                while entries and entries[-1] is None:
                    entries.pop()
                self._cache_sharding = NamedSharding(mesh, P(*entries))
        else:
            self._host_sharding = None
        self._base_key = jax.random.PRNGKey(int(sample_seed))
        self._lookup = int(lookup_ngram)
        self._k = int(num_speculative)
        # ---- speculation tiers (one verify seam, two proposers) ----
        # prompt-lookup (lookup_ngram > 0): zero extra model — proposals
        # are n-gram copies of the row's own committed text; draft-model
        # (draft_forward set): a cheap model proposes k tokens per round
        # through its own dense KV cache. Either way the TARGET scores
        # the whole k+1 window in ONE dispatch through the block table
        # and rejected positions roll the lease pointer back.
        self._draft = draft_forward is not None
        self._draft_fwd = draft_forward
        self._draft_params = draft_params
        self._draft_cfg = draft_cfg
        self._draft_cache_sharding = draft_cache_sharding
        if self._draft and self._lookup:
            raise ValueError(
                "lookup_ngram and draft_forward are mutually exclusive "
                "(draft-free vs draft-model speculation — two proposers "
                "behind the same verify seam)"
            )
        if self._draft and draft_cfg is None:
            raise ValueError("draft_forward requires draft_cfg")
        if self._draft and (
            getattr(draft_cfg, "vocab_size", None)
            != getattr(cfg, "vocab_size", None)
        ):
            raise ValueError(
                "speculative draft must share the target vocab: "
                f"draft {getattr(draft_cfg, 'vocab_size', None)} != "
                f"target {getattr(cfg, 'vocab_size', None)}"
            )
        if self._draft and (
            int(getattr(draft_cfg, "max_seq_len", 0)) < self._max_len
        ):
            # the draft's dense cache runs the ENGINE's max_len (its
            # rope tables included) — a draft configured for fewer
            # positions would silently propose garbage past its range
            # (acceptance collapse, no error), the hazard the infer
            # path's min(target, draft) context clamp exists to prevent
            raise ValueError(
                "speculative draft must cover the serve context: "
                f"draft max_seq_len {getattr(draft_cfg, 'max_seq_len', 0)}"
                f" < engine max_len {self._max_len} (override the "
                "draft's max_seq_len or shrink max_len)"
            )
        self._spec = bool(self._lookup) or self._draft
        if self._spec and self._k < 1:
            raise ValueError(
                f"num_speculative must be >= 1, got {self._k}"
            )
        self._t = int(prefill_chunk)
        if self._t < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        self._max_queue_depth = int(max_queue_depth)
        if self._max_queue_depth < 0:
            raise ValueError(
                f"max_queue_depth must be >= 0, got {max_queue_depth}"
            )
        self._max_queue_delay = float(max_queue_delay_s)
        if self._max_queue_delay < 0:
            raise ValueError(
                f"max_queue_delay_s must be >= 0, got {max_queue_delay_s}"
            )
        self._clock = clock
        # NEXUS_SANITIZE arms the allocator's scratch-tail audit (the
        # unmapped-tail contract) alongside the conftest-installed
        # serve() wrappers — stdlib-only check, read once at build time
        from nexus_tpu.testing.sanitizers import sanitizers_enabled

        self._sanitize = sanitizers_enabled()
        # ---- observability (round 12, nexus_tpu/obs/) ----
        self._tracer = tracer
        if flight_recorder is False:
            self.flight_recorder = None
        else:
            self.flight_recorder = flight_recorder or FlightRecorder()
        self._live_gauges = bool(live_gauges)
        self._gauge_tags = list(gauge_tags or [])
        self._storm_threshold = int(storm_threshold)
        if self._storm_threshold < 1:
            raise ValueError(
                f"storm_threshold must be >= 1, got {storm_threshold}"
            )
        # the last drain/sanitizer/storm trip's snapshot (the failover
        # supervisor collects it into its report after engine death)
        self.last_flight_dump: Optional[dict] = None
        # drain snapshot of the last cancelled serve() run (engine death):
        # the ServeFailoverPlanner's input
        self.last_drain: Optional[List[DrainedRequest]] = None
        self._block_size = int(kv_block_size)
        if self._block_size < 0:
            raise ValueError(
                f"kv_block_size must be >= 0, got {kv_block_size}"
            )
        self._paged = self._block_size > 0
        if self._paged:
            # per-row virtual capacity in blocks (the block-table width)
            self._blocks_per_row = -(-self._max_len // self._block_size)
            # usable pool blocks; the cache carries ONE extra scratch
            # block (id == num_blocks) that the allocator never hands
            # out — unmapped table tails and released rows point there
            self._num_blocks = int(kv_num_blocks) or (
                self._b * self._blocks_per_row
            )
            if self._num_blocks < 1:
                raise ValueError(
                    f"kv_num_blocks must be >= 1, got {kv_num_blocks}"
                )
        else:
            self._blocks_per_row = 0
            self._num_blocks = 0
        # cross-request KV reuse rides the paged layout only (the dense
        # rows have no shareable unit)
        self._prefix = bool(prefix_cache) and self._paged
        if attention_path not in ("fused", "gather"):
            raise ValueError(
                f"attention_path must be 'fused' or 'gather', got "
                f"{attention_path!r}"
            )
        self._attn_path = attention_path
        # the fused kernel + Hydragen dispatch ride the paged layout
        # only (dense rows read a contiguous stripe — nothing to fuse)
        self._fused = self._paged and attention_path == "fused"
        # wait-queue ordering (runtime/scheduling.py): resolved once so
        # a bad name fails at construction, not mid-serve
        self._policy = make_admission_policy(
            admission_policy, aging_waves=admission_aging_waves
        )
        # decoded blocks enter the radix tree at row release (the
        # multi-turn surface); off = the round-6 prompt-only matcher,
        # kept as the bench A/B baseline
        self._prefix_completions = bool(prefix_completions)
        if kv_pool_dtype not in KV_POOL_DTYPES:
            raise ValueError(
                f"kv_pool_dtype must be one of {KV_POOL_DTYPES}, got "
                f"{kv_pool_dtype!r}"
            )
        if kv_pool_dtype == "int8" and not self._paged:
            raise ValueError(
                "kv_pool_dtype='int8' sizes the paged block pool; the "
                "dense layout quantizes via cfg.kv_cache_quantized"
            )
        self._kv_pool_int8 = kv_pool_dtype == "int8"
        self._host_cache_bytes = int(host_cache_bytes)
        if self._host_cache_bytes < 0:
            raise ValueError(
                f"host_cache_bytes must be >= 0, got {host_cache_bytes}"
            )
        if host_cache_dtype not in HOST_CACHE_DTYPES:
            raise ValueError(
                f"host_cache_dtype must be one of {HOST_CACHE_DTYPES}, "
                f"got {host_cache_dtype!r}"
            )
        self._host_cache_dtype = host_cache_dtype
        # the spill tier rides the radix tree (spilled state lives in
        # it), so it follows the prefix cache's paged-only inertness
        self._host_tier = self._prefix and self._host_cache_bytes > 0
        # restored blocks upload in fixed-width waves (one compiled
        # program; a wave with more restores than the width loops the
        # SAME program) — sized past the common case of every row
        # restoring a few blocks at once
        self._restore_wave = max(4, 2 * self._b)
        # rounds per dispatch: one round = one target forward committing
        # 1..k+1 tokens, so this keeps a spec chunk's committed-token
        # budget comparable to a plain chunk's C single-token steps
        self._rounds = max(1, -(-self._chunk // (self._k + 1)))
        # worst-case growth past a row's finish inside one dispatch: the
        # host only re-evaluates done-ness at chunk boundaries. The ONE
        # formula shared with ServeSpec.serve_slack() — spec validation
        # and the engine's admission rule can't diverge.
        from nexus_tpu.api.runtime_spec import serve_dispatch_slack

        self._slack = serve_dispatch_slack(
            self._chunk, self._lookup, self._k, draft=self._draft
        )

        cfg_ = cfg
        fwd = forward_decode
        C = self._chunk
        B = self._b
        max_len_ = self._max_len
        base_key = self._base_key
        use_fused = self._fused

        def _with_attn_operands(cache_in, shared_blocks, shared_table):
            """Thread the fused path's per-wave operands into the feed
            cache (consumed by the decode scaffold like ``n_valid``):
            the Hydragen shared-run length — a TRACED scalar, so a new
            run length is a new operand VALUE, never a new compile key —
            and the (M,) aliased leading block ids. The gather path
            passes neither and dispatches the round-6 gather read."""
            if use_fused:
                cache_in["shared_blocks"] = shared_blocks
                cache_in["shared_table"] = shared_table
            return cache_in

        def _strip_attn_keys(cache_out):
            """Normalize a family's returned cache: the scaffold consumes
            the fused operands, but stub families that pass unknown keys
            through must not change the scan carry structure."""
            return {
                k: v for k, v in cache_out.items()
                if k not in ("shared_blocks", "shared_table", "n_valid")
            }

        def _pick(logits_row, temp, seed, pos):
            """Per-row token choice: argmax at temp 0, else a categorical
            sample keyed by (request seed, absolute buffer position) —
            scheduling never enters the key, so sampling is
            batch-invariant."""
            key = jax.random.fold_in(jax.random.fold_in(base_key, seed), pos)
            safe_t = jnp.maximum(temp, 1e-6)
            sampled = jax.random.categorical(key, logits_row / safe_t)
            return jnp.where(
                temp > 0.0, sampled, jnp.argmax(logits_row, axis=-1)
            ).astype(jnp.int32)

        def _pick_wave(logits, temps, seeds, poss):
            """Batch token choice with an all-greedy fast path: the
            per-row threefry fold-ins + vocab-wide categorical draws are
            pure waste when NO request in the wave samples (the common
            serving case), so a scalar `lax.cond` skips them wholesale —
            measured ~10% of the narrow decode chunk at 16 rows on the
            CPU lane. Exact either way: greedy rows take the identical
            argmax inside the sampled branch's per-row `where`, so a
            sampled co-resident never changes a greedy row's tokens
            (batch-invariance, tested)."""
            return lax.cond(
                jnp.any(temps > 0.0),
                lambda: jax.vmap(_pick)(logits, temps, seeds, poss),
                lambda: jnp.argmax(logits, axis=-1).astype(jnp.int32),
            )

        def _make_decode_chunk(T):
            """Chunk program at feed width T: C steps in ONE dispatch;
            each step feeds a (B, T) window. Decode rows carry 1 real
            token (slot 0 = ``tok``), admitting rows carry up to T
            prompt tokens gathered from ``buf`` at ``ptr`` — the
            scaffold's per-row ``n_valid`` drops the padding slots' K/V
            writes and advances each row's cache depth by its real token
            count. ``done`` rows emit their held token and roll their
            pointer back each step (the write lands on the same slot
            next step — no growth, no overflow).

            TWO widths compile (T and 1): a T-slot feed costs every row
            T slots of attention/matmul work, so the host dispatches the
            wide program only while some row is actually prefilling and
            the pure-decode program the rest of the time (measured
            on-chip: the width-16 program more than tripled the plain
            decode step at 8 rows — docs/PERF.md round-4 serving).
            Either program is EXACT for any state (a prefilling row
            under the width-1 program just streams 1 token/step)."""

            def _decode_chunk(params, cache, tok, ptr, done, buf, plen,
                              temp, seed, shared_blocks, shared_table):
                def step(carry, _):
                    cache, tok, ptr = carry
                    prefilling = (ptr < plen) & ~done
                    n_valid = jnp.where(
                        prefilling, jnp.minimum(T, plen - ptr), 1
                    ).astype(jnp.int32)
                    pos = jnp.clip(
                        ptr[:, None] + jnp.arange(T)[None, :],
                        0, max_len_ - 1,
                    )
                    feed = jnp.where(
                        prefilling[:, None],
                        jnp.take_along_axis(buf, pos, axis=1),
                        tok[:, None],
                    )
                    cache_in = dict(cache)
                    cache_in["n_valid"] = n_valid
                    cache_in = _with_attn_operands(
                        cache_in, shared_blocks, shared_table
                    )
                    logits, cache2 = fwd(params, cfg_, feed, cache_in)
                    cache2 = _strip_attn_keys(dict(cache2))
                    cache2["length"] = jnp.where(
                        done, cache["length"], cache2["length"]
                    )
                    # the sampled token's buffer position is the
                    # post-feed length — the key input that makes
                    # sampling positional. Each row's real last slot is
                    # n_valid-1 (slot 0 for decode rows; the final
                    # prompt token for a row finishing its prefill).
                    pick_logits = jnp.take_along_axis(
                        logits,
                        (n_valid - 1)[:, None, None].astype(jnp.int32),
                        axis=1,
                    )[:, 0]
                    nxt = _pick_wave(
                        pick_logits, temp, seed, cache2["length"]
                    ).astype(tok.dtype)
                    finish = prefilling & (plen - ptr <= T)
                    emit = (~done) & (finish | ~prefilling)
                    nxt = jnp.where(emit, nxt, tok)
                    ptr2 = jnp.where(prefilling, ptr + n_valid, ptr)
                    return (cache2, nxt, ptr2), (nxt, emit)

                (cache, tok, ptr), (toks, emits) = lax.scan(
                    step, (cache, tok, ptr), None, length=C
                )
                return cache, tok, ptr, toks, emits  # (C, B), (C, B)

            return _decode_chunk

        self._pick = _pick

        def _insert_wave(cache, buf, ptr, plen, temp_vec, seed_vec,
                         rows, prompts, ps, starts, temps, seeds):
            """Admit up to B requests in ONE tiny dispatch: write each
            prompt into its row of the token buffer and reset the row's
            prefill pointer + cache depth to ``starts`` (0 for a cold
            prompt; the matched prefix length on a prefix-cache hit —
            the shared blocks already hold K/V for positions below it,
            so prefill resumes there). Unused wave slots carry an
            out-of-range row index and scatter-drop. The K/V buffers are
            untouched — stale data beyond a row's (reset) length is
            invisible to the length-masked attention and is overwritten
            as the prompt streams in."""
            cache = dict(cache)
            cache["length"] = cache["length"].at[rows].set(
                starts, mode="drop"
            )
            buf = buf.at[rows].set(prompts, mode="drop")
            ptr = ptr.at[rows].set(starts, mode="drop")
            plen = plen.at[rows].set(ps, mode="drop")
            temp_vec = temp_vec.at[rows].set(temps, mode="drop")
            seed_vec = seed_vec.at[rows].set(seeds, mode="drop")
            return cache, buf, ptr, plen, temp_vec, seed_vec

        # ---- speculative variants (the proposer seam, round 11) ----
        # ONE verify structure, two proposers: prompt-lookup (n-gram
        # copies from the committed text, computed in-trace — zero extra
        # model) and a DRAFT MODEL (a k+1-step width-1 scan through its
        # own dense KV cache). The verify program is identical either
        # way — proposals enter it as a (B, k) value — so a future
        # proposer (Medusa heads, host-side grammar jumps) plugs in
        # without touching the commit/rollback invariants.
        k_spec, g_spec, R = self._k, self._lookup, self._rounds
        W = k_spec + 1
        rows_idx = jnp.arange(B)
        d_fwd, d_cfg = draft_forward, draft_cfg

        def _draft_propose(d_params, d_cache, tok, frontier, done,
                           active, buf):
            """One round's draft-model proposals: a k+1-step scan of
            width-1 draft feeds. Each step feeds EITHER the next
            committed token from ``buf`` (teacher forcing, whenever the
            draft's cache pointer sits below the row's committed
            ``frontier`` — this one rule covers prompt prefill AND the
            catch-up after a prefix-cache hit let the TARGET skip
            positions the draft still has to ingest) or the draft's own
            previous prediction (speculative proposing past the
            frontier). Rows with nothing to feed (done; prefilling rows
            whose prompt ran out mid-scan) ride along at n_valid=0 —
            no K/V write, no pointer advance. Returns the (B, k)
            proposals (garbage for rows that were teacher-forcing —
            the verify rejects them, exactness never depends on
            proposal quality) and the updated draft cache."""
            def dstep(carry, _):
                d_cache, dtok = carry
                pos = d_cache["length"]  # (B,) the draft's next slot
                teach = pos < frontier
                feed = jnp.where(
                    teach,
                    jnp.take_along_axis(
                        buf,
                        jnp.clip(pos, 0, max_len_ - 1)[:, None],
                        axis=1,
                    )[:, 0],
                    dtok,
                )
                n_valid = jnp.where(
                    done, 0, jnp.where(teach | active, 1, 0)
                ).astype(jnp.int32)
                dc = dict(d_cache)
                dc["n_valid"] = n_valid
                logits, d_cache2 = d_fwd(
                    d_params, d_cfg, feed[:, None], dc
                )
                d_cache2 = {
                    k2: v2 for k2, v2 in dict(d_cache2).items()
                    if k2 != "n_valid"
                }
                nxt = jnp.argmax(logits[:, 0], axis=-1).astype(tok.dtype)
                return (d_cache2, nxt), nxt

            (d_cache, _), drafted = lax.scan(
                dstep, (d_cache, tok), None, length=W
            )
            # drafted (W, B): step i's output proposes position i+1 of
            # the window; the final step's output is discarded but its
            # feed put the last proposal's K/V in the draft cache (the
            # all-accepted case resumes after it)
            return drafted.swapaxes(0, 1)[:, :k_spec], d_cache

        def _make_spec_chunk(with_draft):
            """R speculative rounds in ONE dispatch: decode rows propose
            k tokens (n-gram lookup or the draft model) and verify in
            one k+1-wide target forward; PREFILLING rows ride the same
            forward with k+1 prompt tokens in their window instead
            (chunked prefill at T = k+1), emitting their first token
            the round their prompt completes. Commit +
            rollback-by-pointer go through models/decoding.py's shared
            helpers."""
            from nexus_tpu.models.decoding import (
                _commit_speculation,
                _greedy_accept,
                prompt_lookup_propose,
            )

            def round_body(params, d_params, cache, d_cache, tok, ptr,
                           done, buf, plen, shared_blocks, shared_table):
                prefilling = (ptr < plen) & ~done
                active = ~done & ~prefilling
                last_pos = cache["length"]  # (B,) == tok's buffer position
                if with_draft:
                    # committed frontier: positions of buf the draft may
                    # teacher-force (the prompt while prefilling; the
                    # committed text incl. tok once active)
                    frontier = jnp.where(prefilling, plen, last_pos + 1)
                    proposals, d_cache = _draft_propose(
                        d_params, d_cache, tok, frontier, done, active,
                        buf,
                    )
                else:
                    proposals, _found = prompt_lookup_propose(
                        buf, last_pos, k_spec, g_spec
                    )
                pf_pos = jnp.clip(
                    ptr[:, None] + jnp.arange(W)[None, :], 0, max_len_ - 1
                )
                block = jnp.where(
                    prefilling[:, None],
                    jnp.take_along_axis(buf, pf_pos, axis=1),
                    jnp.concatenate([tok[:, None], proposals], axis=1),
                )
                n_valid = jnp.where(
                    prefilling, jnp.minimum(W, plen - ptr), W
                ).astype(jnp.int32)
                cache_in = dict(cache)
                cache_in["n_valid"] = n_valid
                cache_in = _with_attn_operands(
                    cache_in, shared_blocks, shared_table
                )
                logits, cache2 = fwd(params, cfg_, block, cache_in)
                cache2 = _strip_attn_keys(dict(cache2))
                target_choice = jnp.argmax(logits, axis=-1).astype(tok.dtype)
                accepted, out = _greedy_accept(proposals, target_choice)
                accepted = jnp.where(active, accepted, 0)
                # commit + rollback-by-pointer via the SHARED helper (the
                # subtle invariants — frozen-row scatter drop, correction
                # token's K/V arriving on the next feed — live in
                # models/decoding.py, once). Non-active rows keep the
                # scaffold's length (prefill advance) or roll back (done).
                keep_len = jnp.where(
                    done, cache["length"], cache2["length"]
                )
                buf, _n_new, new_len = _commit_speculation(
                    buf, rows_idx, last_pos, active, accepted, out, k_spec,
                    max_len_, keep_len,
                )
                cache2["length"] = new_len
                finish = prefilling & (plen - ptr <= W)
                # a finishing row's first token reads the logits at its
                # real last prompt slot, lands in buf[plen] (committed
                # text the lookup proposer sees), and becomes next
                # round's feed — its K/V arrives on that feed, the same
                # invariant as a correction token
                first_tok = jnp.take_along_axis(
                    target_choice, (n_valid - 1)[:, None], axis=1
                )[:, 0]
                wpos = jnp.where(finish, plen, max_len_ + 1)
                buf = buf.at[rows_idx, wpos].set(first_tok, mode="drop")
                new_tok = jnp.where(
                    active, out[rows_idx, accepted],
                    jnp.where(finish, first_tok, tok),
                )
                ptr2 = jnp.where(prefilling, ptr + n_valid, ptr)
                # emitted tokens this round: decode rows commit
                # accepted+1 from `out`; a finishing row emits exactly
                # its first token (stored into out slot 0 for the host)
                out = jnp.where(
                    finish[:, None] & (jnp.arange(W) == 0)[None, :],
                    first_tok[:, None], out,
                )
                n_emit = jnp.where(
                    active, accepted + 1, jnp.where(finish, 1, 0)
                )
                if with_draft:
                    # draft rollback-by-pointer, in lockstep with the
                    # target's: an active row's rejected draft positions
                    # rewind to the committed length (their K/V is
                    # overwritten by the next round's feeds);
                    # teacher-forcing rows keep their own advance — it
                    # never passes the committed frontier, which is
                    # always <= the row's committed length
                    d_len = d_cache["length"]
                    d_cache = dict(d_cache)
                    d_cache["length"] = jnp.where(
                        active, jnp.minimum(d_len, new_len), d_len
                    )
                return (cache2, d_cache, new_tok, ptr2, buf), (
                    out, accepted, n_emit, active,
                )

            if with_draft:
                def _spec_chunk(params, d_params, cache, d_cache, tok,
                                ptr, done, buf, plen, shared_blocks,
                                shared_table):
                    def round_(carry, _):
                        cache, d_cache, tok, ptr, buf = carry
                        return round_body(
                            params, d_params, cache, d_cache, tok, ptr,
                            done, buf, plen, shared_blocks, shared_table,
                        )

                    ((cache, d_cache, tok, ptr, buf),
                     (outs, accs, n_emits, actives)) = lax.scan(
                        round_, (cache, d_cache, tok, ptr, buf), None,
                        length=R,
                    )
                    # outs (R, B, k+1); accs/n_emits/actives (R, B)
                    return (cache, d_cache, tok, ptr, buf, outs, accs,
                            n_emits, actives)
            else:
                def _spec_chunk(params, cache, tok, ptr, done, buf, plen,
                                shared_blocks, shared_table):
                    def round_(carry, _):
                        cache, d_cache, tok, ptr, buf = carry
                        return round_body(
                            params, None, cache, d_cache, tok, ptr,
                            done, buf, plen, shared_blocks, shared_table,
                        )

                    # the proposer carry slot rides empty (None is an
                    # empty pytree — same scan structure both tiers)
                    ((cache, _dc, tok, ptr, buf),
                     (outs, accs, n_emits, actives)) = lax.scan(
                        round_, (cache, None, tok, ptr, buf), None,
                        length=R,
                    )
                    # outs (R, B, k+1); accs/n_emits/actives (R, B)
                    return cache, tok, ptr, buf, outs, accs, n_emits, actives

            return _spec_chunk

        # donate the cache (and the spec path's token buffer): XLA updates
        # the K/V buffers in place instead of copying the whole cache
        # every chunk (same pattern as train/trainer.py's donated state).
        # Gated on a capability probe, not the platform name: current
        # jax donates fine on CPU, and without it every dispatch pays a
        # full pool copy — a cost proportional to POOL size, which is
        # exactly the ∝rows overhead the fused kernel exists to remove
        # (rows16's pool is 4x rows4's; docs/PERF.md round 8).
        from nexus_tpu.utils.hw import supports_donation

        donate = supports_donation()
        self._decode_chunk = jax.jit(
            _make_decode_chunk(self._t),
            donate_argnums=(1,) if donate else (),
        )
        # pure-decode program: dispatched whenever no row is prefilling
        # (the overwhelming share of chunks at steady state)
        self._decode_chunk_narrow = (
            jax.jit(
                _make_decode_chunk(1),
                donate_argnums=(1,) if donate else (),
            )
            if self._t > 1 else self._decode_chunk
        )
        self._insert_fn = jax.jit(
            _insert_wave,
            donate_argnums=(0, 1, 2, 3, 4, 5) if donate else (),
        )
        # copy-on-write program (paged only): copy pool blocks src→dst
        # across every K/V plane in one tiny dispatch; padding pairs
        # carry an out-of-range dst and drop (models/decoding.py).
        # Each engine jits its OWN trivial closure rather than the
        # module-level function: jax shares one compiled-program cache
        # across every `jax.jit(same_fn)` wrapper, so a bare wrap would
        # let OTHER engines' compiles (different shapes in other tests
        # or co-resident engines) leak into this engine's
        # `_cache_size()` — the per-engine recompile sanitizer's counts
        # must be per-engine facts.
        self._copy_fn = jax.jit(
            lambda cache, src, dst: copy_kv_blocks(cache, src, dst),
            donate_argnums=(0,) if donate else (),
        )
        # host-tier programs (round 10, models/decoding.py): the spill
        # download gathers ONE block's planes (block id TRACED — one
        # program whatever pool pressure reclaims), the restore upload
        # scatters a fixed-width wave of host payloads into
        # freshly-allocated blocks (OOB padding drops)
        self._spill_gather_fn = jax.jit(
            lambda cache, blk: gather_kv_block(cache, blk)
        )
        self._restore_write_fn = jax.jit(
            lambda cache, dst, planes: write_kv_blocks(
                cache, dst, planes
            ),
            donate_argnums=(0,) if donate else (),
        )
        if self._draft:
            self._spec_chunk = jax.jit(
                _make_spec_chunk(True),
                donate_argnums=(2, 3, 7) if donate else (),
            )

            def _draft_reset(d_cache, rows):
                """Reset admitted rows' DRAFT cache pointers to 0 in one
                tiny dispatch (the draft has no prefix cache — it
                teacher-forces the whole prompt from the round scans'
                width-1 feeds). Unused wave slots carry an out-of-range
                row index and scatter-drop, mirroring the insert wave."""
                d_cache = dict(d_cache)
                d_cache["length"] = d_cache["length"].at[rows].set(
                    0, mode="drop"
                )
                return d_cache

            self._draft_reset_fn = jax.jit(
                _draft_reset, donate_argnums=(0,) if donate else ()
            )
        else:
            self._spec_chunk = jax.jit(
                _make_spec_chunk(False),
                donate_argnums=(1, 5) if donate else (),
            )
        # int8 KV serving rides the same scaffold as static decode: the
        # chunk program quantizes on write and the insert path never
        # touches K/V (chunked prefill streams the prompt in-band), so
        # the scale planes need no admission-time handling at all.
        # kv_pool_dtype='int8' (round 10) selects the same quantized
        # layout at the serve level — one pool, two switches.
        self._quantized = (
            bool(getattr(cfg, "kv_cache_quantized", False))
            or self._kv_pool_int8
        )
        # per-position cache bytes across layers and k+v (+ the int8
        # scale planes) — the currency of the KV metrics
        if self._quantized:
            self._pos_bytes = cfg.n_layers * cfg.n_kv_heads * (
                cfg.head_dim * 1 + 4
            ) * 2
        else:
            self._pos_bytes = (
                cfg.n_layers * cfg.n_kv_heads * cfg.head_dim
                * int(np.dtype(cfg.dtype).itemsize) * 2
            )
        # ---- engine-LIFETIME KV state (round 16) ----
        # the device pool, radix tree, and host tier survive across
        # serve() calls — cross-call prefix reuse is the whole point of
        # a persistent engine; reset_cache() is the escape hatch
        self._warmed = False
        self._serve_calls = 0
        self.cache_resets = 0
        self._build_kv_state()

    def _build_kv_state(self) -> None:
        """(Re)build the engine-lifetime KV bookkeeping from scratch:
        host spill store, block allocator + radix prefix index, the
        host-side block-table mirror, and the persisted device cache
        slot (None = mint fresh on the next serve). Called once at
        construction and again by :meth:`reset_cache`."""
        self._host_store = (
            HostBlockStore(
                self._host_cache_bytes, dtype=self._host_cache_dtype
            )
            if self._paged and self._host_tier else None
        )
        self._alloc = (
            BlockAllocator(
                self._num_blocks, self._block_size,
                prefix_index=(
                    PrefixCacheIndex() if self._prefix else None
                ),
                host_cache=self._host_store,
            )
            if self._paged else None
        )
        # the sanitizer's radix-tree audit hook (and the bench's
        # introspection point): the content index — engine-lifetime
        # since round 16, so "last" now means "current"
        self.last_prefix_index = (
            self._alloc.index if self._alloc is not None else None
        )
        # the sanitizer's host-tier audit hook: spilled tree entries and
        # store keys must agree bit for bit
        self.last_host_store = self._host_store
        self._table_np = np.full(
            (self._b, self._blocks_per_row or 1), self._num_blocks,
            dtype=np.int32,
        )
        # the persisted device cache between serve() calls; ownership
        # transfers INTO serve() (donated dispatches consume it), so a
        # call that raises mid-run leaves this None and the next call
        # rebuilds from a clean slate via reset_cache()
        self._kv_cache = None
        # distinguishes "just (re)built, cache legitimately unminted"
        # from "a prior call crashed mid-run" at serve() entry — an
        # explicit reset_cache() must not be re-counted as a crash
        # recovery there
        self._kv_fresh = True
        # digests already indexed when the current serve() call began —
        # the committed-publication audit treats them as prior calls'
        # committed text (re-proven when they were published), and the
        # cross-call hit ledger counts matches against them
        self.last_preexisting_keys: frozenset = frozenset()

    def reset_cache(self) -> None:
        """Escape hatch: discard ALL engine-lifetime KV state — the
        device pool content, the radix prefix tree, and the host spill
        tier — as if the engine were freshly built. The next serve()
        call starts cache-cold (its first dispatch re-mints the pool;
        the compiled programs are untouched, so no re-warm-up). Never
        call mid-serve."""
        self._build_kv_state()
        self.cache_resets += 1

    def set_observability(self, tracer: Any = None,
                          flight_recorder: Any = False,
                          live_gauges: bool = False,
                          gauge_tags: Optional[Sequence[str]] = None):
        """Swap the obs attachments between serve() runs.

        The supported same-engine toggle: the bench's tracing-overhead
        A/B serves one engine alternately with the obs surface on and
        off, so the measurement compares identical compiled programs,
        pool state, and prefix-tree warmth — engine-identity noise (two
        separately-built engines measurably differ on the CPU lane even
        when configured identically) never enters the ratio. Takes
        effect at the next serve() call; never call it mid-serve."""
        self._tracer = tracer
        if flight_recorder is False:
            self.flight_recorder = None
        else:
            self.flight_recorder = flight_recorder or FlightRecorder()
        self._live_gauges = bool(live_gauges)
        self._gauge_tags = list(gauge_tags or [])

    def _mint(self, x, dtype=None):
        """Host value → device array with a dispatch-stable commitment
        (replicated on the cache mesh when one is set — see __init__)."""
        arr = jnp.asarray(x, dtype)
        if self._host_sharding is not None:
            arr = jax.device_put(arr, self._host_sharding)
        return arr

    def _fresh_cache(self):
        """The serve cache at its REAL layout (paged pool + scratch
        block, or the legacy dense rows) with the caller's sharding
        constraint pinned — used for warm-up AND the serving runs so
        both compile the same program."""
        b, max_len, cfg = self._b, self._max_len, self._cfg
        if self._paged:
            c = init_paged_kv_cache(
                cfg.n_layers, cfg.n_kv_heads, cfg.head_dim, cfg.dtype,
                b, self._num_blocks + 1, self._block_size,
                self._blocks_per_row, quantized=self._quantized,
            )
        else:
            c = init_kv_cache(
                cfg.n_layers, cfg.n_kv_heads, cfg.head_dim, cfg.dtype,
                b, max_len, quantized=self._quantized,
            )
            c["length"] = jnp.zeros((b,), jnp.int32)
        c = constrain_kv_sharding(c, self._cache_sharding)
        if self._host_sharding is not None:
            # k/v (+ scales) already carry the cache sharding; commit
            # the host-side leaves (tables, lengths) replicated so the
            # first dispatch's cache signature equals the steady
            # state's
            c = {
                k: (v if k in ("k", "v", "k_scale", "v_scale")
                    else jax.device_put(v, self._host_sharding))
                for k, v in c.items()
            }
        return c

    def _fresh_draft_cache(self):
        """The draft proposer's own KV cache: DENSE rows at the
        draft's shapes (a draft is small by design, so a worst-case
        ``batch × max_len`` stripe is cheap next to the target's
        pool) with vector lengths — rollback is the same
        pointer-rewind the dense speculative loops use. No block
        table, no prefix sharing: the draft teacher-forces every
        admitted prompt from position 0 (see _draft_propose)."""
        b, max_len = self._b, self._max_len
        d_cfg = self._draft_cfg
        dc = init_kv_cache(
            d_cfg.n_layers, d_cfg.n_kv_heads, d_cfg.head_dim,
            d_cfg.dtype, b, max_len,
            quantized=getattr(d_cfg, "kv_cache_quantized", False),
        )
        dc["length"] = jnp.zeros((b,), jnp.int32)
        dc = constrain_kv_sharding(dc, self._draft_cache_sharding)
        if self._host_sharding is not None:
            # commit EVERY leaf on the mesh (k/v replicated when no
            # explicit draft sharding was given): a fresh cache
            # whose commitment differs from the steady-state jit
            # outputs is a second compile key for the verify and
            # draft-reset programs — the PR 7 recompile class
            kv = ("k", "v", "k_scale", "v_scale")
            keep = kv if self._draft_cache_sharding is not None else ()
            dc = {
                k: (v if k in keep
                    else jax.device_put(v, self._host_sharding))
                for k, v in dc.items()
            }
        return dc

    @staticmethod
    def _restore_plane_zeros(c, n):
        """(L, n, Bs, ...) zero stacks matching every K/V plane of
        cache ``c`` — the restore wave's padding template (and its
        warm-up payload)."""
        planes = {}
        for key in ("k", "v", "k_scale", "v_scale"):
            if key in c:
                shp = c[key].shape
                planes[key] = np.zeros(
                    (shp[0], n) + tuple(shp[2:]),
                    dtype=np.dtype(c[key].dtype),
                )
        return planes

    def warmup(self) -> None:
        """Compile every program the serve loop can dispatch (idempotent
        — ONCE per engine lifetime, not per call). serve() calls this
        before starting its clock, so tokens/sec and the per-request
        latencies measure serving, not XLA compilation; a long-lived
        replica may call it eagerly at construction time instead so its
        FIRST streamed arrival doesn't pay the compile either."""
        if self._warmed:
            return
        b, max_len = self._b, self._max_len
        # warm with the REAL layout or jit compiles a second program for
        # the constrained cache on the first timed chunk (scale planes
        # included — unconstrained they replicate on a sharded mesh)
        warm_cache = self._fresh_cache()
        warm_buf = self._mint(np.zeros((b, max_len), np.int32))

        def zi():
            # donation demands DISTINCT buffers per donated argnum (a
            # shared array would be both donated twice in one call and
            # dead for the next one) — mint a fresh array per use
            return self._mint(np.zeros((b,), np.int32))

        def zf():
            return self._mint(np.zeros((b,), np.float32))

        m_slots = self._blocks_per_row or 1
        zero_shared = (
            self._mint(np.int32(0)),
            self._mint(np.full((m_slots,), self._num_blocks, np.int32)),
        )
        # the insert consumes its donated inputs; thread its RETURNS
        # into the chunk warm-up instead of reusing dead arrays
        (warm_cache, warm_buf, warm_ptr, warm_plen, warm_temp,
         warm_seed) = self._insert_fn(
            warm_cache, warm_buf, zi(), zi(), zf(), zi(),
            self._mint(np.full((b,), b, np.int32)),
            self._mint(np.zeros((b, max_len), np.int32)), zi(), zi(),
            zf(), zi(),
        )
        if self._draft:
            # warm in SERVE order — reset on the eager fresh cache,
            # then the verify chunk on the reset's jit output — so both
            # commitment flavors the timed run produces are the ones
            # already compiled (mirrors the insert→chunk threading
            # above; the reset first fires at the first admission wave,
            # inside the timed window)
            warm_d = self._draft_reset_fn(
                self._fresh_draft_cache(),
                self._mint(np.full((b,), b, np.int32)),
            )
            out = self._spec_chunk(
                self._params, self._draft_params, warm_cache, warm_d,
                zi(), warm_ptr, self._mint(np.ones((b,), np.bool_)),
                warm_buf, warm_plen, *zero_shared,
            )
            np.asarray(out[5])  # host fetch: the warm-up really completed
            del warm_d
        elif self._lookup:
            out = self._spec_chunk(
                self._params, warm_cache, zi(), warm_ptr,
                self._mint(np.ones((b,), np.bool_)), warm_buf, warm_plen,
                *zero_shared,
            )
            np.asarray(out[4])  # host fetch: the warm-up really completed
        else:
            out = self._decode_chunk(
                self._params, warm_cache, zi(), warm_ptr,
                self._mint(np.ones((b,), np.bool_)), warm_buf, warm_plen,
                warm_temp, warm_seed, *zero_shared,
            )
            np.asarray(out[3])  # host fetch: the warm-up really completed
            if self._decode_chunk_narrow is not self._decode_chunk:
                # the wide warm-up donated its state; mint fresh buffers
                # for the pure-decode program's compile
                warm2 = self._fresh_cache()
                out = self._decode_chunk_narrow(
                    self._params, warm2, zi(), zi(),
                    self._mint(np.ones((b,), np.bool_)),
                    self._mint(np.zeros((b, max_len), np.int32)), zi(),
                    zf(), zi(), *zero_shared,
                )
                np.asarray(out[3])
        if self._paged and self._host_tier:
            # compile the host-tier programs outside the timed window
            # (they first fire mid-run, under pool pressure): the spill
            # download with a traced block id, and the restore upload
            # at its fixed wave width with all-OOB (dropped) padding
            wc = self._fresh_cache()
            jax.device_get(
                self._spill_gather_fn(wc, self._mint(np.int32(0)))
            )
            wc = self._restore_write_fn(
                wc,
                self._mint(np.full(
                    (self._restore_wave,), self._num_blocks + 1,
                    np.int32,
                )),
                {k: self._mint(v) for k, v in
                 self._restore_plane_zeros(
                     wc, self._restore_wave
                 ).items()},
            )
            np.asarray(wc["length"])
            del wc
        del warm_cache, warm_buf, out
        self._warmed = True

    def _validate_request(self, req: ServeRequest, req_idx: int):
        """Per-request admission checks → (prompt, p, budget)."""
        prompt = np.asarray(req.prompt, dtype=np.int32)
        p = int(prompt.shape[0])
        if p < 1:
            raise ValueError(f"request {req_idx}: empty prompt")
        if self._spec and req.temperature > 0:
            raise ValueError(
                f"request {req_idx}: speculative serving (prompt-lookup "
                "or draft-model) is greedy-exact only; temperature must "
                "be 0"
            )
        # budget: leave the dispatch's worst-case overrun + 1 below the
        # cache end so an almost-finished chunk can never run the row
        # past it (plain: chunk steps; speculative: rounds*(k+1) commits
        # plus the k-wide verify block's K/V writes)
        budget = min(
            int(req.max_new_tokens), self._max_len - 1 - p - self._slack
        )
        if budget < 1:
            raise ValueError(
                f"request {req_idx}: prompt ({p}) + chunk slack "
                f"({self._slack}) leaves no decode budget within "
                f"max_len {self._max_len}"
            )
        if self._paged:
            # a request whose worst-case block need exceeds the whole
            # pool can NEVER be admitted — an error now, not a hang later
            need = -(-self._row_cap(p, budget) // self._block_size)
            if need > self._num_blocks:
                raise ValueError(
                    f"request {req_idx}: needs {need} KV blocks "
                    f"(prompt {p} + budget {budget} + slack "
                    f"{self._slack}) but the HBM pool has only "
                    f"{self._num_blocks}; raise kv_num_blocks, run the "
                    "int8 pool (kv_pool_dtype doubles blocks per HBM "
                    "byte), or shrink the request — the host spill "
                    "tier cannot help here: restored blocks still "
                    "live in the pool while a row reads them, so one "
                    "request's worst case must fit the HBM tier alone"
                )
        return prompt, p, budget

    def _row_cap(self, p: int, budget: int) -> int:
        """Worst-case cache positions one admitted request can ever
        touch: prompt + trimmed budget + one dispatch's overrun + the
        held token's slot. The reservation unit of HBM-aware admission —
        always <= max_len by the budget trim above."""
        return min(self._max_len, p + budget + self._slack + 1)

    def _admit_wave(self, cache, buf, ptr, plen, temp_vec, seed_vec,
                    admissions):
        """Admit up to B requests with ONE insert dispatch: stack the
        wave's prompts into fixed (B, max_len) arrays (unused slots
        scatter-drop via an out-of-range row index) and write them into
        the device state. No model forward happens here — the chunk
        program streams each prompt in-band, starting at the row's
        matched prefix length (0 without a prefix-cache hit).
        ``admissions``: [(row, req, req_idx, prompt, p, budget,
        matched), ...] (pre-validated by the caller, which gates on the
        block pool first) → [(row, _RowState, steps), ...]."""
        b, max_len = self._b, self._max_len
        rows = np.full((b,), b, dtype=np.int32)  # b == dropped slot
        prompts = np.zeros((b, max_len), dtype=np.int32)
        ps = np.zeros((b,), dtype=np.int32)
        starts = np.zeros((b,), dtype=np.int32)
        temps = np.zeros((b,), dtype=np.float32)
        seeds = np.zeros((b,), dtype=np.int32)
        out = []
        width = (self._k + 1) if self._spec else self._t
        now = self._clock()
        for i, (row, req, req_idx, prompt, p, budget, matched) in enumerate(
            admissions
        ):
            rows[i] = row
            prompts[i, :p] = prompt
            ps[i] = p
            starts[i] = matched
            temps[i] = req.temperature
            seeds[i] = req.seed
            steps = -(-(p - matched) // width)
            out.append((row,
                        _RowState(request_idx=req_idx, budget=budget,
                                  admitted_t=now),
                        steps))
            self._prefill_steps += steps
            # step-slots the matched prefix did NOT consume — the
            # direct compute saving of the prefix cache
            self._prefill_steps_saved += -(-p // width) - steps
        with dispatch_annotation("nexus.serve.insert_wave"):
            cache, buf, ptr, plen, temp_vec, seed_vec = self._insert_fn(
                cache, buf, ptr, plen, temp_vec, seed_vec,
                self._mint(rows), self._mint(prompts), self._mint(ps),
                self._mint(starts), self._mint(temps), self._mint(seeds),
            )
        self._insert_dispatches += 1
        return cache, buf, ptr, plen, temp_vec, seed_vec, out

    def serve(self, requests: Sequence[ServeRequest], cancel=None,
              heartbeat=None, tracer=None, source=None,
              ext_backlog=None):
        """Run the queue (plus any streamed arrivals) to completion →
        (results, metrics).

        results[i] corresponds to requests[i]. Metrics: committed vs
        scheduled step-slots (the continuous-batching win is this
        utilization staying high under uneven lengths — in-band prefill
        steps are scheduled slots, so admission cost shows up here
        honestly), chunk count, wall time, decode tokens/sec over
        committed tokens.

        Every request terminates with an explicit ``status`` — deadline
        misses and bounded-queue sheds produce honest terminal results,
        never silent drops or unbounded queuing (queue policing runs at
        every wave boundary).

        ``heartbeat``: wave-boundary liveness callback — called with the
        committed-token count after every decode chunk; the serve
        entrypoint wires it to a ``hb-serve-<template>`` lease renewer
        (ha/lease.py) so the failover detector confirms engine death
        exactly as for trainers.

        ``tracer``: a per-CALL ServeTracer override (round 15). The
        fleet attaches a FRESH tracer to every serve call so each
        call's span timelines can be stitched into cross-replica
        journeys without resetting the engine-attached tracer or the
        rest of the observability surface (set_observability swaps
        everything; this swaps one run's tracer only). None keeps the
        engine-attached tracer.

        ``cancel``: a utils.signals.CancelToken. When it fires, serve()
        stops at the next wave boundary, releases every KV lease (the
        pool partition stays leak-free — free + parked == the whole
        pool), records a drain snapshot of the unfinished in-flight and
        queued requests in ``self.last_drain`` (committed tokens
        preserved — the ServeFailoverPlanner's requeue input), and
        returns with ``metrics['interrupted'] = True``; unfinished
        entries of ``results`` stay None.

        ``source`` (round 16, open-loop serving): an arrival stream —
        any object with the :class:`~nexus_tpu.runtime.traffic
        .TraceSource` protocol (``poll(now_s) -> [ServeRequest]``,
        ``exhausted()``, ``wait(now_s)``, ``due(now_s)``; times are
        seconds since THIS call's clock start). The engine polls it at
        every wave boundary and admits arrivals into the SAME
        continuous-batching loop the pre-queued requests run in; when
        every row is idle and the stream has more to deliver, the
        engine blocks in ``source.wait`` (which sleeps real time or
        advances an injected clock) instead of returning. ``results``
        grows to cover streamed requests, in arrival order after the
        pre-queued ones. ``ext_backlog``: a callable returning how many
        requests are pending OUTSIDE this call (a fleet replica's
        inbox) — folded into the ``serve_queue_depth`` live gauge so
        the autoscaler and p2c spill read real backlog, never engine
        math.

        The engine's KV state is ENGINE-LIFETIME (round 16): the block
        pool, radix prefix tree, and host spill tier persist across
        serve() calls, so a warm engine's admissions match prefixes
        cached by EARLIER calls (``prefix_hit_tokens_cross_call``
        ledgers the cross-call share). ``reset_cache()`` drops all of
        it. Under NEXUS_SANITIZE a warm entry re-audits the boundary
        state (pool partition, tree closure, store coherence) before
        serving — state dirtied between calls trips the sanitizer here,
        not mid-wave.

        Every program the loop can dispatch is compiled BEFORE the
        clock starts (once per engine lifetime — warmup()) — tokens/sec
        and the per-request latencies measure serving, not XLA
        compilation (the infer bench warms the same way)."""
        b, max_len = self._b, self._max_len
        requests = list(requests)

        # ---- engine-lifetime KV state pickup (round 16) ----
        alloc = self._alloc
        host_store = self._host_store
        if self._kv_cache is None and not self._kv_fresh:
            # the prior call raised mid-run: its donated device cache
            # is gone, so the tree/store bookkeeping points at payloads
            # that no longer exist — rebuild everything cache-cold
            # rather than serve stale-block hits (_kv_fresh excludes a
            # deliberate reset_cache() or a never-served engine, both
            # already clean)
            self.reset_cache()
            alloc = self._alloc
            host_store = self._host_store
        if self._sanitize and self._serve_calls > 0 and self._kv_cache is not None:
            # warm-entry audit: the boundary state a previous call left
            # behind must still be clean BEFORE new admissions build on
            # it (the same partition/closure/coherence invariants the
            # post-serve audits prove, re-checked against between-call
            # mutation)
            from nexus_tpu.testing.sanitizers import audit_warm_boundary

            audit_warm_boundary(self, context="serve[warm-entry]")
        self.last_preexisting_keys = (
            frozenset(alloc.index.indexed_keys())
            if alloc is not None and alloc.index is not None
            else frozenset()
        )
        self.warmup()  # idempotent: compiles once per engine lifetime

        t0 = self._clock()
        self.last_drain = None
        self.last_flight_dump = None
        # ---- observability hookup (round 12, nexus_tpu/obs/) ----
        # all three are pure host-side bookkeeping: the tracer and the
        # flight recorder are dict appends, the gauges a handful of
        # registry writes per wave — each site guards on None so the
        # disabled path costs one branch
        tracer = tracer if tracer is not None else self._tracer
        flight = self.flight_recorder
        gauges = (
            LiveGauges(tags=self._gauge_tags) if self._live_gauges
            else None
        )
        tripped: set = set()

        def trip_flight(reason: str, detail: Optional[dict] = None):
            """Freeze the flight ring — once per reason per run, so a
            storm that persists across waves yields one dump of its
            onset instead of a dump per wave."""
            if flight is None or reason in tripped:
                return
            tripped.add(reason)
            self.last_flight_dump = flight.trip(
                reason, t=self._clock() - t0, detail=detail,
            )

        if tracer is not None:
            # journey ids (round 15): the fleet-stable identity each
            # request carries — the tracer dump echoes it per request
            # so the fleet's JourneyBook can stitch this call's
            # timelines into cross-replica journeys
            tracer.begin(len(requests), journeys=[
                str(getattr(r, "journey", "") or "") for r in requests
            ])
            for i, req_ in enumerate(requests):
                tracer.event(
                    i, "enqueued", t=0.0,
                    prompt_tokens=len(req_.prompt),
                    max_new_tokens=int(req_.max_new_tokens),
                )
        if flight is not None:
            flight.record("run_start", t=0.0, requests=len(requests))
        interrupted = False

        def zi():
            # donation demands DISTINCT buffers per donated argnum (a
            # shared array would be both donated twice in one call and
            # dead for the next one) — mint a fresh array per use
            return self._mint(np.zeros((b,), np.int32))

        def zf():
            return self._mint(np.zeros((b,), np.float32))

        # fused-path operands (traced VALUES — one program whatever the
        # wave's shared run is): the Hydragen shared-run length and the
        # aliased leading block ids; an all-scratch table + length 0 is
        # the no-shared-run neutral element, reused whenever detection
        # finds nothing (gather/dense engines pass it uninspected)
        m_slots = self._blocks_per_row or 1
        zero_shared = (
            self._mint(np.int32(0)),
            self._mint(np.full((m_slots,), self._num_blocks, np.int32)),
        )
        # the device cache's OWNERSHIP transfers into this call: a warm
        # engine resumes the pool its last call left behind (parked
        # prefix payloads intact — the cross-call hit surface); a fresh
        # engine, or one reset_cache() just wiped, mints cold. Donated
        # dispatches consume the array, so the slot is cleared here and
        # re-stashed only when the call completes.
        if self._kv_cache is not None:
            cache = self._kv_cache
            self._kv_cache = None
        else:
            cache = self._fresh_cache()  # vector length from step 0
        # the freshness token is consumed HERE: from this point on, a
        # None _kv_cache means this call died mid-run and the next call
        # must rebuild (see the entry check above)
        self._kv_fresh = False
        d_cache = self._fresh_draft_cache() if self._draft else None
        buf = self._mint(np.zeros((b, max_len), np.int32))
        tok_vec = zi()
        ptr_vec = zi()
        plen_vec = zi()
        temp_vec = zf()
        seed_vec = zi()
        rows: List[Optional[_RowState]] = [None] * b
        # host-side mirror of each row's remaining prefill steps (at the
        # chunk program's feed width) — selects the wide program only
        # while some row is actually streaming its prompt. Correctness
        # never depends on it (either program is exact for any state).
        prefill_left = [0] * b
        results: List[Optional[ServeResult]] = [None] * len(requests)
        # FIFO admission queue of request indices. Prefix-aware deferral
        # may SKIP a request (its prefix is being prefilled by an active
        # row — admitting now would duplicate exactly the compute the
        # cache saves) and re-queue it at the front; a pool-full refusal
        # still blocks the head (refund-wait, never overtaken).
        pending = deque(range(len(requests)))
        # arrival anchoring (round 16): the absolute arrival stamp per
        # request — t0 + arrival_s. Latency/queue attribution and the
        # ttft rollup measure from here; deadlines and the queue-delay
        # shed anchor at max(arrival, t0) so a request whose arrival
        # predates this call (fleet inbox wait: arrival_s < 0) can
        # never be charged engine time it spent elsewhere twice, and a
        # streamed arrival's deadline budgets ITS wait, not the
        # stream's.
        arrive_t = [
            t0 + float(getattr(r, "arrival_s", 0.0) or 0.0)
            for r in requests
        ]

        def dl_anchor(req_idx: int) -> float:
            a = arrive_t[req_idx]
            return a if a > t0 else t0

        streamed = 0
        committed = 0
        scheduled_slots = 0
        chunks = 0
        shed_count = 0
        deadline_miss_count = 0
        deadline_cancelled_rows = 0
        # peak WAIT-queue depth, sampled post-admission / pre-shed at
        # every wave boundary (police_depth) — comparable against
        # max_queue_depth, which bounds the same population; the raw
        # arrival burst is just len(requests)
        queue_depth_peak = 0
        target_forwards = 0
        drafted = 0
        accepted_total = 0
        self._insert_dispatches = 0
        self._prefill_steps = 0
        self._prefill_steps_saved = 0

        # ---- paged-pool bookkeeping (all host-side) ----
        # per-position cache bytes across layers and k+v (+ the int8
        # scale planes) — the currency of the KV metrics
        pos_bytes = self._pos_bytes
        if host_store is not None:
            def spill_download(blk: int, _key: bytes) -> dict:
                """The device half of a demotion: gather the victim's
                planes (one compiled program — the id is traced) and
                fetch them to the host. The victim is parked (frozen,
                fully written) and the fetch synchronizes behind every
                enqueued dispatch, so the payload is exact."""
                planes = jax.device_get(self._spill_gather_fn(
                    cache, self._mint(np.int32(blk))
                ))
                return {k: np.asarray(v) for k, v in planes.items()}

            # re-bound every call: the closure reads THIS call's live
            # ``cache`` local (the engine-lifetime allocator outlives
            # any one call's device array)
            alloc.spill_fn = spill_download
        leases: List[Optional[_BlockLease]] = [None] * b
        caps = [0] * b  # _row_cap per active row
        plen_host = [0] * b  # prompt length per active row
        scratch = self._num_blocks  # the one block the allocator never owns
        # engine-lifetime table mirror: at every call boundary all rows
        # point at scratch (release_row resets them), so persisting the
        # array is free — and the first wave always re-pushes it
        # (table_dirty starts True)
        table_np = self._table_np
        reserved_blocks_total = 0  # Σ per-admission PRIVATE reservations
        alloc_block_steps = 0  # Σ per-chunk allocated blocks (residency)
        table_dirty = [True]  # admission/finish/growth since last push
        # ---- prefix-cache bookkeeping (host-side, per active row) ----
        row_keys: List[List[bytes]] = [[] for _ in range(b)]  # chain keys
        indexed_upto = [0] * b  # chain keys already published to the index
        pf_ptr = [0] * b  # exact host mirror of the row's prefill pointer
        keys_cache: dict = {}  # request idx → chain keys (deferral re-scan)
        hit_tokens = 0
        hit_requests = 0
        # cross-call share of the hits (round 16): matched tokens whose
        # digests were already indexed when THIS call began — prefix
        # reuse paid for by a PREVIOUS call's prefill/decode work
        pre_keys = self.last_preexisting_keys
        cross_hit_tokens = 0
        cross_hit_requests = 0
        cow_copies = 0
        # host-tier ledger (round 10): prompt tokens served by swapping
        # spilled blocks back in (a subset of hit_tokens), and the
        # requests that restored at least one block
        restore_hit_tokens = 0
        restore_hit_requests = 0
        # matched-depth histogram (blocks of tree depth per hit) — the
        # hit-rate-by-depth ledger the bench scenarios report
        hit_depth_hist: dict = {}
        completion_blocks_registered = 0
        # cache-aware admission bookkeeping: how many waves have
        # overtaken each still-waiting request (the aging counter) and
        # the total overtake count (the reordering ledger)
        passed_over: dict = {}
        admission_overtakes = 0
        hydragen_waves = 0  # dispatches that ran with a shared run > 0
        hydragen_shared_slots = 0  # Σ shared-run blocks over those waves
        ttfts: List[float] = []
        queues: List[float] = []

        def grow_and_push_tables():
            """Map every active row's next-dispatch coverage (its length
            can grow by at most ``slack`` past prompt + emitted within
            one dispatch — the same bound the budget trim uses) and push
            the table to the device cache. In-reservation growth can
            never fail (BlockAllocator invariant), which is what makes
            admission eviction-free. Steady-state chunks (no admission,
            no finish, no block-boundary crossing) skip the upload — the
            chunk program passes the table through its returned cache,
            so the device copy stays valid until the host changes it."""
            nonlocal cache
            grow_t = None  # one clock read per wave with growth, lazily
            for r in range(b):
                state = rows[r]
                if state is None or leases[r] is None:
                    continue
                cover = min(
                    caps[r],
                    plen_host[r] + len(state.emitted) + self._slack,
                )
                before = len(leases[r].blocks)
                blks = leases[r].grow_to(alloc.blocks_for(cover))
                if len(blks) != before:
                    table_np[r, : len(blks)] = blks
                    table_dirty[0] = True
                    if tracer is not None:
                        if grow_t is None:
                            grow_t = round(self._clock() - t0, 6)
                        tracer.event(
                            state.request_idx, "lease_grow", t=grow_t,
                            row=r, wave=chunks + 1,
                            blocks_mapped=len(blks),
                        )
            if self._sanitize:
                # the unmapped-tail contract: everything past a row's
                # mapped blocks points at the scratch block, always —
                # a violation trips the flight recorder on its way out,
                # so the postmortem shows the waves that led up to it
                try:
                    alloc.audit_scratch_tails(table_np, [
                        len(leases[r].blocks) if leases[r] is not None
                        else 0
                        for r in range(b)
                    ])
                except AssertionError as e:
                    trip_flight("sanitizer", {"error": str(e)})
                    raise
            if table_dirty[0]:
                cache = dict(cache)
                cache["block_table"] = self._mint(table_np)
                table_dirty[0] = False

        def detect_shared_run():
            """Hydragen wave-level detection (host-side, O(B·M) numpy):
            the longest run of leading table entries shared by ALL live
            rows — prefix-cache hits alias the same physical block ids,
            so same-preamble waves share trivially and unrelated waves
            mismatch at slot 0. Needs >= 2 live rows (a single row's
            "shared" prefix amortizes nothing) and returns the run
            length plus the minted traced operands; 0/neutral otherwise
            — the SAME compiled program either way."""
            if not self._fused:
                return 0, zero_shared
            live = [
                r for r in range(b)
                if rows[r] is not None and leases[r] is not None
            ]
            if len(live) < 2:
                return 0, zero_shared
            # entries past a lease's mapped blocks are scratch — cap the
            # run at the shallowest mapping so it only ever covers real,
            # fully-owned blocks
            s = min(len(leases[r].blocks) for r in live)
            head = table_np[live[0]]
            for r in live[1:]:
                neq = np.nonzero(table_np[r, :s] != head[:s])[0]
                if neq.size:
                    s = int(neq[0])
                if s == 0:
                    return 0, zero_shared
            return s, (self._mint(np.int32(s)), self._mint(head.copy()))

        def finish(state: _RowState, status: str = STATUS_OK) -> None:
            nonlocal committed
            committed += len(state.emitted)
            arr = arrive_t[state.request_idx]
            ttft = max(0.0, state.first_tok_t - state.admitted_t)
            # the ROLLUP ttft anchors at ARRIVAL (round 16): under
            # streamed admission "time to first token" the user felt
            # includes the queue wait, or an open-loop p95 would look
            # flat while the backlog exploded
            ttft_arr = max(0.0, state.first_tok_t - arr)
            queue_s = max(0.0, state.admitted_t - arr)
            if status == STATUS_OK:
                # the latency rollups describe SERVED requests only — a
                # cancelled row's ttft must not flatter (or poison) the
                # p95 of the work that actually completed
                ttfts.append(ttft_arr)
                queues.append(queue_s)
                if gauges is not None:
                    # same population as the end-of-run rollup, so the
                    # rolling p95 and the final p95 agree on the data
                    gauges.observe_finish(ttft_arr, queue_s)
            done = self._clock()
            done_t = done - t0
            latency = max(0.0, done - arr)
            results[state.request_idx] = ServeResult(
                tokens=list(np.asarray(
                    requests[state.request_idx].prompt, dtype=np.int32
                )) + state.emitted,
                new_tokens=len(state.emitted),
                finished_by_stop=state.stopped,
                latency_s=latency,
                ttft_s=round(ttft, 6),
                queue_s=round(queue_s, 6),
                status=status,
                retries=int(getattr(
                    requests[state.request_idx], "retries", 0
                )),
            )
            if tracer is not None:
                tracer.event(
                    state.request_idx, "terminal",
                    t=round(done_t, 6), status=status,
                    new_tokens=len(state.emitted),
                    latency_s=round(latency, 6),
                    finished_by_stop=state.stopped,
                )
            if flight is not None and status == STATUS_DEADLINE_EXCEEDED:
                flight.record(
                    "deadline", t=done_t, request=state.request_idx,
                    queued=False,
                )

        def finish_queued(req_idx: int, status: str) -> None:
            """Terminal result for a request REFUSED before admission
            (shed / queued-deadline-miss): prompt only, zero compute."""
            req = requests[req_idx]
            done = self._clock()
            done_t = done - t0
            results[req_idx] = ServeResult(
                tokens=[int(t) for t in np.asarray(
                    req.prompt, dtype=np.int32
                )],
                new_tokens=0,
                finished_by_stop=False,
                latency_s=max(0.0, done - arrive_t[req_idx]),
                status=status,
                retries=int(getattr(req, "retries", 0)),
            )
            if tracer is not None:
                tracer.event(
                    req_idx, "terminal", t=round(done_t, 6),
                    status=status, new_tokens=0,
                    latency_s=round(done_t, 6), finished_by_stop=False,
                )
            if flight is not None:
                flight.record(
                    "shed" if status == STATUS_SHED else "deadline",
                    t=done_t, request=req_idx, queued=True,
                )

        def police_deadlines() -> None:
            """Pre-admission policing: queued requests past their
            deadline terminate ``deadline_exceeded`` (nobody is waiting
            for the answer), and requests queued longer than
            ``max_queue_delay_s`` shed — neither should consume a row.
            FIFO order of the survivors is untouched."""
            nonlocal shed_count, deadline_miss_count
            now = self._clock()
            for req_idx in list(pending):
                req = requests[req_idx]
                dl = float(getattr(req, "deadline_s", 0.0) or 0.0)
                anchor = dl_anchor(req_idx)
                if dl > 0 and now - anchor >= dl:
                    pending.remove(req_idx)
                    finish_queued(req_idx, STATUS_DEADLINE_EXCEEDED)
                    deadline_miss_count += 1
                elif (self._max_queue_delay > 0
                        and now - anchor > self._max_queue_delay):
                    pending.remove(req_idx)
                    finish_queued(req_idx, STATUS_SHED)
                    shed_count += 1

        def police_depth() -> None:
            """POST-admission policing: ``max_queue_depth`` bounds the
            requests left WAITING after the engine has taken everything
            its free rows can serve this wave (shedding before admission
            would refuse work while rows sit idle). Past the bound the
            LOWEST-priority queued request sheds first (ties: the most
            recently enqueued) — an overload burst produces honest
            ``shed`` statuses instead of unbounded queue growth."""
            nonlocal shed_count, queue_depth_peak
            queue_depth_peak = max(queue_depth_peak, len(pending))
            while (self._max_queue_depth > 0
                    and len(pending) > self._max_queue_depth):
                victim_pos, victim_pri = 0, None
                for pos, req_idx in enumerate(pending):
                    pri = int(getattr(requests[req_idx], "priority", 0))
                    if victim_pri is None or pri <= victim_pri:
                        victim_pri, victim_pos = pri, pos
                victim = pending[victim_pos]
                del pending[victim_pos]
                finish_queued(victim, STATUS_SHED)
                shed_count += 1

        def register_completion_blocks(r: int, state: _RowState) -> None:
            """Decoded blocks enter the radix tree when the row releases
            — the multi-turn surface: a successor whose prompt is this
            request's full prompt + completion matches the whole chain,
            not just the prompt half (the round-6 index registered
            prompt blocks only, so multi-turn traffic always missed
            past turn one). Registrable tokens stop ONE short of the
            last emitted token: its K/V write may not have landed when
            the host noticed the row was done (a stop token on a
            chunk's final step is emitted but never fed), and an
            indexed block must be fully frozen. Every earlier emitted
            token was fed — its K/V was written with the committed
            value when its successor was produced."""
            nonlocal completion_blocks_registered
            p = plen_host[r]
            if not state.emitted or pf_ptr[r] < p:
                return  # nothing decoded, or prefill never finished
            usable = p + len(state.emitted) - 1
            n_reg = min(usable // self._block_size, len(leases[r].blocks))
            if n_reg <= indexed_upto[r]:
                return
            full = list(np.asarray(
                requests[state.request_idx].prompt, dtype=np.int32
            )) + state.emitted[:-1]
            keys = chain_keys(full, self._block_size, limit=n_reg)
            blks = leases[r].blocks
            while indexed_upto[r] < n_reg:
                if not chain_extendable(r, keys, blks):
                    break  # predecessor held by another lease
                j = indexed_upto[r]
                if alloc.register_block(
                    keys[j], blks[j], parent=keys[j - 1] if j else None
                ):
                    completion_blocks_registered += 1
                indexed_upto[r] += 1

        def release_row(r: int) -> None:
            """Free a row whose request terminated (completion, deadline
            cancellation, or drain): publish its decoded full blocks
            into the radix tree (the multi-turn surface — drained rows
            included, so a requeued request re-matches its own prior
            work), then refund its lease — the allocator parks
            shareable prefix blocks (indexed content survives for
            future hits) and frees the rest — and point the table at
            scratch so the frozen slot's rolled-back writes can't touch
            a re-allocated block."""
            state = rows[r]
            rows[r] = None
            prefill_left[r] = 0
            if self._paged and leases[r] is not None:
                if (self._prefix and self._prefix_completions
                        and state is not None):
                    register_completion_blocks(r, state)
                leases[r].release()
                leases[r] = None
                table_np[r, :] = scratch
                table_dirty[0] = True
                row_keys[r] = []
                indexed_upto[r] = 0
                pf_ptr[r] = 0

        def row_done(state: _RowState) -> bool:
            return state.stopped or len(state.emitted) >= state.budget

        def req_chain_keys(req_idx: int) -> List[bytes]:
            """The request's full-block hash-chain keys, derived once
            and cached — the ONE derivation site, shared by the policy
            ranking signal and the admission matcher so the two can
            never diverge."""
            if req_idx not in keys_cache:
                keys_cache[req_idx] = chain_keys(
                    np.asarray(requests[req_idx].prompt, dtype=np.int32),
                    self._block_size,
                )
            return keys_cache[req_idx]

        def resident_match_tokens(req_idx: int):
            """The cache-aware policy's ranking signal for ``req_idx``.

            Without a host tier this is the round-9 contract verbatim —
            a plain int of resident-matchable prompt tokens (custom
            AdmissionPolicy implementations written against it keep
            working). With the tier attached it is the TIERED
            ``(resident, spilled)`` pair (a spilled hit costs a restore
            upload, so it ranks below a resident hit but above a miss;
            runtime/scheduling.py orders lexicographically and accepts
            both forms). 0 without the prefix cache, so every policy
            degrades to FIFO there."""
            if not self._prefix:
                return 0 if host_store is None else (0, 0)
            shared, skeys, matched, _ = alloc.match_prefix(
                req_chain_keys(req_idx), len(requests[req_idx].prompt)
            )
            if host_store is None:
                return matched
            spilled_tok = len(skeys) * self._block_size
            return (matched - spilled_tok, spilled_tok)

        def chain_extendable(r: int, keys, blks) -> bool:
            """Registration guard: a row may extend the radix tree only
            under a parent digest HELD BY THE ROW'S OWN BLOCK at that
            position. When another lease's block holds the predecessor
            (this row's duplicate registration was refused first-writer
            -wins — e.g. a turn-1 predecessor finished and registered
            its completion chain while this row was still prefilling
            the same content — or the position is a CoW copy whose
            original stays indexed), attaching this row's REFERENCED
            block beneath it could leave the other chain's PARKED run
            with a referenced descendant: descendant closure breaks,
            audit() fires, and leaf-first eviction could find no
            reclaimable leaf under pool pressure. Stopping keeps every
            tree edge between blocks of one publishing chain."""
            j = indexed_upto[r]
            return (j == 0
                    or alloc.index.holder(keys[j - 1]) == blks[j - 1])

        def admit_into(free_rows):
            """Fill free rows from the queue — one insert dispatch per
            wave; the prompts stream through the next chunks in-band.
            The ORDER admission tries requests is the policy's
            (runtime/scheduling.py): cache-aware ranks by longest
            resident radix-tree match (re-matched against the tree
            every wave, so deferred groups and freshly-parked
            completion chains re-rank honestly) with FIFO aging so
            nothing starves; fifo is strict arrival order. Paged: each
            admission must RESERVE its worst-case PRIVATE block count
            first (HBM-aware gate); with the prefix cache on, the
            prompt's longest cached prefix is matched first and mapped
            SHARED (refcount bumps, no reservation), and prefill starts
            past it. A pool-full refusal stops the wave — the policy's
            chosen head waits for refunds and is never overtaken within
            the order (with aging, that preserves bounded waiting). A
            prefix-DEFER skips the request — its next needed block is
            being prefilled by an active row right now, so admitting it
            would duplicate exactly the compute the cache saves; once
            the leader publishes, the whole deferred group admits
            together in one wave. Progress is guaranteed: deferral
            requires an ACTIVE prefilling row, and _validate_request
            rejects requests that exceed the whole pool outright."""
            nonlocal cache, d_cache, buf, ptr_vec, plen_vec, temp_vec
            nonlocal seed_vec
            nonlocal reserved_blocks_total, hit_tokens, hit_requests
            nonlocal cross_hit_tokens, cross_hit_requests
            nonlocal cow_copies, admission_overtakes
            nonlocal restore_hit_tokens, restore_hit_requests
            if not free_rows or not pending:
                return
            # chain keys active rows will publish soon — the deferral set
            inflight = set()
            if self._prefix:
                for r in range(b):
                    if rows[r] is not None and row_keys[r]:
                        inflight.update(row_keys[r][indexed_upto[r]:])
            arrival_pos = {idx: i for i, idx in enumerate(pending)}
            order = self._policy.order(
                list(pending), passed_over, resident_match_tokens
            )
            wave = []
            # (row, p, budget, lease, matched, cow_src, keys) per slot
            wave_meta = []
            admitted_idx = []
            deferred = set()
            # (dst block, numpy planes) per restored block this wave —
            # uploaded in fixed-width dispatches after the insert
            restore_jobs = []
            for req_idx in order:
                if not free_rows:
                    break
                req = requests[req_idx]
                prompt, p, budget = self._validate_request(req, req_idx)
                shared, skeys, matched, cow_src = [], [], 0, None
                keys: List[bytes] = []
                if self._prefix:
                    keys = req_chain_keys(req_idx)
                    shared, skeys, matched, cow_src = alloc.match_prefix(
                        keys, p
                    )
                    published = (len(shared) + len(skeys)
                                 + (1 if cow_src is not None else 0))
                    if (published < len(keys)
                            and keys[published] in inflight):
                        deferred.add(req_idx)
                        continue
                lease = None
                if self._paged:
                    need = (
                        alloc.blocks_for(self._row_cap(p, budget))
                        - len(shared) - len(skeys)
                    )
                    lease = alloc.admit(need, shared=shared,
                                        restore=skeys)
                    if lease is None:
                        break  # pool full: the policy head waits
                    reserved_blocks_total += need
                    if skeys:
                        # promotion: the allocator rebound each spilled
                        # digest to a fresh block and popped its host
                        # payload (tree and store transition together);
                        # queue the uploads — int8-demoted payloads
                        # dequantize back to the pool dtype HERE,
                        # quantized pools take theirs verbatim
                        for blk, payload, demoted in (
                            lease.restored_payloads
                        ):
                            if demoted:
                                payload = {
                                    "k": dequantize_kv_host(
                                        payload["k"], payload["k_scale"]
                                    ),
                                    "v": dequantize_kv_host(
                                        payload["v"], payload["v_scale"]
                                    ),
                                }
                            restore_jobs.append((blk, payload))
                        restore_hit_tokens += (
                            len(skeys) * self._block_size
                        )
                        restore_hit_requests += 1
                    if cow_src is not None:
                        # copy-on-write: materialize the private copy of
                        # the partially-reused block NOW (within the
                        # reservation — can't fail) and queue the device
                        # copy for right after the insert dispatch
                        lease.grow_to(len(lease.shared) + 1)
                if matched:
                    hit_tokens += matched
                    hit_requests += 1
                    depth = (len(shared) + len(skeys)
                             + (1 if cow_src is not None else 0))
                    hit_depth_hist[depth] = (
                        hit_depth_hist.get(depth, 0) + 1
                    )
                    if pre_keys:
                        # the contiguous leading run of matched digests
                        # that predate this call — tokens a PREVIOUS
                        # serve() call's work served (the radix prefix
                        # property makes the pre-existing run a prefix
                        # of the match)
                        pre_depth = 0
                        for kk in keys[:depth]:
                            if kk not in pre_keys:
                                break
                            pre_depth += 1
                        if pre_depth:
                            cross_hit_tokens += min(
                                matched, pre_depth * self._block_size
                            )
                            cross_hit_requests += 1
                row = free_rows.pop(0)
                admitted_idx.append(req_idx)
                wave.append((row, req, req_idx, prompt, p, budget, matched))
                wave_meta.append(
                    (row, p, budget, lease, matched, cow_src, keys)
                )
                # the keys THIS row will publish defer same-prefix
                # followers later in this very wave (intra-wave dedup)
                if self._prefix:
                    inflight.update(keys[
                        len(shared) + len(skeys)
                        + (1 if cow_src is not None else 0):
                    ])
            for req_idx in admitted_idx:
                pending.remove(req_idx)  # arrival order of the rest kept
            if admitted_idx:
                # aging: a still-waiting request was OVERTAKEN when a
                # later arrival was admitted ahead of it this wave;
                # after admission_aging_waves of those the policy must
                # promote it (bounded starvation). Deliberately-deferred
                # requests don't age — they are waiting on a leader, not
                # losing races.
                last_pos = max(arrival_pos[i] for i in admitted_idx)
                for req_idx in pending:
                    if (req_idx not in deferred
                            and arrival_pos[req_idx] < last_pos):
                        passed_over[req_idx] = (
                            passed_over.get(req_idx, 0) + 1
                        )
                        admission_overtakes += 1
            if (self._sanitize and alloc is not None
                    and alloc.index is not None):
                # the radix-tree invariant, asserted next to the
                # pool-partition audit (NEXUS_SANITIZE); a violation
                # trips the flight recorder for the postmortem
                try:
                    alloc.index.audit()
                except AssertionError as e:
                    trip_flight("sanitizer", {"error": str(e)})
                    raise
            if not wave:
                return
            (cache, buf, ptr_vec, plen_vec, temp_vec, seed_vec,
             admitted) = self._admit_wave(
                cache, buf, ptr_vec, plen_vec, temp_vec, seed_vec, wave,
            )
            if self._draft:
                # the admitted rows' DRAFT pointers reset to 0 (the
                # draft re-ingests each prompt teacher-forced; the
                # target may start past a prefix-cache match, the draft
                # catches up through the same frontier rule)
                d_rows = np.full((b,), b, dtype=np.int32)
                for i, (row, _st, _steps) in enumerate(admitted):
                    d_rows[i] = row
                d_cache = self._draft_reset_fn(
                    d_cache, self._mint(d_rows)
                )
            cow_pairs = []
            for (row, state, steps), (_, p, budget, lease, matched,
                                      cow_src, keys) in zip(
                admitted, wave_meta
            ):
                rows[row] = state
                prefill_left[row] = steps
                if tracer is not None:
                    # cache attribution of the admission decision: how
                    # much of the prompt the radix tree served, split
                    # resident vs host-tier-restored, plus the CoW and
                    # the private reservation the pool promised
                    restored_n = (
                        len(lease.restored_payloads) if lease else 0
                    )
                    adm_t = round(max(0.0, state.admitted_t - t0), 6)
                    tracer.event(
                        state.request_idx, "admitted", t=adm_t, row=row,
                        queue_s=adm_t, prompt_tokens=p, budget=budget,
                        matched_tokens=matched,
                        shared_blocks=(
                            len(lease.shared) - restored_n if lease
                            else 0
                        ),
                        restored_blocks=restored_n,
                        cow_copy=cow_src is not None,
                        reserved_blocks=(
                            lease.reservation if lease else 0
                        ),
                    )
                if self._paged:
                    leases[row] = lease
                    caps[row] = self._row_cap(p, budget)
                    plen_host[row] = p
                    table_np[row, :] = scratch
                    # the shared prefix (and the CoW copy, if any) must
                    # be in the table BEFORE the first chunk reads it —
                    # grow_and_push_tables only writes on GROWTH
                    mapped = lease.blocks
                    if mapped:
                        table_np[row, : len(mapped)] = mapped
                    table_dirty[0] = True
                    row_keys[row] = keys
                    indexed_upto[row] = len(lease.shared) + (
                        1 if cow_src is not None else 0
                    )
                    pf_ptr[row] = matched
                    if cow_src is not None:
                        cow_pairs.append(
                            (cow_src, lease.blocks[len(lease.shared)])
                        )
            if cow_pairs:
                # one tiny dispatch copies every CoW block of the wave;
                # ordering is the device stream's — the copy lands
                # before the next chunk program reads the copies
                src = np.full((b,), self._num_blocks + 1, dtype=np.int32)
                dst = np.full((b,), self._num_blocks + 1, dtype=np.int32)
                for i, (s_, d_) in enumerate(cow_pairs):
                    src[i], dst[i] = s_, d_
                cache = self._copy_fn(
                    cache, self._mint(src), self._mint(dst)
                )
                cow_copies += len(cow_pairs)
            if restore_jobs:
                # promotion upload: ONE fixed-shape dispatch per wave
                # (a wave restoring more blocks than the width loops
                # the same compiled program) scatters every restored
                # host payload into its freshly-allocated block —
                # stream ordering lands it before the next chunk reads,
                # exactly like the CoW copy above. Unused slots carry
                # an out-of-range id and drop.
                W = self._restore_wave
                for j0 in range(0, len(restore_jobs), W):
                    batch = restore_jobs[j0:j0 + W]
                    ids = np.full((W,), self._num_blocks + 1, np.int32)
                    planes = self._restore_plane_zeros(cache, W)
                    for i, (blk, payload) in enumerate(batch):
                        ids[i] = blk
                        for k_ in planes:
                            planes[k_][:, i] = np.asarray(
                                payload[k_]
                            ).astype(planes[k_].dtype, copy=False)
                    with dispatch_annotation("nexus.serve.restore_upload"):
                        cache = self._restore_write_fn(
                            cache, self._mint(ids),
                            {k_: self._mint(v_)
                             for k_, v_ in planes.items()},
                        )
            if flight is not None:
                flight.record(
                    "admission", t=self._clock() - t0,
                    n=len(admitted), queue_depth=len(pending),
                    policy=self._policy.name,
                    aged=int(getattr(
                        self._policy, "last_wave_meta", {}
                    ).get("aged", 0)),
                    restores=len(restore_jobs), cow=len(cow_pairs),
                )

        src = source

        def poll_source() -> int:
            """Drain due arrivals from the stream into the wait queue —
            requests, results, arrival stamps, and (when tracing) a
            fresh per-request timeline all grow in lock-step. Returns
            how many arrived; they admit at this wave's boundary like
            any other queued request."""
            nonlocal streamed
            if src is None:
                return 0
            new = src.poll(self._clock() - t0)
            for req in new:
                idx = len(requests)
                requests.append(req)
                results.append(None)
                arrive_t.append(
                    t0 + float(getattr(req, "arrival_s", 0.0) or 0.0)
                )
                pending.append(idx)
                streamed += 1
                if tracer is not None:
                    tracer.extend(
                        journey=str(getattr(req, "journey", "") or "")
                    )
                    tracer.event(
                        idx, "enqueued",
                        t=round(max(0.0, arrive_t[idx] - t0), 6),
                        prompt_tokens=len(req.prompt),
                        max_new_tokens=int(req.max_new_tokens),
                    )
            return len(new)

        def ext_pending() -> int:
            """Backlog OUTSIDE the in-call wait queue: arrived-but-
            unpolled stream events plus whatever the caller's own queue
            (a fleet replica's inbox) reports. The serve_queue_depth
            live gauge folds this in so the autoscaler and p2c spill
            read the real stream, not just this wave's snapshot."""
            n = 0
            if src is not None:
                n += int(src.due(self._clock() - t0))
            if ext_backlog is not None:
                n += int(ext_backlog())
            return n

        def source_live() -> bool:
            return src is not None and not src.exhausted()

        police_deadlines()
        admit_into([r for r in range(b) if rows[r] is None])
        police_depth()
        if shed_count + deadline_miss_count >= self._storm_threshold:
            # the arrival burst itself overflowed the bounded queue —
            # the t0 flavor of a shed storm
            trip_flight(
                "shed_storm" if shed_count >= deadline_miss_count
                else "deadline_storm",
                {"wave": 0, "shed": shed_count,
                 "deadline": deadline_miss_count},
            )

        while (any(r is not None for r in rows) or pending
                or source_live()):
            if cancel is not None and cancel.cancelled():
                # engine death / fencing: stop at the wave boundary,
                # snapshot every unfinished request (committed tokens
                # preserved — they are an exact prefix of the full
                # completion, so the failover planner can fold them into
                # the requeued prompt), and refund every KV lease so the
                # pool partitions cleanly into free + parked
                elapsed = max(0.0, self._clock() - t0)
                drained: List[DrainedRequest] = []
                for r in range(b):
                    state = rows[r]
                    if state is None:
                        continue
                    drained.append(DrainedRequest(
                        request_idx=state.request_idx,
                        committed=list(state.emitted),
                        admitted=True,
                        elapsed_s=elapsed,
                    ))
                    if tracer is not None:
                        tracer.event(
                            state.request_idx, "drained", t=elapsed,
                            committed_tokens=len(state.emitted),
                            admitted=True,
                        )
                    if flight is not None:
                        flight.record(
                            "drain_request", t=elapsed,
                            request=state.request_idx,
                            committed=len(state.emitted), admitted=True,
                        )
                    release_row(r)
                for req_idx in pending:
                    drained.append(DrainedRequest(
                        request_idx=req_idx, elapsed_s=elapsed,
                    ))
                    if tracer is not None:
                        tracer.event(
                            req_idx, "drained", t=elapsed,
                            committed_tokens=0, admitted=False,
                        )
                    if flight is not None:
                        flight.record(
                            "drain_request", t=elapsed, request=req_idx,
                            committed=0, admitted=False,
                        )
                pending.clear()
                self.last_drain = drained
                # the failover postmortem: freeze the recent waves with
                # the drained cohort stamped into the trip detail (the
                # chaos test cross-checks dump tail vs drained set)
                trip_flight("drain", {
                    "wave": chunks,
                    "drained": [d.request_idx for d in drained],
                })
                interrupted = True
                break
            if src is not None:
                poll_source()
            if not any(r is not None for r in rows):
                # every row idle: admit whatever just arrived; when the
                # stream still has deliveries coming, WAIT for the next
                # one (real sleep, or an injected clock's advance)
                # instead of returning with the trace half-served
                police_deadlines()
                admit_into([r for r in range(b) if rows[r] is None])
                police_depth()
                if not any(r is not None for r in rows):
                    if not source_live():
                        break
                    if heartbeat is not None:
                        heartbeat(committed)
                    if gauges is not None:
                        # idle gaps still publish: the autoscaler must
                        # see an empty engine with a building backlog
                        gauges.publish(
                            queue_depth=len(pending) + ext_pending(),
                            running_rows=0,
                            free_pool_blocks=(
                                alloc.free_blocks if alloc else 0
                            ),
                            host_cache_bytes=(
                                host_store.bytes
                                if host_store is not None else 0
                            ),
                            committed_tokens=committed, waves=chunks,
                        )
                    src.wait(self._clock() - t0)
                    continue
            if self._paged:
                # map the blocks this dispatch can touch, then sample the
                # pool's residency for the bytes-per-token metric
                grow_and_push_tables()
                alloc_block_steps += alloc.allocated_blocks
            shared_s, shared_ops = detect_shared_run()
            if shared_s:
                hydragen_waves += 1
                hydragen_shared_slots += shared_s
            done_vec = self._mint(
                np.asarray([r is None or row_done(r) for r in rows]),
                jnp.bool_,
            )
            if self._spec:
                with dispatch_annotation("nexus.serve.spec_chunk"):
                    if self._draft:
                        (cache, d_cache, tok_vec, ptr_vec, buf, outs,
                         accs, n_emits, actives) = self._spec_chunk(
                            self._params, self._draft_params, cache,
                            d_cache, tok_vec, ptr_vec, done_vec, buf,
                            plen_vec, *shared_ops,
                        )
                    else:
                        (cache, tok_vec, ptr_vec, buf, outs, accs,
                         n_emits, actives) = self._spec_chunk(
                            self._params, cache, tok_vec, ptr_vec,
                            done_vec, buf, plen_vec, *shared_ops,
                        )
                chunks += 1
                # one verify scores k+1 positions; utilization over them
                # is acceptance-sensitive by design
                scheduled_slots += self._rounds * (self._k + 1) * b
                (host_outs, host_accs, host_emits,
                 host_actives) = jax.device_get(
                    (outs, accs, n_emits, actives)
                )  # one batched fetch: (R,B,k+1), (R,B) x3
                pf_advance = self._rounds * (self._k + 1)
            else:
                chunk_fn = (
                    self._decode_chunk
                    if any(
                        prefill_left[r] > 0
                        for r in range(b) if rows[r] is not None
                    )
                    else self._decode_chunk_narrow
                )
                with dispatch_annotation("nexus.serve.decode_chunk"):
                    cache, tok_vec, ptr_vec, toks, emits = chunk_fn(
                        self._params, cache, tok_vec, ptr_vec, done_vec,
                        buf, plen_vec, temp_vec, seed_vec, *shared_ops,
                    )
                chunks += 1
                scheduled_slots += self._chunk * b
                # one batched device→host fetch (each np.asarray would
                # pay its own tunnel round-trip)
                host_toks, host_emits = jax.device_get((toks, emits))
                pf_advance = self._chunk * (
                    self._t if chunk_fn is self._decode_chunk else 1
                )
                for r in range(b):
                    prefill_left[r] = max(0, prefill_left[r] - self._chunk)
            now = self._clock()
            if heartbeat is not None:
                # wave-boundary liveness: the serve-side analogue of the
                # Trainer's on_step renew — committed tokens play the
                # step counter (the lease's progress record)
                heartbeat(committed)
            if self._prefix:
                # mirror each row's prefill pointer exactly (per step a
                # prefilling row advances by min(width, remaining), so a
                # whole dispatch advances by min(dispatch width·steps,
                # remaining)), then PUBLISH every prompt block the
                # dispatch finished writing — from that instant the
                # block is matchable by new admissions
                for r in range(b):
                    if rows[r] is None or leases[r] is None:
                        continue
                    if pf_ptr[r] < plen_host[r]:
                        pf_was = pf_ptr[r]
                        pf_ptr[r] = min(
                            plen_host[r], pf_ptr[r] + pf_advance
                        )
                        if tracer is not None and pf_ptr[r] > pf_was:
                            tracer.event(
                                rows[r].request_idx, "prefill_chunk",
                                t=round(now - t0, 6), row=r,
                                wave=chunks, from_pos=pf_was,
                                to_pos=pf_ptr[r],
                            )
                    pub = min(
                        pf_ptr[r] // self._block_size, len(row_keys[r])
                    )
                    blks = leases[r].blocks
                    while indexed_upto[r] < pub:
                        if not chain_extendable(r, row_keys[r], blks):
                            break  # predecessor held by another lease
                        j = indexed_upto[r]
                        alloc.register_block(
                            row_keys[r][j], blks[j],
                            parent=row_keys[r][j - 1] if j else None,
                        )
                        indexed_upto[r] += 1
            shed_wave0 = shed_count
            miss_wave0 = deadline_miss_count
            for r in range(b):
                state = rows[r]
                if state is None:
                    continue
                row_n0 = len(state.emitted)
                row_accepted = 0
                row_rounds = 0
                if self._spec:
                    for ri in range(self._rounds):
                        if row_done(state):
                            break
                        if host_actives[ri, r]:
                            target_forwards += 1
                            drafted += self._k
                            accepted_total += int(host_accs[ri, r])
                            row_accepted += int(host_accs[ri, r])
                            row_rounds += 1
                        for t in host_outs[ri, r, :int(host_emits[ri, r])]:
                            if row_done(state):
                                break
                            if not state.emitted:
                                state.first_tok_t = now
                            state.emitted.append(int(t))
                            if self._stop >= 0 and int(t) == self._stop:
                                state.stopped = True
                else:
                    for c in range(self._chunk):
                        if row_done(state):
                            break
                        if not host_emits[c, r]:
                            continue  # the row was prefilling this step
                        t = int(host_toks[c, r])
                        if not state.emitted:
                            state.first_tok_t = now
                        state.emitted.append(t)
                        if self._stop >= 0 and t == self._stop:
                            state.stopped = True
                if tracer is not None:
                    row_delta = len(state.emitted) - row_n0
                    if row_n0 == 0 and row_delta > 0:
                        tracer.event(
                            state.request_idx, "first_token",
                            t=round(now - t0, 6), row=r, wave=chunks,
                            ttft_s=round(max(
                                0.0, state.first_tok_t - state.admitted_t
                            ), 6),
                        )
                    if row_delta > 0:
                        # plain decode: every committed token was one
                        # scheduled slot (accepted == tokens, rejected
                        # 0); speculative rows attribute the round's
                        # accept/reject split
                        rej = (
                            max(0, row_rounds * self._k - row_accepted)
                            if self._spec else 0
                        )
                        tracer.event(
                            state.request_idx, "decode_wave",
                            t=round(now - t0, 6), row=r, wave=chunks,
                            tokens=row_delta,
                            accepted=(row_accepted if self._spec
                                      else row_delta),
                            rejected=rej,
                        )
                if row_done(state):
                    finish(state)
                    release_row(r)
                    continue
                dl = float(getattr(
                    requests[state.request_idx], "deadline_s", 0.0
                ) or 0.0)
                if dl > 0 and now - dl_anchor(state.request_idx) >= dl:
                    # deadline cancellation at the wave boundary: report
                    # the partial completion honestly, free the lease
                    # (shareable prefix blocks PARK for future hits —
                    # the cancelled work's K/V is not wasted), and hand
                    # the row to the next queued request
                    finish(state, status=STATUS_DEADLINE_EXCEEDED)
                    deadline_cancelled_rows += 1
                    deadline_miss_count += 1
                    release_row(r)
            # reap expired waiters, admit into every row this chunk
            # freed (ONE insert wave, no forward), then bound what is
            # STILL waiting — depth shedding never refuses work a free
            # row could have taken this wave
            police_deadlines()
            admit_into([r for r in range(b) if rows[r] is None])
            police_depth()
            # ---- wave-boundary observability (round 12) ----
            shed_d = shed_count - shed_wave0
            miss_d = deadline_miss_count - miss_wave0
            if shed_d + miss_d >= self._storm_threshold:
                # a deadline/shed STORM: one boundary terminated a
                # burst of requests — exactly when the end-of-run dict
                # is least useful, so freeze the recent waves now
                trip_flight(
                    "shed_storm" if shed_d >= miss_d
                    else "deadline_storm",
                    {"wave": chunks, "shed": shed_d, "deadline": miss_d},
                )
            if flight is not None or gauges is not None:
                live_rows = sum(1 for s in rows if s is not None)
                free_blocks = alloc.free_blocks if alloc else 0
                host_bytes = (
                    host_store.bytes if host_store is not None else 0
                )
            if flight is not None:
                # fresh stamp, not the pre-boundary `now`: finish/shed/
                # admission events recorded above carry later clock
                # reads, and the ring's time axis must not run
                # backwards within one boundary's seq order
                flight.record(
                    "wave", t=self._clock() - t0, wave=chunks,
                    queue_depth=len(pending), running_rows=live_rows,
                    committed=committed, free_blocks=free_blocks,
                    spills=alloc.spills if alloc else 0,
                    restores=alloc.restores if alloc else 0,
                    evictions=alloc.evictions if alloc else 0,
                    host_bytes=host_bytes,
                )
            if gauges is not None:
                gauges.publish(
                    queue_depth=len(pending) + ext_pending(),
                    running_rows=live_rows,
                    free_pool_blocks=free_blocks,
                    host_cache_bytes=host_bytes,
                    committed_tokens=committed, waves=chunks,
                )
        wall = self._clock() - t0
        # ownership back to the engine: the pool (parked prefix
        # payloads included) survives for the next call's cross-call
        # hits — on interrupt too, since the drain released every lease
        # and the partition is clean. The request list (streamed
        # arrivals included) is what the post-serve audits iterate.
        self._kv_cache = cache
        self._serve_calls += 1
        self.last_requests = requests
        if flight is not None and not interrupted:
            flight.record("run_end", t=wall, committed=committed)
        _pctl = percentile_nearest_rank
        metrics = {
            "requests": len(requests),
            "committed_tokens": committed,
            "scheduled_step_slots": scheduled_slots,
            "slot_utilization": (
                round(committed / scheduled_slots, 4)
                if scheduled_slots else 1.0
            ),
            "decode_chunks": chunks,
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(committed / wall, 2) if wall else 0.0,
            "insert_dispatches": self._insert_dispatches,
            "prefill_steps": self._prefill_steps,
            "prefill_chunk": (
                (self._k + 1) if self._spec else self._t
            ),
            # ---- robustness ledger (round 7) ----
            "interrupted": interrupted,
            "queue_depth_peak": queue_depth_peak,
            "shed_requests": shed_count,
            "shed_rate": (
                round(shed_count / len(requests), 4) if requests else 0.0
            ),
            "deadline_miss_requests": deadline_miss_count,
            "deadline_miss_rate": (
                round(deadline_miss_count / len(requests), 4)
                if requests else 0.0
            ),
            "deadline_cancelled_rows": deadline_cancelled_rows,
            "ok_requests": sum(
                1 for res in results
                if res is not None and res.status == STATUS_OK
            ),
            # ---- admission scheduling (round 9) ----
            "admission_policy": self._policy.name,
            # admissions that jumped ahead of an older waiting request
            # (0 under fifo, and under cache-aware whenever the cache
            # ranking agrees with arrival order)
            "admission_overtakes": admission_overtakes,
            # ---- observability ledger (round 12, nexus_tpu/obs/) ----
            "traced": tracer is not None,
            "flight_recorder_events": (
                flight.events_recorded if flight is not None else 0
            ),
            "flight_dumps": len(tripped),
            "live_gauge_publishes": (
                gauges.publishes if gauges is not None else 0
            ),
            # ---- engine-lifetime / open-loop ledger (round 16) ----
            # serve calls this ENGINE has completed (this one included)
            # — cross-call reuse is only possible past 1; cache_resets
            # counts reset_cache() wipes; streamed_requests arrived via
            # the source mid-run (0 = pure closed-loop)
            "engine_serve_calls": self._serve_calls,
            "cache_resets": self.cache_resets,
            "streamed_requests": streamed,
        }
        # admission → first committed token (chunk-granular) and
        # enqueue → admission waits, per request — OMITTED when no
        # request was served at all (an all-shed round must not report a
        # perfect p95; percentile_nearest_rank returns NaN on empties)
        if ttfts:
            metrics["ttft_p50_s"] = round(_pctl(ttfts, 0.50), 4)
            metrics["ttft_p95_s"] = round(_pctl(ttfts, 0.95), 4)
        if queues:
            metrics["queue_p50_s"] = round(_pctl(queues, 0.50), 4)
            metrics["queue_p95_s"] = round(_pctl(queues, 0.95), 4)
        # ---- KV-cache economics (the paged-vs-dense ledger) ----
        # bytes-per-request compares what one admitted request COSTS the
        # cache: its block reservation (paged) vs a whole max_len row
        # (dense); bytes-per-committed-token integrates actual residency
        # over the run's dispatches. Dense numbers use the same formulas
        # so an A/B of the two layouts reads off directly.
        block_bytes = pos_bytes * (self._block_size or 0)
        dense_row_bytes = pos_bytes * max_len
        metrics["kv_layout"] = "paged" if self._paged else "dense"
        metrics["kv_dense_bytes_per_request"] = dense_row_bytes
        if self._paged:
            # which table-read implementation served (the r8 A/B knob)
            # and the Hydragen ledger: how many dispatches ran with a
            # shared-prefix run and how many block-slots of per-row
            # gather+score work the decomposition replaced with the
            # once-per-wave batched prefix computation
            metrics["attention_path"] = self._attn_path
            if self._fused:
                metrics["hydragen_waves"] = hydragen_waves
                metrics["hydragen_shared_slots"] = hydragen_shared_slots
            metrics["kv_block_size"] = self._block_size
            metrics["kv_num_blocks"] = self._num_blocks
            metrics["kv_pool_bytes"] = (self._num_blocks + 1) * block_bytes
            metrics["kv_peak_allocated_blocks"] = alloc.peak_allocated
            metrics["kv_peak_allocated_bytes"] = (
                alloc.peak_allocated * block_bytes
            )
            metrics["kv_bytes_per_request"] = (
                round(reserved_blocks_total * block_bytes / len(requests), 1)
                if requests else 0.0
            )
            metrics["kv_bytes_per_committed_token"] = (
                round(alloc_block_steps * block_bytes / committed, 1)
                if committed else 0.0
            )
            # end-of-run pool partition (the leak audit's ground truth):
            # free + parked + allocated must equal the pool, and with
            # every lease terminal — completion, cancellation, or drain
            # — allocated and reserved must both be 0
            part = alloc.pool_partition()
            metrics["kv_free_blocks_final"] = part["free"]
            metrics["kv_parked_blocks_final"] = part["parked"]
            metrics["kv_allocated_blocks_final"] = part["allocated"]
            metrics["kv_reserved_blocks_final"] = part["reserved"]
            metrics["prefix_cache"] = self._prefix
            if self._prefix:
                # the tentpole ledger: tokens whose prefill compute AND
                # K/V writes were skipped, the step-slots that saving
                # translates to at this feed width, and the CoW /
                # eviction traffic behind it
                metrics["prefix_hit_tokens"] = hit_tokens
                metrics["prefix_hit_requests"] = hit_requests
                # cross-call share (round 16): hits served by digests a
                # PREVIOUS serve() call indexed — 0 on a cold engine by
                # construction, the warm-vs-cold A/B's headline number
                metrics["prefix_hit_tokens_cross_call"] = (
                    cross_hit_tokens
                )
                metrics["prefix_hit_requests_cross_call"] = (
                    cross_hit_requests
                )
                metrics["prefix_prefill_steps_saved"] = (
                    self._prefill_steps_saved
                )
                metrics["prefix_cow_copies"] = cow_copies
                metrics["prefix_evictions"] = alloc.evictions
                metrics["prefix_cached_blocks_final"] = (
                    alloc.cached_blocks
                )
                # radix-tree ledger (round 9): hit counts by matched
                # tree depth (in blocks — multi-turn successors hit
                # DEEP, cold requests are absent) and how many decoded
                # completion blocks entered the tree at release
                metrics["prefix_hit_depth_hist"] = dict(
                    sorted(hit_depth_hist.items())
                )
                metrics["prefix_completion_blocks"] = (
                    completion_blocks_registered
                )
                # host-tier ledger (round 10): demotion/promotion
                # traffic and the store's residency — spilled_blocks is
                # total demotions (evictions that kept their content),
                # restore_hit_tokens the prompt tokens served by
                # swapping spilled blocks back instead of recomputing
                metrics["host_cache_enabled"] = host_store is not None
                if host_store is not None:
                    hs = host_store.stats()
                    metrics["spilled_blocks"] = alloc.spills
                    metrics["restored_blocks"] = alloc.restores
                    metrics["restore_hit_tokens"] = restore_hit_tokens
                    metrics["restore_hit_requests"] = (
                        restore_hit_requests
                    )
                    metrics["host_cache_bytes"] = hs["bytes"]
                    metrics["host_cache_bytes_peak"] = hs["bytes_peak"]
                    metrics["host_cache_dtype"] = host_store.dtype
                    metrics["host_cache_evictions"] = (
                        alloc.host_evictions
                    )
                    # the spilled tier's partition slot: entries still
                    # demoted at teardown (tree ⟺ store, the sanitizer
                    # cross-checks) — like parked blocks, they survive
                    # the run for future hits
                    metrics["kv_spilled_blocks_final"] = (
                        alloc.index.spilled_count
                    )
                    metrics["host_cache_entries_final"] = hs["entries"]
        else:
            metrics["kv_pool_bytes"] = b * dense_row_bytes
            metrics["kv_bytes_per_request"] = dense_row_bytes
            metrics["kv_bytes_per_committed_token"] = (
                round(chunks * b * dense_row_bytes / committed, 1)
                if committed else 0.0
            )
        metrics["kv_reduction_vs_dense"] = (
            round(dense_row_bytes / metrics["kv_bytes_per_request"], 3)
            if metrics["kv_bytes_per_request"] else 1.0
        )
        # ---- speculation ledger (rounds 3/11) ----
        # decode_dispatches_per_committed_token is THE spec-decoding
        # cost metric: target verify forwards spent per token that
        # actually COMMITTED (drafted-then-rejected tokens are pure
        # cost, never output — they appear here as a ratio > the ideal
        # 1/(k+1), never as throughput). Plain decode is 1.0 by
        # construction — every committed token is exactly one scheduled
        # forward step of its row — so the A/B leg reads off directly:
        # < 1.0 means speculation beats one-forward-per-token.
        if self._spec:
            metrics["speculative_kind"] = (
                "draft_model" if self._draft else "prompt_lookup"
            )
            if self._lookup:
                metrics["prompt_lookup_ngram"] = self._lookup
            metrics["num_speculative"] = self._k
            metrics["target_forwards"] = target_forwards
            metrics["acceptance_rate"] = (
                round(accepted_total / drafted, 4) if drafted else 0.0
            )
            metrics["accepted_per_round"] = (
                round(accepted_total / target_forwards, 4)
                if target_forwards else 0.0
            )
            metrics["decode_dispatches_per_committed_token"] = (
                round(target_forwards / committed, 4) if committed
                else 0.0
            )
        else:
            metrics["decode_dispatches_per_committed_token"] = (
                1.0 if committed else 0.0
            )
        return results, metrics
