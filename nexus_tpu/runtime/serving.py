"""Continuous-batching serving engine (BASELINE config #3).

Static-batch decode (``autoregressive_generate``) holds every sequence
until the LAST one finishes: a batch mixing a 10-token reply with a
1000-token reply wastes ~half its step-slots, and new requests wait for
the whole batch to drain. This engine serves a REQUEST QUEUE through a
fixed-shape decode batch instead — iteration-level scheduling:

  * the KV cache runs VECTOR lengths (per-row depths, the same
    models/decoding.py scaffold that batched speculation uses), so every
    row decodes at its own position with its own causal mask and rows
    never interact;
  * when a row finishes (stop token or budget), the engine PREFILLS the
    next queued request into a single-row cache and scatters it into the
    freed row between decode chunks — admission never recompiles the
    decode step (prompt lengths are bucketed so prefill compiles once
    per bucket, not once per length);
  * decode runs in chunks of ``chunk`` steps under one dispatch
    (``lax.scan``), the host inspects the emitted tokens at chunk
    boundaries — the scheduling granularity / dispatch overhead
    trade-off. Finished rows inside a chunk roll their cache pointer
    back each step (their write is overwritten next step), so a drained
    row idles safely at fixed depth regardless of how long it stays
    empty.

Exactness contract: a request's output is a function of the request
alone — never of its row, its batch co-residents, or the engine's batch
size. At temperature 0 that is EXACTLY the model's greedy decode of the
prompt in isolation (tests/test_serving.py proves it against
``autoregressive_generate`` row for row); at temperature > 0 the
sampling key is (request seed, buffer position), so the sampled stream
is reproducible and batch-invariant (also tested). Continuous batching
changes only WHEN work is scheduled, never what is computed.

TPU-shaped: one compiled decode step for the whole serve loop (static
shapes), one compiled prefill per prompt-length bucket, admission =
one scatter. The fp KV-cache layout only (the int8 cache's scale planes
would double the insert surface; quantized serving stays on the static
path for now).

Known limitation: admission prefill SERIALIZES with decode — while a
freed row's next request prefills, the other rows idle (one device, one
program at a time). At high turnover with long prompts this caps
utilization; the next step would be chunked prefill (interleaving
prompt chunks into decode dispatches), which changes the chunk program
and is not yet worth its complexity at the measured utilizations
(89% at 4 rows, docs/PERF.md).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from nexus_tpu.models.decoding import init_kv_cache

PREFILL_BUCKET = 64  # prompt lengths round up to this (compile-count bound)


@dataclass
class ServeRequest:
    """One queued generation request.

    ``temperature > 0`` samples instead of argmax. The sampling key for
    the token at buffer position ``pos`` is
    ``fold_in(fold_in(engine_base_key, seed), pos)`` — a function of the
    request alone, NOT of scheduling — so a request's output is
    identical whatever row it lands in, whoever its batch co-residents
    are, and whatever the engine's batch size is (the same
    batch-invariance contract as greedy, tested in test_serving.py).
    Plain temperature only (top-k/top-p truncation stays on the static
    path)."""

    prompt: Sequence[int]
    max_new_tokens: int = 128
    temperature: float = 0.0
    seed: int = 0


@dataclass
class ServeResult:
    """Completed request: prompt + generated ids (stop token included when
    one was hit), plus per-request latency from serve() start."""

    tokens: List[int]
    new_tokens: int
    finished_by_stop: bool
    latency_s: float


@dataclass
class _RowState:
    request_idx: int
    budget: int
    emitted: List[int] = field(default_factory=list)
    stopped: bool = False


class ServingEngine:
    def __init__(
        self,
        forward_decode: Callable,
        params: Any,
        cfg: Any,
        batch_size: int,
        max_len: Optional[int] = None,
        stop_token_id: int = -1,
        chunk: int = 8,
        cache_sharding: Optional[Any] = None,
        sample_seed: int = 0,
        lookup_ngram: int = 0,
        num_speculative: int = 4,
    ):
        """``lookup_ngram > 0`` switches the decode chunks to SPECULATIVE
        rounds: each round proposes ``num_speculative`` tokens by n-gram
        prompt lookup from the row's own committed text (the engine keeps
        a device-side token buffer per row), verifies them in ONE
        ``k+1``-wide target forward, and commits the accepted prefix —
        models/decoding.py's draft-free speculation running under
        continuous batching. Greedy-exact: outputs equal the plain
        engine's token for token (tested); a chunk runs
        ``ceil(chunk / (k+1))`` rounds so its committed-token budget
        matches a plain chunk's. Greedy only (requests with
        temperature > 0 are rejected at admission)."""
        if getattr(cfg, "kv_cache_quantized", False):
            raise ValueError(
                "ServingEngine supports the fp KV cache only; unset "
                "kv_cache_quantized (int8 serving: use the static batch path)"
            )
        self._fwd = forward_decode
        self._params = params
        self._cfg = cfg
        self._b = int(batch_size)
        self._max_len = int(max_len or cfg.max_seq_len)
        if self._max_len > cfg.max_seq_len:
            raise ValueError(
                f"max_len {self._max_len} exceeds model max_seq_len "
                f"{cfg.max_seq_len}"
            )
        self._stop = int(stop_token_id)
        self._chunk = int(chunk)
        self._cache_sharding = cache_sharding
        self._prefill_cache: Dict[Any, Callable] = {}
        self._warmed: Dict[int, set] = {}  # bucket -> compiled group sizes
        self._prefill_dispatches = 0
        self._base_key = jax.random.PRNGKey(int(sample_seed))
        self._lookup = int(lookup_ngram)
        self._k = int(num_speculative)
        if self._lookup and self._k < 1:
            raise ValueError(
                f"num_speculative must be >= 1, got {self._k}"
            )
        # rounds per dispatch: one round = one target forward committing
        # 1..k+1 tokens, so this keeps a spec chunk's committed-token
        # budget comparable to a plain chunk's C single-token steps
        self._rounds = max(1, -(-self._chunk // (self._k + 1)))
        # worst-case growth past a row's finish inside one dispatch: the
        # host only re-evaluates done-ness at chunk boundaries. The ONE
        # formula shared with ServeSpec.serve_slack() — spec validation
        # and the engine's admission rule can't diverge.
        from nexus_tpu.api.runtime_spec import serve_dispatch_slack

        self._slack = serve_dispatch_slack(
            self._chunk, self._lookup, self._k
        )

        cfg_ = cfg
        fwd = forward_decode
        C = self._chunk
        base_key = self._base_key

        def _pick(logits_row, temp, seed, pos):
            """Per-row token choice: argmax at temp 0, else a categorical
            sample keyed by (request seed, absolute buffer position) —
            scheduling never enters the key, so sampling is
            batch-invariant."""
            key = jax.random.fold_in(jax.random.fold_in(base_key, seed), pos)
            safe_t = jnp.maximum(temp, 1e-6)
            sampled = jax.random.categorical(key, logits_row / safe_t)
            return jnp.where(
                temp > 0.0, sampled, jnp.argmax(logits_row, axis=-1)
            ).astype(jnp.int32)

        def _decode_chunk(params, cache, tok, done, temp, seed):
            """C decode steps in ONE dispatch. ``done`` rows emit their
            held token and roll their pointer back each step (the write
            lands on the same slot next step — no growth, no overflow)."""

            def step(carry, _):
                cache, tok, done = carry
                logits, cache2 = fwd(params, cfg_, tok[:, None], cache)
                cache2 = dict(cache2)
                cache2["length"] = jnp.where(
                    done, cache["length"], cache2["length"]
                )
                # the sampled token's buffer position is the post-feed
                # length — the key input that makes sampling positional
                nxt = jax.vmap(_pick)(
                    logits[:, -1], temp, seed, cache2["length"]
                ).astype(tok.dtype)
                nxt = jnp.where(done, tok, nxt)
                return (cache2, nxt, done), nxt

            (cache, tok, done), toks = lax.scan(
                step, (cache, tok, done), None, length=C
            )
            return cache, tok, toks  # toks: (C, B)

        self._pick = _pick

        def _insert(cache, row, row_k, row_v, length, tok_vec, first_tok,
                    temp_vec, req_temp, seed_vec, req_seed):
            """Scatter one prefilled request into a freed batch row."""
            cache = dict(cache)
            cache["k"] = cache["k"].at[:, row].set(row_k[:, 0])
            cache["v"] = cache["v"].at[:, row].set(row_v[:, 0])
            cache["length"] = cache["length"].at[row].set(length)
            return (
                cache,
                tok_vec.at[row].set(first_tok),
                temp_vec.at[row].set(req_temp),
                seed_vec.at[row].set(req_seed),
            )

        # ---- speculative (prompt-lookup) variants ----
        k_spec, g_spec, R = self._k, self._lookup, self._rounds
        rows_idx = jnp.arange(self._b)

        def _spec_chunk(params, cache, tok, done, buf):
            """R speculative rounds in ONE dispatch: propose k by n-gram
            lookup in each row's committed text, verify in one k+1-wide
            forward, commit the accepted prefix (models/decoding.py's
            prompt-lookup round under per-row freezing)."""
            from nexus_tpu.models.decoding import (
                _commit_speculation,
                _greedy_accept,
                prompt_lookup_propose,
            )

            max_len_ = buf.shape[1]

            def round_(carry, _):
                cache, tok, done, buf = carry
                last_pos = cache["length"]  # (B,) == tok's buffer position
                proposals, _found = prompt_lookup_propose(
                    buf, last_pos, k_spec, g_spec
                )
                block = jnp.concatenate([tok[:, None], proposals], axis=1)
                logits, cache2 = fwd(params, cfg_, block, cache)
                target_choice = jnp.argmax(logits, axis=-1).astype(tok.dtype)
                accepted, out = _greedy_accept(proposals, target_choice)
                accepted = jnp.where(done, 0, accepted)
                # commit + rollback-by-pointer via the SHARED helper (the
                # subtle invariants — frozen-row scatter drop, correction
                # token's K/V arriving on the next feed — live in
                # models/decoding.py, once)
                buf, _n_new, new_len = _commit_speculation(
                    buf, rows_idx, last_pos, ~done, accepted, out, k_spec,
                    max_len_, cache["length"],
                )
                new_tok = jnp.where(done, tok, out[rows_idx, accepted])
                cache2 = dict(cache2)
                cache2["length"] = new_len
                return (cache2, new_tok, done, buf), (out, accepted)

            (cache, tok, done, buf), (outs, accs) = lax.scan(
                round_, (cache, tok, done, buf), None, length=R
            )
            return cache, tok, buf, outs, accs  # (R, B, k+1), (R, B)

        def _insert_spec(cache, row, row_k, row_v, length, tok_vec,
                         first_tok, temp_vec, req_temp, seed_vec, req_seed,
                         buf, prompt_row):
            cache, tok_vec, temp_vec, seed_vec = _insert(
                cache, row, row_k, row_v, length, tok_vec, first_tok,
                temp_vec, req_temp, seed_vec, req_seed,
            )
            buf = buf.at[row].set(prompt_row)
            buf = buf.at[row, length].set(first_tok)
            return cache, tok_vec, temp_vec, seed_vec, buf

        # donate the cache (and the token vector in insert): XLA updates
        # the K/V buffers in place instead of copying the multi-GB cache
        # every chunk (same pattern as train/trainer.py's donated state).
        # CPU can't donate and would warn on every dispatch — TPU only.
        from nexus_tpu.utils.hw import is_tpu

        donate = is_tpu()
        self._decode_chunk = jax.jit(
            _decode_chunk, donate_argnums=(1,) if donate else ()
        )
        self._insert_fn = jax.jit(
            _insert, donate_argnums=(0, 5, 7, 9) if donate else ()
        )
        self._spec_chunk = jax.jit(
            _spec_chunk, donate_argnums=(1, 4) if donate else ()
        )
        self._insert_spec_fn = jax.jit(
            _insert_spec,
            donate_argnums=(0, 5, 7, 9, 11) if donate else (),
        )

    def _prefill(self, bucket: int, n: int) -> Callable:
        """Compile-once-per-(bucket, group-size) prefill: n right-padded
        prompts (n, Pb) through ONE forward — simultaneously freed rows
        admit in one dispatch instead of n (prefill serializes with
        decode, so dispatch count is the admission tax; measured in the
        16-row probe, docs/PERF.md). Each row's first generated token
        reads the logits at ITS real last prompt position. K/V written
        past a row's real_len is garbage, but each decode step overwrites
        its slot before the mask can expose it (position p is written at
        the same step whose query first sees p). Group sizes are padded
        to powers of two (dummy rows: one zero token) to bound the
        compile count."""
        key = (bucket, n)
        if key in self._prefill_cache:
            return self._prefill_cache[key]
        cfg_, fwd = self._cfg, self._fwd
        max_len = self._max_len
        pick = self._pick

        def prefill(params, prompts, real_lens, temps, seeds):
            # group-local cache; the BATCH cache carries the serving
            # sharding and the insert scatter lands into it
            cache = init_kv_cache(
                cfg_.n_layers, cfg_.n_kv_heads, cfg_.head_dim, cfg_.dtype,
                n, max_len,
            )
            logits, cache = fwd(params, cfg_, prompts, cache)
            last = jnp.take_along_axis(
                logits, (real_lens - 1)[:, None, None].astype(jnp.int32),
                axis=1,
            )[:, 0]  # (n, V)
            # each first token sits at its row's buffer position real_len
            firsts = jax.vmap(pick)(last, temps, seeds, real_lens).astype(
                prompts.dtype
            )
            return cache["k"], cache["v"], firsts

        fn = jax.jit(prefill)
        self._prefill_cache[key] = fn
        return fn

    def _bucket_of(self, p: int) -> int:
        """Prompt length -> prefill bucket (shared by validation, warm-up,
        and the initial-wave scan — these MUST agree or warmed compiles
        desynchronize from admission keys)."""
        return min(-(-p // PREFILL_BUCKET) * PREFILL_BUCKET, self._max_len)

    def _validate_request(self, req: ServeRequest, req_idx: int):
        """Per-request admission checks → (prompt, p, budget, bucket)."""
        prompt = np.asarray(req.prompt, dtype=np.int32)
        p = int(prompt.shape[0])
        if p < 1:
            raise ValueError(f"request {req_idx}: empty prompt")
        if self._lookup and req.temperature > 0:
            raise ValueError(
                f"request {req_idx}: speculative (prompt-lookup) serving "
                "is greedy-exact only; temperature must be 0"
            )
        # budget: leave the dispatch's worst-case overrun + 1 below the
        # cache end so an almost-finished chunk can never run the row
        # past it (plain: chunk steps; speculative: rounds*(k+1) commits
        # plus the k-wide verify block's K/V writes)
        budget = min(
            int(req.max_new_tokens), self._max_len - 1 - p - self._slack
        )
        if budget < 1:
            raise ValueError(
                f"request {req_idx}: prompt ({p}) + chunk slack "
                f"({self._slack}) leaves no decode budget within "
                f"max_len {self._max_len}"
            )
        return prompt, p, budget, self._bucket_of(p)

    @staticmethod
    def _group_pad(n: int) -> int:
        pad = 1
        while pad < n:
            pad *= 2
        return pad

    def _admit_group(self, cache, tok_vec, temp_vec, seed_vec, buf,
                     admissions):
        """Admit several requests with ONE prefill dispatch per prompt
        bucket (admission serializes with decode, so dispatches are the
        tax — simultaneously freed rows share a forward). ``admissions``:
        [(row, req, req_idx), ...]. Returns the updated device state plus
        [(row, _RowState), ...] in admission order per bucket group."""
        prepared = [
            (row, req_idx, req, *self._validate_request(req, req_idx))
            for row, req, req_idx in admissions
        ]
        by_bucket = {}
        for item in prepared:
            by_bucket.setdefault(item[6], []).append(item)
        out = []
        subgroups = []
        for bucket, group in by_bucket.items():
            # split into group sizes the warm-up already compiled: a
            # mid-run XLA compile (~10 s on the tunnel) costs far more
            # than the dispatches batching saves. Prefer padding UP to
            # the smallest warmed size that fits the whole remainder
            # (dummy rows are cheap; an extra dispatch is not); fall back
            # to the largest warmed size below it. Size 1 is always warm.
            warmed = sorted(self._warmed.get(bucket, {1}))
            i = 0
            while i < len(group):
                remaining = len(group) - i
                geq = [w for w in warmed if w >= remaining]
                n_pad = (
                    min(geq) if geq
                    else max(w for w in warmed if w <= remaining)
                )
                take = min(n_pad, remaining)
                subgroups.append((bucket, group[i:i + take], n_pad))
                i += take
        for bucket, group, n_pad in subgroups:
            prompts = np.zeros((n_pad, bucket), dtype=np.int32)
            lens = np.ones((n_pad,), dtype=np.int32)  # dummy rows: 1 token
            temps = np.zeros((n_pad,), dtype=np.float32)
            seeds = np.zeros((n_pad,), dtype=np.int32)
            for i, (_row, _ri, req, prompt, p, _b, _bk) in enumerate(group):
                prompts[i, :p] = prompt
                lens[i] = p
                temps[i] = req.temperature
                seeds[i] = req.seed
            ks, vs, firsts = self._prefill(bucket, n_pad)(
                self._params, jnp.asarray(prompts), jnp.asarray(lens),
                jnp.asarray(temps), jnp.asarray(seeds),
            )
            self._prefill_dispatches += 1
            firsts_np = np.asarray(firsts)
            for i, (row, req_idx, req, prompt, p, budget, _bk) in enumerate(
                group
            ):
                first = jnp.asarray(int(firsts_np[i]), jnp.int32)
                temp = jnp.asarray(req.temperature, jnp.float32)
                seed = jnp.asarray(req.seed, jnp.int32)
                if self._lookup:
                    prompt_row = np.zeros((self._max_len,), dtype=np.int32)
                    prompt_row[:p] = prompt
                    (cache, tok_vec, temp_vec, seed_vec,
                     buf) = self._insert_spec_fn(
                        cache, jnp.asarray(row, jnp.int32),
                        ks[:, i:i + 1], vs[:, i:i + 1],
                        jnp.asarray(p, jnp.int32), tok_vec, first,
                        temp_vec, temp, seed_vec, seed,
                        buf, jnp.asarray(prompt_row),
                    )
                else:
                    cache, tok_vec, temp_vec, seed_vec = self._insert_fn(
                        cache, jnp.asarray(row, jnp.int32),
                        ks[:, i:i + 1], vs[:, i:i + 1],
                        jnp.asarray(p, jnp.int32), tok_vec, first,
                        temp_vec, temp, seed_vec, seed,
                    )
                state = _RowState(request_idx=req_idx, budget=budget)
                state.emitted.append(int(firsts_np[i]))
                out.append((row, state))
        return cache, tok_vec, temp_vec, seed_vec, buf, out

    def serve(self, requests: Sequence[ServeRequest]):
        """Run the queue to completion → (results, metrics).

        results[i] corresponds to requests[i]. Metrics: committed vs
        scheduled step-slots (the continuous-batching win is this
        utilization staying high under uneven lengths), chunk count,
        wall time, decode tokens/sec over committed tokens.

        The decode chunk and every prefill bucket the queue will need are
        compiled BEFORE the clock starts — tokens/sec and the per-request
        latencies measure serving, not XLA compilation (the infer bench
        warms the same way)."""
        b, max_len = self._b, self._max_len
        cfg = self._cfg

        # ---- warm-up (outside the timed window) ----
        # compile every (bucket, 1) the queue can need (steady-state
        # turnover admits mostly single rows), (bucket, 2) where two
        # same-bucket requests exist, and the exact group sizes of the
        # INITIAL admission wave; mid-run waves only ever use these
        # warmed sizes (the splitter pads up or splits down — no
        # mid-run compiles)
        totals = {}
        for req in requests:
            if len(req.prompt) >= 1:
                bk = self._bucket_of(len(req.prompt))
                totals[bk] = totals.get(bk, 0) + 1
        warm_keys = {(bucket, 1) for bucket in totals}
        if b > 1:  # steady-state turnover often frees 2 rows per chunk —
            # but a size-2 group needs two same-bucket requests to exist
            warm_keys |= {
                (bucket, 2) for bucket, n in totals.items() if n >= 2
            }
        initial = {}
        for req in requests[:b]:
            if len(req.prompt) >= 1:
                bk = self._bucket_of(len(req.prompt))
                initial[bk] = initial.get(bk, 0) + 1
        for bk, n in initial.items():
            warm_keys.add((bk, self._group_pad(n)))
        self._warmed = {}
        for bucket, n in sorted(warm_keys):
            self._prefill(bucket, n)(
                self._params, jnp.zeros((n, bucket), jnp.int32),
                jnp.ones((n,), jnp.int32), jnp.zeros((n,), jnp.float32),
                jnp.zeros((n,), jnp.int32),
            )
            self._warmed.setdefault(bucket, set()).add(n)
        warm_cache = init_kv_cache(
            cfg.n_layers, cfg.n_kv_heads, cfg.head_dim, cfg.dtype,
            b, max_len,
        )
        if self._cache_sharding is not None:
            # warm with the REAL layout or jit compiles a second program
            # for the constrained cache on the first timed chunk
            for key in ("k", "v"):
                warm_cache[key] = lax.with_sharding_constraint(
                    warm_cache[key], self._cache_sharding
                )
        warm_cache["length"] = jnp.zeros((b,), jnp.int32)
        if self._lookup:
            _, _, _, outs, _ = self._spec_chunk(
                self._params, warm_cache, jnp.zeros((b,), jnp.int32),
                jnp.ones((b,), jnp.bool_),
                jnp.zeros((b, max_len), jnp.int32),
            )
            np.asarray(outs)  # host fetch: the warm-up really completed
        else:
            _, _, toks = self._decode_chunk(
                self._params, warm_cache, jnp.zeros((b,), jnp.int32),
                jnp.ones((b,), jnp.bool_), jnp.zeros((b,), jnp.float32),
                jnp.zeros((b,), jnp.int32),
            )
            np.asarray(toks)  # host fetch: the warm-up really completed
        del warm_cache

        t0 = time.monotonic()
        cache = init_kv_cache(
            cfg.n_layers, cfg.n_kv_heads, cfg.head_dim, cfg.dtype,
            b, max_len,
        )
        if self._cache_sharding is not None:
            cache = dict(cache)
            for key in ("k", "v"):
                cache[key] = lax.with_sharding_constraint(
                    cache[key], self._cache_sharding
                )
        cache["length"] = jnp.zeros((b,), jnp.int32)  # vector from step 0
        tok_vec = jnp.zeros((b,), jnp.int32)
        temp_vec = jnp.zeros((b,), jnp.float32)
        seed_vec = jnp.zeros((b,), jnp.int32)
        buf = (
            jnp.zeros((b, max_len), jnp.int32) if self._lookup else None
        )
        rows: List[Optional[_RowState]] = [None] * b
        results: List[Optional[ServeResult]] = [None] * len(requests)
        next_req = 0
        committed = 0
        scheduled_slots = 0
        chunks = 0
        target_forwards = 0
        drafted = 0
        accepted_total = 0
        self._prefill_dispatches = 0

        def finish(state: _RowState) -> None:
            nonlocal committed
            committed += len(state.emitted)
            results[state.request_idx] = ServeResult(
                tokens=list(np.asarray(
                    requests[state.request_idx].prompt, dtype=np.int32
                )) + state.emitted,
                new_tokens=len(state.emitted),
                finished_by_stop=state.stopped,
                latency_s=time.monotonic() - t0,
            )

        def row_done(state: _RowState) -> bool:
            return state.stopped or len(state.emitted) >= state.budget

        def admit_into(free_rows):
            """Fill free rows from the queue, batching each wave's prefills
            by bucket (one dispatch per bucket per wave). A request whose
            FIRST token is already the stop token finishes immediately and
            its row re-enters the free pool for the next wave."""
            nonlocal cache, tok_vec, temp_vec, seed_vec, buf, next_req
            while free_rows and next_req < len(requests):
                wave = []
                while free_rows and next_req < len(requests):
                    wave.append(
                        (free_rows.pop(0), requests[next_req], next_req)
                    )
                    next_req += 1
                (cache, tok_vec, temp_vec, seed_vec, buf,
                 admitted) = self._admit_group(
                    cache, tok_vec, temp_vec, seed_vec, buf, wave,
                )
                for row, state in admitted:
                    if self._stop >= 0 and state.emitted[-1] == self._stop:
                        state.stopped = True
                    if row_done(state):
                        finish(state)
                        free_rows.append(row)
                    else:
                        rows[row] = state

        admit_into([r for r in range(b) if rows[r] is None])

        while any(r is not None for r in rows):
            done_vec = jnp.asarray(
                [r is None or row_done(r) for r in rows], jnp.bool_
            )
            if self._lookup:
                cache, tok_vec, buf, outs, accs = self._spec_chunk(
                    self._params, cache, tok_vec, done_vec, buf
                )
                chunks += 1
                # one verify scores k+1 positions; utilization over them
                # is acceptance-sensitive by design
                scheduled_slots += self._rounds * (self._k + 1) * b
                host_outs = np.asarray(outs)   # (R, B, k+1)
                host_accs = np.asarray(accs)   # (R, B)
            else:
                cache, tok_vec, toks = self._decode_chunk(
                    self._params, cache, tok_vec, done_vec, temp_vec,
                    seed_vec,
                )
                chunks += 1
                scheduled_slots += self._chunk * b
                host_toks = np.asarray(toks)  # (C, B)
            for r in range(b):
                state = rows[r]
                if state is None:
                    continue
                if self._lookup:
                    for ri in range(self._rounds):
                        if row_done(state):
                            break
                        n = int(host_accs[ri, r]) + 1
                        target_forwards += 1
                        drafted += self._k
                        accepted_total += int(host_accs[ri, r])
                        for t in host_outs[ri, r, :n]:
                            if row_done(state):
                                break
                            state.emitted.append(int(t))
                            if self._stop >= 0 and int(t) == self._stop:
                                state.stopped = True
                else:
                    for c in range(self._chunk):
                        if row_done(state):
                            break
                        t = int(host_toks[c, r])
                        state.emitted.append(t)
                        if self._stop >= 0 and t == self._stop:
                            state.stopped = True
                if row_done(state):
                    finish(state)
                    rows[r] = None
            # admit the next queued requests into every row this chunk
            # freed — ONE batched wave, not one prefill per row
            admit_into([r for r in range(b) if rows[r] is None])
        wall = time.monotonic() - t0
        metrics = {
            "requests": len(requests),
            "committed_tokens": committed,
            "scheduled_step_slots": scheduled_slots,
            "slot_utilization": (
                round(committed / scheduled_slots, 4)
                if scheduled_slots else 1.0
            ),
            "decode_chunks": chunks,
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(committed / wall, 2) if wall else 0.0,
            "prefill_dispatches": self._prefill_dispatches,
        }
        if self._lookup:
            metrics["speculative_kind"] = "prompt_lookup"
            metrics["prompt_lookup_ngram"] = self._lookup
            metrics["num_speculative"] = self._k
            metrics["target_forwards"] = target_forwards
            metrics["acceptance_rate"] = (
                round(accepted_total / drafted, 4) if drafted else 0.0
            )
        return results, metrics
