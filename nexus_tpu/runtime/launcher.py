"""LocalLauncher: the shard-side executor for in-process shards.

On a real GKE shard, the synced template's Job manifests (materializer.py)
are applied to the cluster and kubelet+GKE do the rest. On a *local* shard
(tests, single-host deployments, BASELINE config #2), this launcher plays
the role of the cluster's job machinery: it watches the shard store for
templates carrying a jax_xla runtime, materializes the Job manifest (same
code path as production), executes the runtime in a worker thread, and
records the outcome as a ConfigMap ``<template>-result`` plus Events —
proving template → running-JAX-job end to end.
"""

from __future__ import annotations

import json
import logging
import threading
import traceback
from typing import Any, Dict, Optional

from nexus_tpu.api.template import NexusAlgorithmTemplate
from nexus_tpu.api.types import ConfigMap, ObjectMeta
from nexus_tpu.cluster.store import ClusterStore, NotFoundError, WatchEvent
from nexus_tpu.controller.events import EventRecorder, EVENT_TYPE_NORMAL, EVENT_TYPE_WARNING
from nexus_tpu.runtime.entrypoints import run_template_runtime
from nexus_tpu.runtime.materializer import materialize_job

logger = logging.getLogger("nexus_tpu.launcher")

RESULT_SUFFIX = "-result"
REASON_JOB_STARTED = "JobStarted"
REASON_JOB_COMPLETED = "JobCompleted"
REASON_JOB_FAILED = "JobFailed"


class LocalLauncher:
    """Watches one shard store and executes runnable templates."""

    def __init__(
        self,
        store: ClusterStore,
        recorder: Optional[EventRecorder] = None,
        max_steps: Optional[int] = None,
        devices=None,
        heartbeat_ttl: float = 0.0,
        step_pace_s: float = 0.0,
    ):
        self.store = store
        self.recorder = recorder or EventRecorder(component="nexus-local-launcher")
        self.max_steps = max_steps
        self.devices = devices
        # heartbeat_ttl > 0 wires the failover lease protocol (ha/lease.py):
        # each running job renews its heartbeat ConfigMap in this store at
        # every step boundary — the launcher plays the worker pod's renewer
        # the way it already plays the kubelet for job status.
        self.heartbeat_ttl = float(heartbeat_ttl)
        # step_pace_s > 0 sleeps at each step boundary — tests and the
        # failover bench use it to give CPU-instant toy steps a realistic
        # wall-clock duration (a kill must be able to land mid-run).
        self.step_pace_s = float(step_pace_s)
        self._seen_generations: Dict[str, int] = {}
        self._threads: Dict[str, threading.Thread] = {}
        # per-running-job cancel tokens — the chaos "kill worker" hook
        self._cancels: Dict[str, Any] = {}
        # newest template revision that arrived while its job was running;
        # re-launched when the running job finishes
        self._pending: Dict[str, NexusAlgorithmTemplate] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()

    # ---------------------------------------------------------------- control
    def start(self) -> None:
        from nexus_tpu.api.workload import Job

        self.store.subscribe(NexusAlgorithmTemplate.KIND, self._on_event)
        # a NEW Job object for a template whose worker is not running means
        # the controller re-materialized it (failover re-placement onto
        # this shard, or a converge after the planner reaped a dead Job) —
        # the generation dedup must not swallow that relaunch
        self.store.subscribe(Job.KIND, self._on_job_event)
        for tmpl in self.store.list(NexusAlgorithmTemplate.KIND):
            self._maybe_launch(tmpl)

    def stop(self, wait: bool = True, timeout: float = 60.0) -> None:
        import time

        from nexus_tpu.api.workload import Job

        self._stop.set()
        self.store.unsubscribe(NexusAlgorithmTemplate.KIND, self._on_event)
        self.store.unsubscribe(Job.KIND, self._on_job_event)
        if wait:
            # loop: a deferred pending-relaunch racing _stop may insert one
            # more thread after the first snapshot; re-snapshot until quiet,
            # but bound the whole wait so one wedged job can't hang shutdown
            deadline = time.monotonic() + timeout
            while True:
                with self._lock:
                    threads = [
                        t for t in self._threads.values() if t.is_alive()
                    ]
                if not threads:
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    logger.warning(
                        "launcher stop: %d job thread(s) still running after "
                        "%.0fs; abandoning wait", len(threads), timeout
                    )
                    return
                for t in threads:
                    t.join(timeout=max(0.05, remaining / len(threads)))

    def kill(self, template_key: str, hard: bool = True) -> bool:
        """Chaos hook ("kill worker"): cancel the running job for a template
        key (``namespace/name``). ``hard=True`` skips the graceful-shutdown
        courtesies (final checkpoint, heartbeat done-marker) — the realistic
        no-grace preemption the failover subsystem exists to recover from.
        Returns True if a running job was signalled."""
        with self._lock:
            cancel = self._cancels.get(template_key)
        if cancel is None:
            return False
        cancel.cancel(hard=hard)
        return True

    def wait_idle(self, timeout: float = 120.0) -> bool:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not any(t.is_alive() for t in self._threads.values()):
                    return True
            time.sleep(0.05)
        return False

    # ----------------------------------------------------------------- events
    def _on_event(self, event: WatchEvent) -> None:
        if self._stop.is_set():
            return
        if event.type in ("ADDED", "MODIFIED"):
            self._maybe_launch(event.obj)

    def _on_job_event(self, event: WatchEvent) -> None:
        """A materialized Job (re)appeared: if its template's worker is not
        running, the generation was executed before but the intent is
        clearly to run again (failover re-placement onto this same shard
        re-creates the Job without any template change) — reset the dedup
        and launch."""
        if self._stop.is_set() or event.type != "ADDED":
            return
        from nexus_tpu.runtime.materializer import LABEL_TEMPLATE

        name = (event.obj.metadata.labels or {}).get(LABEL_TEMPLATE, "")
        if not name:
            return
        try:
            tmpl = self.store.get(
                NexusAlgorithmTemplate.KIND, event.obj.metadata.namespace, name
            )
        except NotFoundError:
            return
        key = tmpl.key()
        with self._lock:
            running = self._threads.get(key)
            if running is not None and running.is_alive():
                return  # normal converge while the worker is up
            self._seen_generations.pop(key, None)
        self._maybe_launch(tmpl)

    def _maybe_launch(self, tmpl: NexusAlgorithmTemplate) -> None:
        if tmpl.spec.runtime is None:
            return
        if self._stop.is_set():
            return
        key = tmpl.key()
        gen = tmpl.metadata.generation
        with self._lock:
            if self._seen_generations.get(key, -1) >= gen:
                return  # this (or a newer) spec generation already ran/running
            running = self._threads.get(key)
            if running is not None and running.is_alive():
                # one job per template at a time — park the NEWEST revision
                # (generation-ordered: a deferred relaunch of an older
                # revision must not clobber a newer parked one);
                # _execute re-launches it when the running job finishes
                parked = self._pending.get(key)
                if parked is None or parked.metadata.generation < gen:
                    self._pending[key] = tmpl
                return
            self._seen_generations[key] = gen
            from nexus_tpu.utils.signals import CancelToken

            self._cancels[key] = CancelToken()
            t = threading.Thread(
                target=self._execute, args=(tmpl,), daemon=True,
                name=f"nexus-job-{tmpl.metadata.name}",
            )
            self._threads[key] = t
        t.start()

    def _synced_replica_id(self, tmpl: NexusAlgorithmTemplate,
                           wait_s: float = 2.0) -> str:
        """The fleet replica id the controller stamped into this
        shard's synced Job env (``NEXUS_SERVE_REPLICA_ID``), or "".
        The launcher materializes its own manifests from the template
        (which is shard-agnostic), so the replica identity — a property
        of the PLACEMENT, not the template — must be read off what the
        controller actually synced here.

        FLEET templates (``serve.replicas > 1``) poll up to ``wait_s``
        for the Job to appear: the launcher wakes on the TEMPLATE sync,
        which lands BEFORE the workload sync applies the Job (the same
        ordering race ``_set_job_statuses`` already waits out) — read
        too early and the engine would renew the SHARED serve lease and
        publish untagged gauges, so the fleet monitor would confirm a
        healthy replica dead. Single-home templates return "" at once
        (there is no identity to wait for)."""
        import time

        rt = tmpl.spec.runtime
        if rt is None or getattr(rt, "mode", "") != "serve":
            return ""
        replicas = max(1, int(getattr(
            getattr(rt, "serve", None), "replicas", 1) or 1))
        if replicas <= 1:
            return ""
        from nexus_tpu.api.workload import Job
        from nexus_tpu.runtime.materializer import LABEL_TEMPLATE

        deadline = time.monotonic() + max(0.0, float(wait_s))
        while True:
            try:
                jobs = self.store.list(
                    Job.KIND, tmpl.metadata.namespace,
                    label_selector={LABEL_TEMPLATE: tmpl.metadata.name},
                )
            except Exception:  # noqa: BLE001 — identity is best-effort
                jobs = []
            for job in jobs:
                spec = getattr(job, "spec", None) or {}
                pod = (spec.get("template") or {}).get("spec") or {}
                for container in pod.get("containers") or []:
                    for env in container.get("env") or []:
                        if env.get("name") == "NEXUS_SERVE_REPLICA_ID":
                            return str(env.get("value") or "")
            if time.monotonic() >= deadline or self._stop.is_set():
                return ""
            time.sleep(0.02)

    # -------------------------------------------------------------- execution
    def _execute(self, tmpl: NexusAlgorithmTemplate) -> None:
        try:
            self._execute_inner(tmpl)
        finally:
            key = tmpl.key()
            with self._lock:
                if self._threads.get(key) is threading.current_thread():
                    del self._threads[key]
                    self._cancels.pop(key, None)
                pending = self._pending.pop(key, None)
            if pending is not None and not self._stop.is_set():
                self._maybe_launch(pending)

    def _execute_inner(self, tmpl: NexusAlgorithmTemplate) -> None:
        import time

        name = tmpl.metadata.name
        with self._lock:
            cancel = self._cancels.get(tmpl.key())
        # fleet replica identity, read ONCE off this shard's synced Job
        # env and used for both the lease name and the engine's gauge
        # tags below — two reads could diverge if the store changed
        # between them (lease under one id, gauges under another)
        serve_rid = (
            self._synced_replica_id(tmpl)
            if tmpl.spec.runtime.mode == "serve" else ""
        )
        renewer = None
        if self.heartbeat_ttl > 0:
            from nexus_tpu.ha.lease import LeaseRenewer

            hb_template = name
            if tmpl.spec.runtime.mode == "serve":
                # serving engines renew ``hb-serve-<template>`` (the
                # detector confirms their death exactly as for trainers;
                # the failover planners strip the infix back to the
                # workload template — ha/serve_failover.py). A FLEET
                # replica — the controller synced a Job carrying
                # NEXUS_SERVE_REPLICA_ID for this shard — renews its own
                # ``hb-serve-<template>--<id>`` lease, the pod path's
                # exact behavior (runtime/worker.py)
                from nexus_tpu.ha.serve_failover import (
                    serve_heartbeat_template,
                    serve_replica_template,
                )

                hb_template = (
                    serve_replica_template(name, serve_rid) if serve_rid
                    else serve_heartbeat_template(name)
                )
            renewer = LeaseRenewer(
                self.store,
                namespace=tmpl.metadata.namespace,
                template_name=hb_template,
                holder=f"local-{self.store.name}",
                ttl_seconds=self.heartbeat_ttl,
            )

        def on_step(step: int) -> None:
            if renewer is not None:
                renewer.renew(step)
            if self.step_pace_s > 0:
                time.sleep(self.step_pace_s)

        try:
            # production code path: manifest materialization must succeed
            jobs = materialize_job(tmpl, shard_name=self.store.name)
            self.recorder.event(
                tmpl, EVENT_TYPE_NORMAL, REASON_JOB_STARTED,
                f"Launching {len(jobs)} job(s) for template {name!r} "
                f"({tmpl.spec.runtime.mode} {tmpl.spec.runtime.model.family})",
            )
            self._set_job_statuses(tmpl, jobs, "Running")
            # failover resume pin: the planner's restore-step annotation
            # (same contract the materializer turns into NEXUS_RESTORE_STEP
            # for real pods)
            from nexus_tpu.runtime.materializer import ANNOTATION_RESTORE_STEP

            raw_restore = (tmpl.metadata.annotations or {}).get(
                ANNOTATION_RESTORE_STEP, ""
            )
            metrics = run_template_runtime(
                tmpl.spec.runtime, devices=self.devices,
                max_steps=self.max_steps, cancel=cancel,
                heartbeat=on_step if (renewer or self.step_pace_s) else None,
                restore_step=int(raw_restore) if raw_restore else None,
                serve_replica_id=serve_rid,
            )
            if metrics.get("interrupted"):
                # killed / preempted mid-run: the job did NOT complete — no
                # done-marker on the heartbeat (a hard kill stops renewing
                # outright, which is exactly what the detector must see)
                self._write_result(tmpl, "Failed", metrics, jobs)
                self._set_job_statuses(tmpl, jobs, "Failed")
                self.recorder.event(
                    tmpl, EVENT_TYPE_WARNING, REASON_JOB_FAILED,
                    f"Template {name!r} interrupted at step "
                    f"{metrics.get('steps')} (killed/preempted)",
                )
                return
            if renewer is not None:
                renewer.complete(int(metrics.get("steps", -1) or -1))
            self._write_result(tmpl, "Succeeded", metrics, jobs)
            self._set_job_statuses(tmpl, jobs, "Succeeded")
            self.recorder.event(
                tmpl, EVENT_TYPE_NORMAL, REASON_JOB_COMPLETED,
                f"Template {name!r} completed: "
                + json.dumps({k: metrics[k] for k in sorted(metrics) if not isinstance(metrics[k], list)}, default=str)[:512],
            )
        except Exception as e:
            logger.exception("job for template %s failed", name)
            if renewer is not None:
                # a worker that REPORTED failure is not a liveness failure:
                # mark the lease done so Job retry policy (not the failover
                # detector) owns what happens next
                renewer.complete()
            self._write_result(
                tmpl, "Failed", {"error": str(e), "traceback": traceback.format_exc()[-2000:]}, []
            )
            self._set_job_statuses(tmpl, None, "Failed")
            self.recorder.event(
                tmpl, EVENT_TYPE_WARNING, REASON_JOB_FAILED,
                f"Template {name!r} failed: {e}",
            )

    def _set_job_statuses(self, tmpl, manifests, phase: str) -> None:
        """Reflect execution state into the store's Job objects (the ones the
        controller's workload sync applied) — the launcher plays kubelet for
        in-process shards, so workload phase back-propagates to template
        status exactly as it would from a real cluster. No-op for Job names
        that don't exist in the store (launcher running without a
        controller)."""
        from nexus_tpu.api.types import Condition, utcnow
        from nexus_tpu.api.workload import Job

        if manifests is None:
            try:
                manifests = materialize_job(tmpl, shard_name=self.store.name)
            except ValueError:
                return
        import time

        from nexus_tpu.api.types import LABEL_CONTROLLER_APP

        ns = tmpl.metadata.namespace
        now = utcnow().isoformat()
        # controller-synced templates carry the provenance label; only then
        # is a controller around to apply Job objects worth waiting for
        managed = LABEL_CONTROLLER_APP in (tmpl.metadata.labels or {})
        # the controller's reconcile applies the Jobs moments after the
        # template lands on the shard; the launcher thread can get here
        # first — wait briefly for 'Running' so the phase transition (and
        # the template_to_running gauge) isn't lost to the race. ONE shared
        # deadline across all manifests: if the Jobs aren't coming (sync
        # error, fail-fast cleanup), we pay at most 5s per template, not
        # 5s per slice.
        deadline = time.monotonic() + (
            5.0 if (phase == "Running" and managed) else 0.0
        )
        for manifest in manifests:
            name = manifest["metadata"]["name"]
            job = None
            while True:
                try:
                    job = self.store.get(Job.KIND, ns, name)
                    break
                except NotFoundError:
                    if time.monotonic() >= deadline or self._stop.is_set():
                        break
                    time.sleep(0.05)
            if job is None:
                continue
            updated = job.deepcopy()
            n = int(job.spec.get("parallelism") or 1)
            if phase == "Running":
                updated.status.active = n
                updated.status.ready = n
                updated.status.start_time = updated.status.start_time or now
            elif phase == "Succeeded":
                updated.status.active = 0
                updated.status.ready = 0
                updated.status.succeeded = int(job.spec.get("completions") or 1)
                updated.status.completion_time = now
                updated.status.conditions = [
                    Condition(type="Complete", status="True", reason="Completed")
                ]
            else:  # Failed
                updated.status.active = 0
                updated.status.ready = 0
                updated.status.failed = updated.status.failed + 1
                updated.status.conditions = [
                    Condition(
                        type="Failed", status="True", reason="BackoffLimitExceeded"
                    )
                ]
            try:
                self.store.update_status(updated)
            except Exception:
                logger.debug("job status update for %s skipped", name)

    def _write_result(
        self, tmpl: NexusAlgorithmTemplate, phase: str, metrics: Dict[str, Any],
        jobs,
    ) -> None:
        result = ConfigMap(
            metadata=ObjectMeta(
                name=tmpl.metadata.name + RESULT_SUFFIX,
                namespace=tmpl.metadata.namespace,
                labels={"app": "nexus-local-launcher"},
            ),
            data={
                "phase": phase,
                "metrics": json.dumps(metrics, default=str),
                "jobManifest": json.dumps(jobs[0], default=str) if jobs else "",
                "generation": str(tmpl.metadata.generation),
            },
        )
        try:
            existing = self.store.get(
                ConfigMap.KIND, result.metadata.namespace, result.metadata.name
            )
            result.metadata = existing.metadata
            result.metadata.labels = {"app": "nexus-local-launcher"}
            self.store.update(result)
        except NotFoundError:
            self.store.create(result)
