"""Replayable arrival traces + the open-loop streaming source.

Every bench before round 16 was CLOSED-LOOP: a fixed queue handed to
``serve()`` post-hoc, so the engine-lifetime radix tree and block pool
never faced the regime they exist for — requests arriving over time,
sharing prefixes across calls, queueing under bursts. This module is
the other half of the round-16 tentpole: a versioned, seed-replayable
trace format plus a :class:`TraceSource` that streams it into a
running ``serve()`` call (or a live :class:`~nexus_tpu.fleet.fleet
.ServeFleet`) through the source protocol the engine polls at wave
boundaries.

Design constraints, in order:

  1. **Replayable.** A trace is pure data (``to_dict``/``from_dict``
     round-trip exactly, ``trace_version`` pinned) and synthesis is
     PURE-SEEDED — :func:`synthesize_trace` never reads a clock or
     global RNG state, so the same ``(seed, knobs)`` always yields the
     same byte-identical trace. Arrival times are trace-relative
     seconds; the wall clock enters only in :class:`TraceSource`, via
     the injectable clock/sleep discipline every timed component of
     this repo uses.
  2. **The shapes that matter.** Poisson and bursty (on/off clustered)
     arrival processes; Zipf-shared prompt prefixes (rank-``a``
     power-law over a shared prefix pool — the system-prompt /
     few-shot-header regime RadixAttention targets); multi-turn chat
     sessions (turn ``k+1``'s prompt is turn ``k``'s full history plus
     a fresh user message, arriving after think time); agent-style
     branching fan-outs (N children sharing the parent's full history,
     arriving near-simultaneously). The last two generalize the PR 9
     radix bench scenarios into trace events.
  3. **Honest chat history.** A successor turn's prompt must contain
     the parent's COMPLETION to exercise cross-call completion-block
     reuse. Completions are model-dependent, so synthesis takes an
     optional ``completion_fn(prompt, budget) -> tokens``; the bench
     passes the stub model's greedy rule and gets exact-replay chat
     histories. Without it, a seeded filler stands in (prefix reuse
     then stops at the prompt chain — still a valid trace, just a
     shallower one).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

TRACE_VERSION = 1

#: Event kinds: a one-shot request, one turn of a chat session, or one
#: branch of an agent fan-out (the parent of a fan-out is kind
#: "single"; its children are "branch").
EVENT_KINDS = ("single", "turn", "branch")


@dataclass
class TraceEvent:
    """One arrival: WHEN (seconds from trace start) and WHAT (the
    request body). ``session`` groups the turns of one conversation or
    the members of one fan-out family; ``turn`` orders within it."""

    arrival_s: float
    prompt: List[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    seed: int = 0
    session: str = ""
    turn: int = 0
    kind: str = "single"

    def to_dict(self) -> dict:
        return {
            "arrival_s": round(float(self.arrival_s), 6),
            "prompt": [int(t) for t in self.prompt],
            "max_new_tokens": int(self.max_new_tokens),
            "temperature": float(self.temperature),
            "seed": int(self.seed),
            "session": str(self.session),
            "turn": int(self.turn),
            "kind": str(self.kind),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TraceEvent":
        return cls(
            arrival_s=float(d["arrival_s"]),
            prompt=[int(t) for t in d["prompt"]],
            max_new_tokens=int(d.get("max_new_tokens", 16)),
            temperature=float(d.get("temperature", 0.0)),
            seed=int(d.get("seed", 0)),
            session=str(d.get("session", "")),
            turn=int(d.get("turn", 0)),
            kind=str(d.get("kind", "single")),
        )


@dataclass
class Trace:
    """A versioned, replayable arrival trace: events sorted by
    ``arrival_s``, the seed and knobs that made them (``meta``), and
    the schema version the loader refuses to mis-read."""

    name: str
    seed: int
    events: List[TraceEvent] = field(default_factory=list)
    meta: Dict[str, Any] = field(default_factory=dict)
    version: int = TRACE_VERSION

    @property
    def duration_s(self) -> float:
        return self.events[-1].arrival_s if self.events else 0.0

    def __len__(self) -> int:
        return len(self.events)

    def to_dict(self) -> dict:
        return {
            "trace_version": int(self.version),
            "name": str(self.name),
            "seed": int(self.seed),
            "meta": dict(self.meta),
            "events": [ev.to_dict() for ev in self.events],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Trace":
        v = int(d.get("trace_version", -1))
        if v != TRACE_VERSION:
            raise ValueError(
                f"trace_version {v} != supported {TRACE_VERSION}"
            )
        return cls(
            name=str(d.get("name", "")),
            seed=int(d.get("seed", 0)),
            meta=dict(d.get("meta", {})),
            events=[TraceEvent.from_dict(e) for e in d.get("events", [])],
            version=v,
        )

    def to_requests(self, deadline_s: float = 0.0,
                    arrivals: bool = False) -> List[Any]:
        """Materialize the trace as a CLOSED-LOOP queue of
        ``ServeRequest`` (the warm-vs-cold A/B's replay form — the
        whole queue exists at ``serve()`` entry). ``arrivals=True``
        keeps the trace arrival stamps on the requests, so a closed-
        loop call still attributes queue time from trace arrival."""
        from nexus_tpu.runtime.serving import ServeRequest

        return [
            ServeRequest(
                prompt=list(ev.prompt),
                max_new_tokens=ev.max_new_tokens,
                temperature=ev.temperature,
                seed=ev.seed,
                deadline_s=deadline_s,
                arrival_s=(float(ev.arrival_s) if arrivals else 0.0),
            )
            for ev in self.events
        ]


# ------------------------------------------------------------- synthesis

def _zipf_probs(n: int, a: float) -> List[float]:
    """Rank power-law p_k ∝ 1/k^a over ranks 1..n, normalized.
    Explicit probabilities (not ``rng.zipf``) so the support is exactly
    the prefix pool — no unbounded draws to clip, replay-stable."""
    raw = [1.0 / float(k) ** float(a) for k in range(1, n + 1)]
    z = sum(raw)
    return [p / z for p in raw]


def synthesize_trace(
    *,
    name: str = "synthetic",
    seed: int = 0,
    vocab_size: int = 128,
    requests: int = 32,
    duration_s: float = 4.0,
    arrival: str = "poisson",
    burst_duty: float = 0.25,
    burst_count: int = 0,
    n_prefixes: int = 4,
    zipf_a: float = 1.1,
    prefix_tokens: int = 24,
    tail_tokens: int = 8,
    max_new_tokens: int = 16,
    multi_turn_frac: float = 0.0,
    turns: int = 2,
    think_s: float = 0.4,
    branch_frac: float = 0.0,
    fanout: int = 3,
    completion_fn: Optional[Callable[[List[int], int], List[int]]] = None,
    temperature: float = 0.0,
) -> Trace:
    """Pure-seeded trace synthesis (no clocks, no global RNG): →
    :class:`Trace` of ``requests`` root arrivals plus their derived
    turn/branch events, sorted by arrival.

    * ``arrival="poisson"``: i.i.d. exponential inter-arrival gaps at
      rate ``requests / duration_s`` — the open-loop steady state.
    * ``arrival="bursty"``: roots cluster into ``burst_count`` (default
      ``max(2, requests // 8)``) bursts whose centers spread evenly
      over ``duration_s``; each burst's width is its even share of the
      duration scaled by ``burst_duty`` — an on/off process with duty
      cycle ``burst_duty`` and peak rate ``1/burst_duty`` times the
      mean, the queue-pressure shape autoscalers are sized against.

    Every root's prompt is a Zipf-shared prefix (rank-``zipf_a``
    power-law over ``n_prefixes`` pooled ``prefix_tokens``-token
    prefixes) plus a unique ``tail_tokens``-token tail. A
    ``multi_turn_frac`` fraction of roots become ``turns``-turn chat
    sessions (successor prompt = full prior history + completion +
    fresh user tail, arriving ``think_s`` later with seeded jitter); a
    ``branch_frac`` fraction become agent fan-outs (``fanout`` children
    sharing the root's full history + completion, each with its own
    tail, arriving near-simultaneously ``think_s`` after the root).
    ``completion_fn`` supplies exact completions for those histories
    (see module docstring); None → seeded filler tokens.
    """
    import numpy as np

    if arrival not in ("poisson", "bursty"):
        raise ValueError(f"unknown arrival process {arrival!r}")
    if requests < 1:
        raise ValueError(f"requests must be >= 1, got {requests}")
    rng = np.random.default_rng(int(seed))
    n = int(requests)

    # ---- root arrival process ----
    if arrival == "poisson":
        gaps = rng.exponential(float(duration_s) / n, size=n)
        root_t = np.cumsum(gaps)
    else:
        n_bursts = int(burst_count) or max(2, n // 8)
        span = float(duration_s) / n_bursts
        width = max(1e-3, span * float(burst_duty))
        centers = [(b + 0.5) * span for b in range(n_bursts)]
        root_t = np.sort(np.array([
            centers[i % n_bursts]
            + rng.uniform(-width / 2.0, width / 2.0)
            for i in range(n)
        ]))
    root_t = np.maximum(root_t, 0.0)

    # ---- shared prefix pool (Zipf popularity) ----
    pool = [
        rng.integers(0, vocab_size, size=int(prefix_tokens)).tolist()
        for _ in range(int(n_prefixes))
    ]
    probs = _zipf_probs(int(n_prefixes), float(zipf_a))
    prefix_ids = rng.choice(int(n_prefixes), size=n, p=probs)

    def complete(prompt: List[int], budget: int) -> List[int]:
        if completion_fn is not None:
            return [int(t) for t in completion_fn(prompt, budget)]
        return rng.integers(0, vocab_size, size=int(budget)).tolist()

    def user_tail() -> List[int]:
        return rng.integers(0, vocab_size, size=int(tail_tokens)).tolist()

    # ---- role assignment (seeded permutation, disjoint) ----
    n_branch = min(n, int(round(float(branch_frac) * n)))
    n_turn = min(n - n_branch, int(round(float(multi_turn_frac) * n)))
    order = rng.permutation(n)
    branch_roots = set(int(i) for i in order[:n_branch])
    turn_roots = set(int(i) for i in order[n_branch:n_branch + n_turn])

    events: List[TraceEvent] = []
    for i in range(n):
        t = float(root_t[i])
        prompt = list(pool[int(prefix_ids[i])]) + user_tail()
        if i in turn_roots:
            sid = f"s{i}"
            history = list(prompt)
            arr = t
            for k in range(int(turns)):
                events.append(TraceEvent(
                    arrival_s=arr, prompt=list(history),
                    max_new_tokens=int(max_new_tokens),
                    temperature=float(temperature),
                    session=sid, turn=k, kind="turn",
                ))
                if k + 1 < int(turns):
                    history = (history
                               + complete(history, int(max_new_tokens))
                               + user_tail())
                    arr += float(think_s) * float(rng.uniform(0.75, 1.25))
        elif i in branch_roots:
            sid = f"b{i}"
            events.append(TraceEvent(
                arrival_s=t, prompt=prompt,
                max_new_tokens=int(max_new_tokens),
                temperature=float(temperature),
                session=sid, turn=0, kind="single",
            ))
            history = prompt + complete(prompt, int(max_new_tokens))
            base = t + float(think_s)
            for c in range(int(fanout)):
                events.append(TraceEvent(
                    arrival_s=base + float(rng.uniform(0.0, 0.05)),
                    prompt=history + user_tail(),
                    max_new_tokens=int(max_new_tokens),
                    temperature=float(temperature),
                    session=sid, turn=c + 1, kind="branch",
                ))
        else:
            events.append(TraceEvent(
                arrival_s=t, prompt=prompt,
                max_new_tokens=int(max_new_tokens),
                temperature=float(temperature),
                kind="single",
            ))
    events.sort(key=lambda ev: (ev.arrival_s, ev.session, ev.turn))
    return Trace(
        name=str(name), seed=int(seed), events=events,
        meta={
            "arrival": arrival, "requests": n,
            "duration_s": float(duration_s),
            "burst_duty": float(burst_duty),
            "n_prefixes": int(n_prefixes), "zipf_a": float(zipf_a),
            "prefix_tokens": int(prefix_tokens),
            "tail_tokens": int(tail_tokens),
            "max_new_tokens": int(max_new_tokens),
            "multi_turn_frac": float(multi_turn_frac),
            "turns": int(turns), "think_s": float(think_s),
            "branch_frac": float(branch_frac), "fanout": int(fanout),
            "vocab_size": int(vocab_size),
            "exact_completions": completion_fn is not None,
        },
    )


# ------------------------------------------------------------ the source

class TraceSource:
    """Stream a :class:`Trace` through the source protocol the engine
    (``serve(..., source=)``) and the fleet (``run(..., source=)``)
    poll: ``poll(now_s)`` delivers every not-yet-delivered event whose
    arrival is due at ``now_s`` as a ``ServeRequest`` (``arrival_s``
    stamped with the trace arrival so queue time anchors at ARRIVAL),
    ``due(now_s)``/``exhausted()`` expose backlog, and ``wait(now_s)``
    sleeps toward the next arrival through the injectable ``sleep`` —
    capped at ``max_wait_s`` so the caller's heartbeat/gauge cadence
    survives idle gaps (a fake-clock test injects a sleep that ADVANCES
    its clock and the whole stream replays deterministically).

    ``speed`` compresses trace time into wall time (2.0 = twice as
    fast) — the bench's lever for running second-scale traces in
    CI-scale wall time without changing the trace.

    ``now_s`` is the CALLER's clock, seconds since ITS run start; the
    source is single-consumer and not thread-safe (the engine polls at
    wave boundaries of one serve thread; the fleet polls from its one
    monitor thread)."""

    def __init__(
        self,
        trace: Trace,
        deadline_s: float = 0.0,
        priority: int = 0,
        speed: float = 1.0,
        sleep: Callable[[float], None] = time.sleep,
        max_wait_s: float = 0.05,
    ) -> None:
        if speed <= 0:
            raise ValueError(f"speed must be > 0, got {speed}")
        self.trace = trace
        self._events = sorted(trace.events, key=lambda ev: ev.arrival_s)
        self._times = [float(ev.arrival_s) / float(speed)
                       for ev in self._events]
        self._deadline_s = float(deadline_s)
        self._priority = int(priority)
        self._i = 0
        self._sleep = sleep
        self._max_wait = float(max_wait_s)

    def __len__(self) -> int:
        return len(self._events)

    @property
    def delivered(self) -> int:
        return self._i

    def _request(self, idx: int) -> Any:
        from nexus_tpu.runtime.serving import ServeRequest

        ev = self._events[idx]
        return ServeRequest(
            prompt=list(ev.prompt),
            max_new_tokens=ev.max_new_tokens,
            temperature=ev.temperature,
            seed=ev.seed,
            deadline_s=self._deadline_s,
            priority=self._priority,
            arrival_s=self._times[idx],
        )

    def poll(self, now_s: float) -> List[Any]:
        out: List[Any] = []
        while self._i < len(self._events) and self._times[self._i] <= now_s:
            out.append(self._request(self._i))
            self._i += 1
        return out

    def due(self, now_s: float) -> int:
        j = self._i
        while j < len(self._events) and self._times[j] <= now_s:
            j += 1
        return j - self._i

    def exhausted(self) -> bool:
        return self._i >= len(self._events)

    def wait(self, now_s: float) -> None:
        if self.exhausted():
            return
        delta = self._times[self._i] - float(now_s)
        if delta > 0:
            self._sleep(min(delta, self._max_wait))


class ListSource:
    """The degenerate source: a fixed request list delivered on a fixed
    arrival schedule (``[(arrival_s, request), ...]``) — the unit-test
    and smoke harness form where synthesis would obscure the assert.
    Same protocol as :class:`TraceSource`."""

    def __init__(self, timed_requests: Sequence[Any],
                 sleep: Callable[[float], None] = time.sleep,
                 max_wait_s: float = 0.05) -> None:
        import dataclasses

        pairs = sorted(timed_requests, key=lambda p: float(p[0]))
        self._reqs = [
            dataclasses.replace(r, arrival_s=float(t)) for t, r in pairs
        ]
        self._times = [float(t) for t, _ in pairs]
        self._i = 0
        self._sleep = sleep
        self._max_wait = float(max_wait_s)

    def __len__(self) -> int:
        return len(self._reqs)

    @property
    def delivered(self) -> int:
        return self._i

    def poll(self, now_s: float) -> List[Any]:
        out: List[Any] = []
        while self._i < len(self._reqs) and self._times[self._i] <= now_s:
            out.append(self._reqs[self._i])
            self._i += 1
        return out

    def due(self, now_s: float) -> int:
        j = self._i
        while j < len(self._reqs) and self._times[j] <= now_s:
            j += 1
        return j - self._i

    def exhausted(self) -> bool:
        return self._i >= len(self._reqs)

    def wait(self, now_s: float) -> None:
        if self.exhausted():
            return
        delta = self._times[self._i] - float(now_s)
        if delta > 0:
            self._sleep(min(delta, self._max_wait))
