"""Content-addressed prefix index for the paged serving KV cache.

Cross-request KV reuse (round 6): heavy serving queues are dominated by
shared prompt prefixes — system prompts, few-shot preambles, multi-turn
histories — and the paged block pool (runtime/serving.py) already stores
K/V at block granularity, so a block whose positions hold the K/V of a
known token prefix can back ANY row whose prompt starts with those
tokens. This module is the host-side content index that makes blocks
addressable by what they contain:

  * ``chain_keys`` maps a prompt to one SHA-256 hash-chain digest per
    FULL block (digest j commits to every token of blocks 0..j, so key
    equality implies whole-prefix equality — the prefix property radix
    trees encode structurally, here as a flat dict);
  * ``PrefixCacheIndex`` maps digest → pool block id for blocks whose
    K/V has been fully written, and keeps the refcount-0 subset in LRU
    order so the allocator can reclaim cold cached content under pool
    pressure — and ONLY then (eviction never touches a referenced
    block; the ref-counted BlockAllocator in runtime/serving.py owns
    the refcounts, this index owns content identity and LRU order).

The K/V of prompt position i is a function of tokens 0..i alone, and the
serving engine writes each prompt position exactly once (chunked prefill
is append-only; done-row holding writes land past the prompt), so an
indexed block is FROZEN — sharing it is pure bookkeeping and the
engine's exactness contract carries over unchanged (tested:
tests/test_prefix_cache.py, tests/test_serving.py)."""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np


def chain_keys(
    tokens: Sequence[int], block_size: int, limit: Optional[int] = None
) -> List[bytes]:
    """Hash-chain digests of the FULL ``block_size``-token blocks of
    ``tokens``: ``key[j] = sha256(key[j-1] || tokens[j*bs:(j+1)*bs])``.

    Chaining makes each key commit to the whole prefix through its
    block, so a flat dict lookup per block walks the same structure a
    radix tree would — and two prompts share key j iff they agree on
    every token of blocks 0..j. The trailing partial block (if any) is
    never keyed: only fully-written blocks are shareable. SHA-256, not
    ``hash()``: a collision would silently serve one request another
    request's K/V, so the digest must be cryptographic."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    arr = np.asarray(tokens, dtype=np.int32)
    n = arr.shape[0] // block_size
    if limit is not None:
        n = min(n, int(limit))
    keys: List[bytes] = []
    h = b""
    for j in range(n):
        blk = arr[j * block_size : (j + 1) * block_size]
        h = hashlib.sha256(h + blk.tobytes()).digest()
        keys.append(h)
    return keys


class PrefixCacheIndex:
    """digest → pool block id, plus the LRU set of refcount-0 holders.

    A block is in exactly one of three states from the allocator's view:
    referenced (mapped by >= 1 row), PARKED (refcount 0 but content
    retained here, LRU-evictable), or free (not indexed, on the free
    list). This class tracks the digest mapping for every indexed block
    and the parked subset in least-recently-used order; the allocator
    drives the transitions (``park`` on last release, ``unpark`` on a
    shared re-admission, ``evict_lru`` under pool pressure)."""

    def __init__(self) -> None:
        self._by_key: Dict[bytes, int] = {}
        self._by_block: Dict[int, bytes] = {}
        # refcount-0 indexed blocks, insertion order == LRU → MRU
        self._parked: "OrderedDict[int, None]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._by_key)

    @property
    def parked_count(self) -> int:
        return len(self._parked)

    def put(self, key: bytes, block: int) -> bool:
        """Publish ``block`` as the holder of ``key``'s content. No-op
        (False) when the key is already indexed — first writer wins and
        the duplicate block stays a plain private block — or when the
        block already holds another key (one identity per block)."""
        if key in self._by_key or block in self._by_block:
            return False
        self._by_key[key] = block
        self._by_block[block] = key
        return True

    def match(self, keys: Sequence[bytes]) -> List[int]:
        """Longest indexed prefix of ``keys`` → the blocks holding it.
        Stops at the first miss: a chain broken by eviction can never
        resume mid-prefix (the orphaned descendants simply age out)."""
        blocks: List[int] = []
        for key in keys:
            blk = self._by_key.get(key)
            if blk is None:
                break
            blocks.append(blk)
        return blocks

    def holds(self, block: int) -> bool:
        return block in self._by_block

    def park(self, block: int) -> None:
        """Last reference dropped: retain the content, join the LRU tail
        (most recently used end — it was just in service)."""
        if block not in self._by_block:
            raise ValueError(f"block {block} is not indexed")
        self._parked[block] = None
        self._parked.move_to_end(block)

    def unpark(self, block: int) -> None:
        """A parked block is being re-referenced (shared admission)."""
        self._parked.pop(block, None)

    def parked_blocks(self) -> List[int]:
        """The refcount-0 indexed block ids in LRU → MRU order — the
        leak audit's view of the parked partition (every parked block
        must also be indexed; tests/test_serve_failover.py cross-checks
        this against the allocator's pool partition after drain and
        deadline-cancellation chaos)."""
        bad = [blk for blk in self._parked if blk not in self._by_block]
        if bad:
            raise RuntimeError(
                f"parked blocks {bad} have no content index entry — "
                "park/evict bookkeeping diverged"
            )
        return list(self._parked)

    def evict_lru(self) -> int:
        """Reclaim the least-recently-used PARKED block: drop its digest
        so it can never match again, return it for reallocation. Only
        refcount-0 blocks are ever parked, so eviction can never touch a
        block some row still reads — the allocator calls this only when
        its free list is empty (pool pressure)."""
        if not self._parked:
            raise RuntimeError(
                "no evictable cached blocks (every indexed block is "
                "referenced) — the allocator's admission gate should "
                "have refused before reaching here"
            )
        block, _ = self._parked.popitem(last=False)
        key = self._by_block.pop(block)
        del self._by_key[key]
        return block
