"""Content-addressed RADIX-TREE prefix index for the paged serving KV
cache.

Cross-request KV reuse (round 6) made blocks addressable by content:
``chain_keys`` maps a prompt to one SHA-256 hash-chain digest per FULL
block, and the index maps digests to pool block ids so admission can map
already-written blocks into a new row. Round 9 upgrades the index from a
flat digest→block dict with a flat LRU to a **radix tree over block
digests** (SGLang RadixAttention / ChunkAttention, PAPERS.md):

  * interior nodes hold block RUNS shared by multiple chains (N few-shot
    variants of one system prompt share the preamble run physically;
    the tree splits a run exactly where chains diverge);
  * leaves carry the park/LRU state, and eviction is **leaf-first**: a
    block is reclaimable only when no cached descendant depends on it,
    so a hot interior run outlives its cold tails — the flat LRU could
    evict a shared ancestor and strand every descendant unmatchable;
  * ``match()`` walks the tree and returns the longest cached prefix
    for ANY branching point — including chains extended past a prompt
    by COMPLETION blocks (runtime/serving.py registers decoded blocks
    at row release), which is what lets a multi-turn successor (prompt
    = a prior request's full prompt + completion) hit the prior turn's
    whole chain.

Digest chaining already gives each key the prefix property (key j
commits to every token of blocks 0..j), so tree EDGES need no token
payload: equality of the next digest is equality of the whole prefix.
What the tree adds over the flat dict is the ancestry structure —
parent-linked insert (an orphan whose ancestor was evicted is refused,
never silently unmatchable), leaf-first eviction, and per-depth hit
accounting.

The K/V of prompt position i is a function of tokens 0..i alone, and
the serving engine writes each registered position exactly once before
publishing it, so an indexed block is FROZEN — sharing it is pure
bookkeeping and the engine's exactness contract carries over unchanged
(tested: tests/test_prefix_cache.py, tests/test_property_prefix_cache.py,
tests/test_serving.py)."""

from __future__ import annotations

import hashlib
import heapq
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def chain_keys(
    tokens: Sequence[int], block_size: int, limit: Optional[int] = None
) -> List[bytes]:
    """Hash-chain digests of the FULL ``block_size``-token blocks of
    ``tokens``: ``key[j] = sha256(key[j-1] || tokens[j*bs:(j+1)*bs])``.

    Chaining makes each key commit to the whole prefix through its
    block, so two prompts share key j iff they agree on every token of
    blocks 0..j — the prefix property the radix tree's edges rely on.
    The trailing partial block (if any) is never keyed: only
    fully-written blocks are shareable. SHA-256, not ``hash()``: a
    collision would silently serve one request another request's K/V,
    so the digest must be cryptographic."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    arr = np.asarray(tokens, dtype=np.int32)
    n = arr.shape[0] // block_size
    if limit is not None:
        n = min(n, int(limit))
    keys: List[bytes] = []
    h = b""
    for j in range(n):
        blk = arr[j * block_size : (j + 1) * block_size]
        h = hashlib.sha256(h + blk.tobytes()).digest()
        keys.append(h)
    return keys


class _RadixNode:
    """One tree node: a RUN of consecutive (digest, block) pairs shared
    by every chain through it, plus children keyed by the FIRST digest
    of each child's run. The root is a sentinel with an empty run (all
    chain roots are its children)."""

    __slots__ = ("keys", "blocks", "children", "parent")

    def __init__(self, parent: Optional["_RadixNode"] = None) -> None:
        self.keys: List[bytes] = []
        self.blocks: List[int] = []
        self.children: Dict[bytes, "_RadixNode"] = {}
        self.parent = parent


class PrefixCacheIndex:
    """Radix tree over block digests, plus the LRU set of refcount-0
    holders.

    A block is in exactly one of three states from the allocator's view:
    referenced (mapped by >= 1 row), PARKED (refcount 0 but content
    retained here, evictable), or free (not indexed, on the free list).
    This class owns content identity, tree ancestry, and LRU order; the
    ref-counted BlockAllocator (runtime/serving.py) owns the refcounts
    and drives the transitions (``park`` on last release, ``unpark`` on
    a shared re-admission, ``evict_lru`` under pool pressure).

    Eviction is LEAF-FIRST: ``evict_lru`` reclaims the least-recently
    -used parked block *that has no indexed descendant* (the tail of a
    childless run). The allocator's usage keeps references
    prefix-closed (a row mapping block j maps every ancestor of j), so
    the parked set is always descendant-closed and a parked evictable
    leaf exists whenever anything is parked at all — ``audit`` asserts
    exactly that closure under NEXUS_SANITIZE."""

    def __init__(self) -> None:
        self._root = _RadixNode()
        # digest → (node, offset into the node's run); the O(1) walk
        # accelerator and the parent-lookup for insert
        self._by_key: Dict[bytes, Tuple[_RadixNode, int]] = {}
        self._by_block: Dict[int, bytes] = {}
        # refcount-0 indexed blocks, insertion order == LRU → MRU
        self._parked: "OrderedDict[int, None]" = OrderedDict()
        # eviction accelerator: a min-heap of (park sequence, block)
        # candidate EVICTABLE LEAVES with lazy invalidation, so
        # evict_lru never linearly re-scans parked interior runs (a
        # long parked chain's ancestors sit at the LRU head — a plain
        # scan makes reclaiming an L-block chain Θ(L²)). Entries go
        # stale when a block is unparked/re-parked (sequence mismatch)
        # or gains a child (evictable() re-check at pop); a block
        # parked while a descendant still holds references gets its
        # entry pushed later, by the remove() that exposes it. The
        # sequence number mirrors the OrderedDict's park order exactly,
        # so victim choice is unchanged — audit() cross-checks that
        # every parked evictable block has a live heap entry.
        self._park_clock = 0
        self._park_seq: Dict[int, int] = {}
        self._leaf_heap: List[Tuple[int, int]] = []

    def __len__(self) -> int:
        return len(self._by_key)

    @property
    def parked_count(self) -> int:
        return len(self._parked)

    # ------------------------------------------------------------ insert

    def insert(
        self, key: bytes, block: int, parent: Optional[bytes] = None
    ) -> bool:
        """Publish ``block`` as the holder of ``key``'s content, attached
        under ``parent`` (the preceding digest of its chain; None = a
        chain root). No-op (False) when:

          * the key is already indexed — first writer wins, the
            duplicate block stays a plain private block;
          * the block already holds another key (one identity per
            block);
          * ``parent`` is given but not indexed — the ancestor was
            evicted, and an orphan that could never be reached by a
            root walk must not enter the tree (the flat index used to
            keep such orphans around, unmatchable, until LRU aged them
            out).
        """
        if key in self._by_key or block in self._by_block:
            return False
        if parent is None:
            node: _RadixNode = self._root
            off = -1
        else:
            loc = self._by_key.get(parent)
            if loc is None:
                return False
            node, off = loc
        if node is self._root:
            # the root carries no run; every chain root is a child
            child = _RadixNode(parent=node)
            child.keys.append(key)
            child.blocks.append(block)
            node.children[key] = child
            target, toff = child, 0
        elif off == len(node.keys) - 1 and not node.children:
            # path compression: extend the run in place
            node.keys.append(key)
            node.blocks.append(block)
            target, toff = node, len(node.keys) - 1
        elif off == len(node.keys) - 1:
            # run end already branches: one more branch
            child = _RadixNode(parent=node)
            child.keys.append(key)
            child.blocks.append(block)
            node.children[key] = child
            target, toff = child, 0
        else:
            # the chain diverges MID-run: split the node so the shared
            # ancestors [..off] become an interior run and the old
            # suffix + the new key become siblings
            suffix = _RadixNode(parent=node)
            suffix.keys = node.keys[off + 1 :]
            suffix.blocks = node.blocks[off + 1 :]
            suffix.children = node.children
            for ch in suffix.children.values():
                ch.parent = suffix
            node.keys = node.keys[: off + 1]
            node.blocks = node.blocks[: off + 1]
            node.children = {suffix.keys[0]: suffix}
            for i, k in enumerate(suffix.keys):
                self._by_key[k] = (suffix, i)
            child = _RadixNode(parent=node)
            child.keys.append(key)
            child.blocks.append(block)
            node.children[key] = child
            target, toff = child, 0
        self._by_key[key] = (target, toff)
        self._by_block[block] = key
        return True

    def put(
        self, key: bytes, block: int, parent: Optional[bytes] = None
    ) -> bool:
        """Alias of :meth:`insert` (the round-6 flat-index name)."""
        return self.insert(key, block, parent=parent)

    # ------------------------------------------------------------- match

    def match(self, keys: Sequence[bytes]) -> List[int]:
        """Walk the tree from the root along ``keys`` → the blocks of
        the longest cached prefix. Because digests chain, the walk stops
        at the first divergence — whether that is a miss at a branch
        point, mid-run, or simply the end of what is cached. Chains
        extended by completion blocks match exactly like prompt chains
        (the tree does not know the difference)."""
        blocks: List[int] = []
        node = self._root
        i = 0
        while i < len(keys):
            nxt = node.children.get(keys[i])
            if nxt is None:
                break
            node = nxt
            for j in range(len(node.keys)):
                if i < len(keys) and node.keys[j] == keys[i]:
                    blocks.append(node.blocks[j])
                    i += 1
                else:
                    return blocks  # diverged mid-run / keys exhausted
        return blocks

    def holds(self, block: int) -> bool:
        return block in self._by_block

    def holder(self, key: bytes) -> Optional[int]:
        """The block currently holding ``key``'s content, or None. The
        serving engine's registration guard uses this: a row may extend
        the tree only under a parent digest held by the row's OWN block
        — attaching a referenced block beneath ANOTHER lease's block
        (duplicate-content race, CoW source) could leave a parked run
        with referenced descendants, which breaks the descendant
        closure that leaf-first eviction's progress relies on."""
        loc = self._by_key.get(key)
        if loc is None:
            return None
        node, off = loc
        return node.blocks[off]

    # -------------------------------------------------------- park / LRU

    def park(self, block: int) -> None:
        """Last reference dropped: retain the content, join the LRU tail
        (most recently used end — it was just in service)."""
        if block not in self._by_block:
            raise ValueError(f"block {block} is not indexed")
        self._parked[block] = None
        self._parked.move_to_end(block)
        self._park_clock += 1
        self._park_seq[block] = self._park_clock
        if self.evictable(block):
            heapq.heappush(self._leaf_heap, (self._park_clock, block))

    def unpark(self, block: int) -> None:
        """A parked block is being re-referenced (shared admission)."""
        self._parked.pop(block, None)
        # any heap entry goes stale by sequence mismatch
        self._park_seq.pop(block, None)

    def parked_blocks(self) -> List[int]:
        """The refcount-0 indexed block ids in LRU → MRU order — the
        leak audit's view of the parked partition (every parked block
        must also be indexed; tests/test_serve_failover.py cross-checks
        this against the allocator's pool partition after drain and
        deadline-cancellation chaos)."""
        bad = [blk for blk in self._parked if blk not in self._by_block]
        if bad:
            raise RuntimeError(
                f"parked blocks {bad} have no content index entry — "
                "park/evict bookkeeping diverged"
            )
        return list(self._parked)

    # ----------------------------------------------------------- evict

    def evictable(self, block: int) -> bool:
        """True when ``block`` has no indexed descendant — it is the
        tail of a childless run, so removing it cannot strand a cached
        chain (leaf-first eviction's unit test)."""
        key = self._by_block.get(block)
        if key is None:
            return False
        node, off = self._by_key[key]
        return off == len(node.keys) - 1 and not node.children

    def remove(self, block: int) -> None:
        """Remove an indexed LEAF block from the tree: drop its digest
        so it can never match again. Refuses (RuntimeError) to remove a
        block with indexed descendants — interior runs must outlive
        their tails by construction, never by caller discipline."""
        key = self._by_block.get(block)
        if key is None:
            raise ValueError(f"block {block} is not indexed")
        node, off = self._by_key[key]
        if off != len(node.keys) - 1 or node.children:
            raise RuntimeError(
                f"block {block} still has cached descendants — "
                "leaf-first eviction must reclaim the tails first"
            )
        node.keys.pop()
        node.blocks.pop()
        del self._by_key[key]
        del self._by_block[block]
        self._parked.pop(block, None)
        self._park_seq.pop(block, None)
        exposed: Optional[_RadixNode] = None
        if not node.keys and node.parent is not None:
            # the run emptied: unlink the node (its first — only — key
            # was `key`, which is how the parent indexed it)
            del node.parent.children[key]
            exposed = node.parent
        elif node.keys:
            exposed = node
        # the removal may expose a NEW evictable leaf (the run's new
        # tail, or the parent's tail once its last child unlinks) — if
        # that block is parked, (re)arm its heap entry at its original
        # park sequence so eviction order stays exactly park-LRU
        if (exposed is not None and exposed.parent is not None
                and exposed.keys and not exposed.children):
            tail = exposed.blocks[-1]
            seq = self._park_seq.get(tail)
            if seq is not None:
                heapq.heappush(self._leaf_heap, (seq, tail))

    def evict_lru(self) -> int:
        """Reclaim the least-recently-used parked block WITHOUT cached
        descendants (leaf-first): drop its digest, return it for
        reallocation. Only refcount-0 blocks are ever parked, so
        eviction can never touch a block some row still reads — the
        allocator calls this only when its free list is empty (pool
        pressure). The allocator keeps references prefix-closed, which
        makes the parked set descendant-closed — so whenever anything
        is parked, a parked evictable leaf exists."""
        if not self._parked:
            raise RuntimeError(
                "no evictable cached blocks (every indexed block is "
                "referenced) — the allocator's admission gate should "
                "have refused before reaching here"
            )
        # lazy-invalidation pop: a stale entry is one whose block was
        # unparked (sequence gone), re-parked (sequence moved), or grew
        # a child since it was pushed — skip it; each stale entry is
        # dropped exactly once, so eviction stays amortized O(log n)
        # instead of re-scanning parked interior runs every call
        while self._leaf_heap:
            seq, block = heapq.heappop(self._leaf_heap)
            if self._park_seq.get(block) != seq:
                continue
            if not self.evictable(block):
                continue
            self.remove(block)
            return block
        raise RuntimeError(
            "every parked block has cached descendants that are "
            "still referenced — the allocator's prefix-closed "
            "reference invariant is broken (see audit())"
        )

    # ----------------------------------------------------------- audit

    def audit(self) -> None:
        """The radix-tree invariant, asserted (NEXUS_SANITIZE runs this
        next to the pool-partition audit):

          * structure: every non-root node holds a non-empty run, its
            parent's child entry is keyed by its first digest, and the
            digest/block accelerator maps agree exactly with the runs
            (each block holds one identity, reachable from the root);
          * parked ⊆ indexed (LRU entries always have content);
          * descendant closure: a PARKED block's immediate descendants
            are all parked too — the arithmetic reason leaf-first
            eviction can always make progress and the allocator may
            count every parked block as reclaimable capacity.
        """
        seen_keys: Dict[bytes, Tuple[_RadixNode, int]] = {}
        seen_blocks: Dict[int, bytes] = {}
        stack = [self._root]
        while stack:
            node = stack.pop()
            if node is not self._root and not node.keys:
                raise AssertionError("empty non-root radix node")
            if len(node.keys) != len(node.blocks):
                raise AssertionError("run keys/blocks length mismatch")
            for i, (k, b) in enumerate(zip(node.keys, node.blocks)):
                if k in seen_keys or b in seen_blocks:
                    raise AssertionError(
                        f"digest or block {b} indexed twice"
                    )
                seen_keys[k] = (node, i)
                seen_blocks[b] = k
            for first, child in node.children.items():
                if child.parent is not node:
                    raise AssertionError("child parent link broken")
                if not child.keys or child.keys[0] != first:
                    raise AssertionError(
                        "child entry not keyed by its first digest"
                    )
                stack.append(child)
        if seen_keys != self._by_key:
            raise AssertionError(
                "digest accelerator map diverged from the tree"
            )
        if seen_blocks != self._by_block:
            raise AssertionError(
                "block accelerator map diverged from the tree"
            )
        for blk in self._parked:
            if blk not in self._by_block:
                raise AssertionError(
                    f"parked block {blk} has no content index entry"
                )
        parked = set(self._parked)
        for blk in parked:
            node, off = self._by_key[self._by_block[blk]]
            if off + 1 < len(node.keys):
                descendants = [node.blocks[off + 1]]
            else:
                descendants = [ch.blocks[0] for ch in node.children.values()]
            for d in descendants:
                if d not in parked:
                    raise AssertionError(
                        f"parked block {blk} has referenced descendant "
                        f"{d} — references are no longer prefix-closed"
                    )
        # eviction accelerator coherence: the sequence map tracks the
        # parked set exactly, and every parked EVICTABLE block has a
        # live heap entry (else evict_lru could raise with work left)
        if set(self._park_seq) != parked:
            raise AssertionError(
                "park-sequence map diverged from the parked set"
            )
        live = set(self._leaf_heap)
        for blk in parked:
            if (self.evictable(blk)
                    and (self._park_seq[blk], blk) not in live):
                raise AssertionError(
                    f"parked evictable block {blk} has no live "
                    "eviction-heap entry"
                )
