"""Content-addressed RADIX-TREE prefix index for the paged serving KV
cache.

Cross-request KV reuse (round 6) made blocks addressable by content:
``chain_keys`` maps a prompt to one SHA-256 hash-chain digest per FULL
block, and the index maps digests to pool block ids so admission can map
already-written blocks into a new row. Round 9 upgrades the index from a
flat digest→block dict with a flat LRU to a **radix tree over block
digests** (SGLang RadixAttention / ChunkAttention, PAPERS.md):

  * interior nodes hold block RUNS shared by multiple chains (N few-shot
    variants of one system prompt share the preamble run physically;
    the tree splits a run exactly where chains diverge);
  * leaves carry the park/LRU state, and eviction is **leaf-first**: a
    block is reclaimable only when no cached descendant depends on it,
    so a hot interior run outlives its cold tails — the flat LRU could
    evict a shared ancestor and strand every descendant unmatchable;
  * ``match()`` walks the tree and returns the longest cached prefix
    for ANY branching point — including chains extended past a prompt
    by COMPLETION blocks (runtime/serving.py registers decoded blocks
    at row release), which is what lets a multi-turn successor (prompt
    = a prior request's full prompt + completion) hit the prior turn's
    whole chain.

Digest chaining already gives each key the prefix property (key j
commits to every token of blocks 0..j), so tree EDGES need no token
payload: equality of the next digest is equality of the whole prefix.
What the tree adds over the flat dict is the ancestry structure —
parent-linked insert (an orphan whose ancestor was evicted is refused,
never silently unmatchable), leaf-first eviction, and per-depth hit
accounting.

Round 10 adds a THIRD residency state: **spilled**. With a host-RAM
tier attached (runtime/host_cache.py), pool pressure DEMOTES the
eviction victim instead of destroying it — ``spill`` keeps the entry's
digest in the tree (its block slot becomes the ``SPILLED`` sentinel and
the K/V bytes move to the host store), and a later ``match_tiered``
reports the spilled span after the resident prefix so admission can
PROMOTE it: ``restore`` rebinds the digest to a freshly-allocated pool
block the engine uploads the host copy into. Spill is leaf-first like
eviction, and restore always extends the resident frontier downward, so
every root-to-leaf path is a resident prefix followed by a spilled
suffix — the closure ``audit`` asserts, and the reason a resident
``match`` can simply stop at the first spilled entry. Host-budget
pressure removes spilled entries leaf-first too (``evict_spilled_lru``)
so a dropped tail can never strand a restorable ancestor chain.

The K/V of prompt position i is a function of tokens 0..i alone, and
the serving engine writes each registered position exactly once before
publishing it, so an indexed block is FROZEN — sharing it is pure
bookkeeping and the engine's exactness contract carries over unchanged
(tested: tests/test_prefix_cache.py, tests/test_property_prefix_cache.py,
tests/test_serving.py)."""

from __future__ import annotations

import hashlib
import heapq
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: block-slot sentinel for a tree entry whose K/V live in the host tier
#: (the digest stays matchable, the pool block is gone)
SPILLED = -1


def chain_keys(
    tokens: Sequence[int], block_size: int, limit: Optional[int] = None
) -> List[bytes]:
    """Hash-chain digests of the FULL ``block_size``-token blocks of
    ``tokens``: ``key[j] = sha256(key[j-1] || tokens[j*bs:(j+1)*bs])``.

    Chaining makes each key commit to the whole prefix through its
    block, so two prompts share key j iff they agree on every token of
    blocks 0..j — the prefix property the radix tree's edges rely on.
    The trailing partial block (if any) is never keyed: only
    fully-written blocks are shareable. SHA-256, not ``hash()``: a
    collision would silently serve one request another request's K/V,
    so the digest must be cryptographic."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    arr = np.asarray(tokens, dtype=np.int32)
    n = arr.shape[0] // block_size
    if limit is not None:
        n = min(n, int(limit))
    keys: List[bytes] = []
    h = b""
    for j in range(n):
        blk = arr[j * block_size : (j + 1) * block_size]
        h = hashlib.sha256(h + blk.tobytes()).digest()
        keys.append(h)
    return keys


class _RadixNode:
    """One tree node: a RUN of consecutive (digest, block) pairs shared
    by every chain through it, plus children keyed by the FIRST digest
    of each child's run. The root is a sentinel with an empty run (all
    chain roots are its children)."""

    __slots__ = ("keys", "blocks", "children", "parent")

    def __init__(self, parent: Optional["_RadixNode"] = None) -> None:
        self.keys: List[bytes] = []
        self.blocks: List[int] = []
        self.children: Dict[bytes, "_RadixNode"] = {}
        self.parent = parent


class PrefixCacheIndex:
    """Radix tree over block digests, plus the LRU set of refcount-0
    holders.

    A block is in exactly one of three states from the allocator's view:
    referenced (mapped by >= 1 row), PARKED (refcount 0 but content
    retained here, evictable), or free (not indexed, on the free list).
    This class owns content identity, tree ancestry, and LRU order; the
    ref-counted BlockAllocator (runtime/serving.py) owns the refcounts
    and drives the transitions (``park`` on last release, ``unpark`` on
    a shared re-admission, ``evict_lru`` under pool pressure).

    Eviction is LEAF-FIRST: ``evict_lru`` reclaims the least-recently
    -used parked block *that has no indexed descendant* (the tail of a
    childless run). The allocator's usage keeps references
    prefix-closed (a row mapping block j maps every ancestor of j), so
    the parked set is always descendant-closed and a parked evictable
    leaf exists whenever anything is parked at all — ``audit`` asserts
    exactly that closure under NEXUS_SANITIZE."""

    def __init__(self) -> None:
        self._root = _RadixNode()
        # digest → (node, offset into the node's run); the O(1) walk
        # accelerator and the parent-lookup for insert
        self._by_key: Dict[bytes, Tuple[_RadixNode, int]] = {}
        self._by_block: Dict[int, bytes] = {}
        # refcount-0 indexed blocks, insertion order == LRU → MRU
        self._parked: "OrderedDict[int, None]" = OrderedDict()
        # eviction accelerator: a min-heap of (park sequence, block)
        # candidate EVICTABLE LEAVES with lazy invalidation, so
        # evict_lru never linearly re-scans parked interior runs (a
        # long parked chain's ancestors sit at the LRU head — a plain
        # scan makes reclaiming an L-block chain Θ(L²)). Entries go
        # stale when a block is unparked/re-parked (sequence mismatch)
        # or gains a child (evictable() re-check at pop); a block
        # parked while a descendant still holds references gets its
        # entry pushed later, by the remove() that exposes it. The
        # sequence number mirrors the OrderedDict's park order exactly,
        # so victim choice is unchanged — audit() cross-checks that
        # every parked evictable block has a live heap entry.
        self._park_clock = 0
        self._park_seq: Dict[int, int] = {}
        self._leaf_heap: List[Tuple[int, int]] = []
        # ---- the SPILLED tier (round 10) ----
        # digest → spill sequence for entries whose K/V moved to the
        # host store; plus the host-budget eviction accelerator — a
        # min-heap of (spill sequence, digest) FULL-LEAF candidates
        # with the same lazy invalidation as _leaf_heap. Leaf-first
        # spill means descendants spill before ancestors, so spill
        # sequence order is naturally tail-first and LRU host eviction
        # drops cold tails before the chains that need them.
        self._spill_clock = 0
        self._spilled: Dict[bytes, int] = {}
        self._spilled_heap: List[Tuple[int, bytes]] = []

    def __len__(self) -> int:
        return len(self._by_key)

    @property
    def parked_count(self) -> int:
        return len(self._parked)

    @property
    def spilled_count(self) -> int:
        """Tree entries whose K/V live in the host tier."""
        return len(self._spilled)

    def indexed_keys(self) -> List[bytes]:
        """Every digest the tree currently indexes — resident AND
        spilled (a spilled entry keeps its digest; only its block moved
        to the host tier). The committed-publication audit's iteration
        surface (testing/sanitizers.py): after a serve run, every one
        of these must be a hash-chain prefix of text some request
        actually committed."""
        return list(self._by_key)

    # ------------------------------------------------------------ insert

    def insert(
        self, key: bytes, block: int, parent: Optional[bytes] = None
    ) -> bool:
        """Publish ``block`` as the holder of ``key``'s content, attached
        under ``parent`` (the preceding digest of its chain; None = a
        chain root). No-op (False) when:

          * the key is already indexed — first writer wins, the
            duplicate block stays a plain private block;
          * the block already holds another key (one identity per
            block);
          * ``parent`` is given but not indexed — the ancestor was
            evicted, and an orphan that could never be reached by a
            root walk must not enter the tree (the flat index used to
            keep such orphans around, unmatchable, until LRU aged them
            out).
        """
        if key in self._by_key or block in self._by_block:
            return False
        if parent is None:
            node: _RadixNode = self._root
            off = -1
        else:
            loc = self._by_key.get(parent)
            if loc is None:
                return False
            node, off = loc
        if node is self._root:
            # the root carries no run; every chain root is a child
            child = _RadixNode(parent=node)
            child.keys.append(key)
            child.blocks.append(block)
            node.children[key] = child
            target, toff = child, 0
        elif off == len(node.keys) - 1 and not node.children:
            # path compression: extend the run in place
            node.keys.append(key)
            node.blocks.append(block)
            target, toff = node, len(node.keys) - 1
        elif off == len(node.keys) - 1:
            # run end already branches: one more branch
            child = _RadixNode(parent=node)
            child.keys.append(key)
            child.blocks.append(block)
            node.children[key] = child
            target, toff = child, 0
        else:
            # the chain diverges MID-run: split the node so the shared
            # ancestors [..off] become an interior run and the old
            # suffix + the new key become siblings
            suffix = _RadixNode(parent=node)
            suffix.keys = node.keys[off + 1 :]
            suffix.blocks = node.blocks[off + 1 :]
            suffix.children = node.children
            for ch in suffix.children.values():
                ch.parent = suffix
            node.keys = node.keys[: off + 1]
            node.blocks = node.blocks[: off + 1]
            node.children = {suffix.keys[0]: suffix}
            for i, k in enumerate(suffix.keys):
                self._by_key[k] = (suffix, i)
            child = _RadixNode(parent=node)
            child.keys.append(key)
            child.blocks.append(block)
            node.children[key] = child
            target, toff = child, 0
        self._by_key[key] = (target, toff)
        self._by_block[block] = key
        return True

    def put(
        self, key: bytes, block: int, parent: Optional[bytes] = None
    ) -> bool:
        """Alias of :meth:`insert` (the round-6 flat-index name)."""
        return self.insert(key, block, parent=parent)

    # ------------------------------------------------------------- match

    def match(self, keys: Sequence[bytes]) -> List[int]:
        """Walk the tree from the root along ``keys`` → the blocks of
        the longest RESIDENT cached prefix. Because digests chain, the
        walk stops at the first divergence — a miss at a branch point,
        mid-run, the end of what is cached, or a SPILLED entry (whose
        K/V are in the host tier, not the pool; ``match_tiered``
        reports that continuation). Chains extended by completion
        blocks match exactly like prompt chains (the tree does not know
        the difference)."""
        return self.match_tiered(keys)[0]

    def match_tiered(
        self, keys: Sequence[bytes]
    ) -> Tuple[List[int], List[bytes]]:
        """Walk the tree along ``keys`` → ``(resident_blocks,
        spilled_keys)``: the pool blocks of the longest resident prefix,
        then the digests of the CONTIGUOUS spilled span that extends it
        (restorable from the host store). Spill is leaf-first and
        restore extends the resident frontier downward, so along any
        root path residency is a prefix — the first spilled entry ends
        the resident span for good, and the spilled span ends at the
        first divergence or un-spilled gap."""
        blocks: List[int] = []
        spilled: List[bytes] = []
        node = self._root
        i = 0
        while i < len(keys):
            nxt = node.children.get(keys[i])
            if nxt is None:
                break
            node = nxt
            for j in range(len(node.keys)):
                if i < len(keys) and node.keys[j] == keys[i]:
                    if node.keys[j] in self._spilled:
                        spilled.append(node.keys[j])
                    elif spilled:
                        # a resident entry below a spilled one would
                        # violate the residency-prefix closure audit()
                        # asserts — never extend the span across it
                        return blocks, spilled
                    else:
                        blocks.append(node.blocks[j])
                    i += 1
                else:
                    return blocks, spilled  # diverged / keys exhausted
        return blocks, spilled

    def holds(self, block: int) -> bool:
        return block in self._by_block

    def holder(self, key: bytes) -> Optional[int]:
        """The POOL block currently holding ``key``'s content, or None
        (unknown digest, or spilled — host bytes are nobody's lease).
        The serving engine's registration guard uses this: a row may
        extend the tree only under a parent digest held by the row's
        OWN block — attaching a referenced block beneath ANOTHER
        lease's block (duplicate-content race, CoW source) could leave
        a parked run with referenced descendants, which breaks the
        descendant closure that leaf-first eviction's progress relies
        on."""
        loc = self._by_key.get(key)
        if loc is None or key in self._spilled:
            return None
        node, off = loc
        return node.blocks[off]

    # -------------------------------------------------------- park / LRU

    def park(self, block: int) -> None:
        """Last reference dropped: retain the content, join the LRU tail
        (most recently used end — it was just in service)."""
        if block not in self._by_block:
            raise ValueError(f"block {block} is not indexed")
        self._parked[block] = None
        self._parked.move_to_end(block)
        self._park_clock += 1
        self._park_seq[block] = self._park_clock
        if self.evictable(block):
            heapq.heappush(self._leaf_heap, (self._park_clock, block))

    def unpark(self, block: int) -> None:
        """A parked block is being re-referenced (shared admission)."""
        self._parked.pop(block, None)
        # any heap entry goes stale by sequence mismatch
        self._park_seq.pop(block, None)

    def parked_blocks(self) -> List[int]:
        """The refcount-0 indexed block ids in LRU → MRU order — the
        leak audit's view of the parked partition (every parked block
        must also be indexed; tests/test_serve_failover.py cross-checks
        this against the allocator's pool partition after drain and
        deadline-cancellation chaos)."""
        bad = [blk for blk in self._parked if blk not in self._by_block]
        if bad:
            raise RuntimeError(
                f"parked blocks {bad} have no content index entry — "
                "park/evict bookkeeping diverged"
            )
        return list(self._parked)

    # ----------------------------------------------------------- evict

    def _descendant_entries(
        self, node: _RadixNode, off: int
    ) -> List[bytes]:
        """The IMMEDIATE descendant digests of the entry at (node, off):
        the run's next entry, or every child's first entry at the run
        end. Closure arguments only ever need the immediate layer."""
        if off + 1 < len(node.keys):
            return [node.keys[off + 1]]
        return [ch.keys[0] for ch in node.children.values()]

    def evictable(self, block: int) -> bool:
        """True when ``block`` has no RESIDENT indexed descendant — its
        descendants (if any) are all spilled, so reclaiming (or
        spilling) it cannot strand a resident chain. Without a host
        tier nothing is ever spilled and this is exactly the old
        no-descendants-at-all rule (leaf-first eviction's unit
        test)."""
        key = self._by_block.get(block)
        if key is None:
            return False
        node, off = self._by_key[key]
        return all(
            d in self._spilled
            for d in self._descendant_entries(node, off)
        )

    def _remove_entry(self, key: bytes) -> None:
        """Shared tail surgery for ``remove`` (resident leaf) and
        ``remove_spilled`` (spilled leaf): pop the entry from its run,
        unlink an emptied node, and re-arm the heap entry of whatever
        leaf the removal exposes — a PARKED new tail re-enters
        ``_leaf_heap`` at its original park sequence (victim choice
        stays exactly park-LRU), a SPILLED new full-leaf re-enters
        ``_spilled_heap`` at its original spill sequence. Callers have
        already validated leaf-ness and cleared their own state maps."""
        node, _ = self._by_key.pop(key)
        node.keys.pop()
        node.blocks.pop()
        exposed: Optional[_RadixNode] = None
        if not node.keys and node.parent is not None:
            # the run emptied: unlink the node (its first — only — key
            # was `key`, which is how the parent indexed it)
            del node.parent.children[key]
            exposed = node.parent
        elif node.keys:
            exposed = node
        if (exposed is None or exposed.parent is None
                or not exposed.keys or exposed.children):
            return
        tail_key = exposed.keys[-1]
        sseq = self._spilled.get(tail_key)
        if sseq is not None:
            heapq.heappush(self._spilled_heap, (sseq, tail_key))
            return
        tail = exposed.blocks[-1]
        seq = self._park_seq.get(tail)
        if seq is not None:
            heapq.heappush(self._leaf_heap, (seq, tail))

    def remove(self, block: int) -> None:
        """Remove an indexed LEAF block from the tree: drop its digest
        so it can never match again. Refuses (RuntimeError) to remove a
        block with indexed descendants — interior runs must outlive
        their tails by construction, never by caller discipline.
        (Spilled descendants refuse too: discarding a resident entry
        under which host-tier content hangs would strand it
        unmatchable — the allocator spills, never removes, when a host
        tier is attached.)"""
        key = self._by_block.get(block)
        if key is None:
            raise ValueError(f"block {block} is not indexed")
        node, off = self._by_key[key]
        if off != len(node.keys) - 1 or node.children:
            raise RuntimeError(
                f"block {block} still has cached descendants — "
                "leaf-first eviction must reclaim the tails first"
            )
        del self._by_block[block]
        self._parked.pop(block, None)
        self._park_seq.pop(block, None)
        self._remove_entry(key)

    def _pop_victim(self) -> int:
        """The least-recently-used parked block without resident
        descendants — the ONE victim-selection rule ``evict_lru``
        (discard) and ``spill_lru`` (demote to the host tier) share, so
        attaching a host tier never changes WHICH block pool pressure
        reclaims. Lazy-invalidation pop: a stale entry is one whose
        block was unparked (sequence gone), re-parked (sequence moved),
        or grew a resident child since it was pushed — skip it; each
        stale entry is dropped exactly once, so selection stays
        amortized O(log n) instead of re-scanning parked interior runs
        every call. The popped block is STILL parked and indexed — the
        caller immediately removes or spills it."""
        if not self._parked:
            raise RuntimeError(
                "no evictable cached blocks (every indexed block is "
                "referenced) — the allocator's admission gate should "
                "have refused before reaching here"
            )
        while self._leaf_heap:
            seq, block = heapq.heappop(self._leaf_heap)
            if self._park_seq.get(block) != seq:
                continue
            if not self.evictable(block):
                continue
            return block
        raise RuntimeError(
            "every parked block has cached descendants that are "
            "still referenced — the allocator's prefix-closed "
            "reference invariant is broken (see audit())"
        )

    def evict_lru(self) -> int:
        """Reclaim the least-recently-used parked block WITHOUT
        resident descendants (leaf-first): drop its digest, return it
        for reallocation. Only refcount-0 blocks are ever parked, so
        eviction can never touch a block some row still reads — the
        allocator calls this only when its free list is empty (pool
        pressure) and no host tier is attached (with one, ``spill_lru``
        demotes the same victim instead). The allocator keeps
        references prefix-closed, which makes the parked set
        descendant-closed — so whenever anything is parked, a parked
        evictable leaf exists."""
        block = self._pop_victim()
        self.remove(block)
        return block

    # ----------------------------------------------------- spill tier

    def spill(self, block: int) -> bytes:
        """DEMOTE a parked evictable block: its digest stays in the
        tree (block slot becomes the ``SPILLED`` sentinel) so the chain
        remains matchable, while the pool block returns to the caller
        for reallocation — the caller has already downloaded the K/V
        into the host store under the returned digest. Mirrors
        ``remove``'s preconditions (parked, no resident descendant) and
        its exposure bookkeeping: the predecessor entry may become
        newly evictable (its descendant is now spilled), so a parked
        predecessor re-arms in ``_leaf_heap`` at its original park
        sequence."""
        key = self._by_block.get(block)
        if key is None:
            raise ValueError(f"block {block} is not indexed")
        if block not in self._parked:
            raise ValueError(f"block {block} is referenced, not parked")
        node, off = self._by_key[key]
        if not self.evictable(block):
            raise RuntimeError(
                f"block {block} still has resident descendants — "
                "leaf-first spill must demote the tails first"
            )
        node.blocks[off] = SPILLED
        del self._by_block[block]
        self._parked.pop(block, None)
        self._park_seq.pop(block, None)
        self._spill_clock += 1
        self._spilled[key] = self._spill_clock
        if off == len(node.keys) - 1 and not node.children:
            # a FULL leaf (no indexed descendants at all) is a
            # host-budget eviction candidate right away; interior
            # spilled entries arm later, when _remove_entry exposes them
            heapq.heappush(
                self._spilled_heap, (self._spill_clock, key)
            )
        # the predecessor entry just lost its only resident descendant
        # this side — if parked and now evictable, (re)arm it
        if off > 0:
            pred = node.blocks[off - 1]
        elif node.parent is not None and node.parent.keys:
            pred = node.parent.blocks[-1]
        else:
            pred = SPILLED
        if pred != SPILLED:
            seq = self._park_seq.get(pred)
            if seq is not None and self.evictable(pred):
                heapq.heappush(self._leaf_heap, (seq, pred))
        return key

    def spill_lru(self) -> Tuple[int, bytes]:
        """Victim selection + demotion in one step: the SAME block
        ``evict_lru`` would reclaim, spilled instead of removed →
        ``(block, digest)`` for the caller to download and free."""
        block = self._pop_victim()
        return block, self.spill(block)

    def restore(self, key: bytes, block: int) -> None:
        """PROMOTE a spilled entry: bind its digest to ``block`` (a
        freshly-allocated pool block the engine is uploading the host
        copy into). The entry comes back REFERENCED — the restoring
        lease maps it — never parked; any live ``_spilled_heap`` entry
        goes stale by sequence lookup."""
        if key not in self._spilled:
            raise ValueError("digest is not spilled")
        if block in self._by_block:
            raise ValueError(f"block {block} already holds content")
        del self._spilled[key]
        node, off = self._by_key[key]
        node.blocks[off] = block
        self._by_block[block] = key

    def evict_spilled_lru(self) -> bytes:
        """Host-budget pressure: drop the least-recently-SPILLED entry
        with no indexed descendant at all (the spilled fringe's full
        leaves) from the tree and return its digest — the caller drops
        the matching host-store entry, keeping tree and store in
        lockstep. Leaf-first spill stamps descendants with earlier
        sequences than their ancestors, so LRU order here is naturally
        tail-first and a restorable ancestor chain is never stranded
        behind a dropped tail."""
        while self._spilled_heap:
            seq, key = heapq.heappop(self._spilled_heap)
            if self._spilled.get(key) != seq:
                continue
            node, off = self._by_key[key]
            if off != len(node.keys) - 1 or node.children:
                continue  # grew a descendant; re-armed on its removal
            del self._spilled[key]
            self._remove_entry(key)
            return key
        raise RuntimeError(
            "no spilled entry is a full leaf — the spilled tier's "
            "leaf-first closure is broken (see audit())"
        )

    # ----------------------------------------------------------- audit

    def audit(self) -> None:
        """The radix-tree invariant, asserted (NEXUS_SANITIZE runs this
        next to the pool-partition audit):

          * structure: every non-root node holds a non-empty run, its
            parent's child entry is keyed by its first digest, and the
            digest/block accelerator maps agree exactly with the runs
            (each block holds one identity, reachable from the root);
          * parked ⊆ indexed (LRU entries always have content);
          * spilled coherence: an entry is in ``_spilled`` iff its run
            slot carries the ``SPILLED`` sentinel (no pool block);
          * descendant closure: a PARKED block's immediate descendants
            are all parked or spilled (nothing referenced hangs below
            reclaimable capacity), and a SPILLED entry's immediate
            descendants are all spilled — residency is a prefix of
            every root path, the arithmetic reason leaf-first
            eviction/spill can always make progress and a resident
            ``match`` may stop at the first spilled entry.
        """
        seen_keys: Dict[bytes, Tuple[_RadixNode, int]] = {}
        seen_blocks: Dict[int, bytes] = {}
        seen_spilled = set()
        stack = [self._root]
        while stack:
            node = stack.pop()
            if node is not self._root and not node.keys:
                raise AssertionError("empty non-root radix node")
            if len(node.keys) != len(node.blocks):
                raise AssertionError("run keys/blocks length mismatch")
            for i, (k, b) in enumerate(zip(node.keys, node.blocks)):
                if k in seen_keys:
                    raise AssertionError("digest indexed twice")
                seen_keys[k] = (node, i)
                if b == SPILLED:
                    seen_spilled.add(k)
                else:
                    if b in seen_blocks:
                        raise AssertionError(f"block {b} indexed twice")
                    seen_blocks[b] = k
            for first, child in node.children.items():
                if child.parent is not node:
                    raise AssertionError("child parent link broken")
                if not child.keys or child.keys[0] != first:
                    raise AssertionError(
                        "child entry not keyed by its first digest"
                    )
                stack.append(child)
        if seen_keys != self._by_key:
            raise AssertionError(
                "digest accelerator map diverged from the tree"
            )
        if seen_blocks != self._by_block:
            raise AssertionError(
                "block accelerator map diverged from the tree"
            )
        if seen_spilled != set(self._spilled):
            raise AssertionError(
                "spilled-entry map diverged from the tree's SPILLED "
                "slots"
            )
        for blk in self._parked:
            if blk not in self._by_block:
                raise AssertionError(
                    f"parked block {blk} has no content index entry"
                )
        parked = set(self._parked)
        for blk in parked:
            node, off = self._by_key[self._by_block[blk]]
            for d in self._descendant_entries(node, off):
                if d in self._spilled:
                    continue  # spilled = refcount-0 by construction
                dnode, doff = self._by_key[d]
                if dnode.blocks[doff] not in parked:
                    raise AssertionError(
                        f"parked block {blk} has referenced descendant "
                        f"{dnode.blocks[doff]} — references are no "
                        "longer prefix-closed"
                    )
        for key in self._spilled:
            node, off = self._by_key[key]
            for d in self._descendant_entries(node, off):
                if d not in self._spilled:
                    raise AssertionError(
                        "spilled entry has a resident descendant — "
                        "residency is no longer a prefix of its root "
                        "path"
                    )
        # eviction accelerator coherence: the sequence map tracks the
        # parked set exactly, and every parked EVICTABLE block has a
        # live heap entry (else evict_lru could raise with work left)
        if set(self._park_seq) != parked:
            raise AssertionError(
                "park-sequence map diverged from the parked set"
            )
        live = set(self._leaf_heap)
        for blk in parked:
            if (self.evictable(blk)
                    and (self._park_seq[blk], blk) not in live):
                raise AssertionError(
                    f"parked evictable block {blk} has no live "
                    "eviction-heap entry"
                )
        # the spilled tier's analogue: every spilled FULL LEAF (no
        # indexed descendant at all — the host-budget eviction frontier)
        # has a live heap entry, else evict_spilled_lru could raise
        # with droppable entries left
        live_spilled = set(self._spilled_heap)
        for key, seq in self._spilled.items():
            node, off = self._by_key[key]
            if (off == len(node.keys) - 1 and not node.children
                    and (seq, key) not in live_spilled):
                raise AssertionError(
                    "spilled full-leaf entry has no live "
                    "host-eviction-heap entry"
                )
