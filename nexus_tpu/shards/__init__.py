"""Multi-cluster shard client layer.

Equivalent of nexus-core ``pkg/shards`` (API reconstructed from call sites,
SURVEY.md §2b): one :class:`Shard` per connected shard cluster, exposing
typed remote-write methods that stamp provenance labels and owner references.
"""

from nexus_tpu.shards.shard import Shard
from nexus_tpu.shards.loader import load_shards

__all__ = ["Shard", "load_shards"]
