"""Shard discovery: one shard per config file in a directory.

Equivalent of nexus-core ``shards.LoadShards(ctx, alias, shardConfigDir,
namespace, logger)`` (reference call site main.go:73; file-naming contract
README.md:15 — one ``<name>.kubeconfig`` per shard, mounted from a Secret).

Supported entries in ``shard_config_dir``:
  * ``<name>.localshard`` / ``<name>.localshard.yaml`` — an in-process local
    shard backed by a :class:`~nexus_tpu.cluster.store.ClusterStore`,
    resolved by name via :func:`get_local_store` (file contents are currently
    ignored; state is in-memory only). This is the test / single-host path,
    and the path BASELINE config #2 exercises.
  * ``<name>.kubeconfig`` — a real Kubernetes shard cluster, served by the
    stdlib REST client stack (cluster/kubeapi.py + cluster/kube.py — no
    dependency on the ``kubernetes`` package).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List

from nexus_tpu.cluster.store import ClusterStore
from nexus_tpu.shards.shard import Shard

logger = logging.getLogger("nexus_tpu.shards")

# Registry of named in-process stores so tests / local deployments can
# pre-register stores that load_shards resolves by name.
_local_stores: Dict[str, ClusterStore] = {}


def register_local_store(name: str, store: ClusterStore) -> None:
    _local_stores[name] = store


def get_local_store(name: str) -> ClusterStore:
    if name not in _local_stores:
        _local_stores[name] = ClusterStore(name)
    return _local_stores[name]


def load_shards(
    alias: str,
    shard_config_dir: str,
    namespace: str = "",
) -> List[Shard]:
    """Build one Shard per recognized config file in ``shard_config_dir``."""
    shards: List[Shard] = []
    if not os.path.isdir(shard_config_dir):
        raise FileNotFoundError(f"shard config dir {shard_config_dir!r} not found")
    for entry in sorted(os.listdir(shard_config_dir)):
        path = os.path.join(shard_config_dir, entry)
        if not os.path.isfile(path):
            continue
        if entry.endswith(".kubeconfig"):
            shard_name = entry[: -len(".kubeconfig")]
            shards.append(_load_kube_shard(alias, shard_name, path, namespace))
        elif entry.endswith(".localshard") or entry.endswith(".localshard.yaml"):
            shard_name = entry.split(".localshard")[0]
            shards.append(_load_local_shard(alias, shard_name, path))
        else:
            logger.debug("ignoring unrecognized shard config file %s", entry)
    logger.info("loaded %d shard(s) from %s", len(shards), shard_config_dir)
    return shards


def _load_local_shard(alias: str, shard_name: str, path: str) -> Shard:
    store = get_local_store(shard_name)
    return Shard(
        alias, shard_name, store, capabilities=_read_capabilities(path)
    )


def _read_capabilities(path: str) -> Dict[str, bool]:
    """Parse an optional ``capabilities:`` block from a shard config file.

    The file is YAML; only a flat ``capabilities: {name: bool}`` mapping is
    consulted. Anything unparseable degrades to no advertised capabilities.
    """
    try:
        import yaml  # noqa: PLC0415

        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        caps = doc.get("capabilities") or {}
        return {str(k): bool(v) for k, v in caps.items()}
    except Exception as e:
        logger.warning(
            "could not read capabilities from %s (%s); shard will advertise "
            "no capabilities", path, e,
        )
        return {}


def _load_kube_shard(
    alias: str, shard_name: str, kubeconfig_path: str, namespace: str
) -> Shard:
    from nexus_tpu.cluster.kube import KubeClusterStore  # noqa: PLC0415

    store = KubeClusterStore(shard_name, kubeconfig_path, namespace)
    # Optional capabilities sidecar: <name>.capabilities.yaml next to the
    # kubeconfig (a kubeconfig itself has no room for shard metadata).
    sidecar = os.path.join(
        os.path.dirname(kubeconfig_path), f"{shard_name}.capabilities.yaml"
    )
    caps = _read_capabilities(sidecar) if os.path.isfile(sidecar) else {}
    return Shard(alias, shard_name, store, capabilities=caps)
