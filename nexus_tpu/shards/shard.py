"""Shard — typed client bundle for one shard cluster.

Method surface mirrors the reconstructed nexus-core ``*shards.Shard``
(SURVEY.md §2b; reference call sites controller.go:519-614,727-807 and
constructor controller_test.go:507-515).

Write contract (reference test oracle controller_test.go:183-228):
  * every object written to a shard carries provenance labels
    ``science.sneaksanddata.com/controller-app`` and
    ``science.sneaksanddata.com/configuration-owner: <source alias>``;
  * secrets/configmaps written to a shard carry an ownerReference to the
    **shard-side** template (owner UIDs differ per cluster, so the owner is
    re-resolved on the shard — SURVEY.md §7 hard part (c)).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from nexus_tpu.api.template import NexusAlgorithmSpec, NexusAlgorithmTemplate
from nexus_tpu.api.types import (
    API_VERSION,
    CONTROLLER_APP_NAME,
    LABEL_CONFIGURATION_OWNER,
    LABEL_CONTROLLER_APP,
    ConfigMap,
    ObjectMeta,
    OwnerReference,
    Secret,
)
from nexus_tpu.api.workgroup import (
    NexusAlgorithmWorkgroup,
    NexusAlgorithmWorkgroupSpec,
)
from nexus_tpu.api.workload import Job, Service
from nexus_tpu.cluster.informer import InformerFactory, Lister
from nexus_tpu.cluster.store import ClusterStore, ConflictError, NotFoundError


class Shard:
    """Client bundle + watch caches for one shard cluster."""

    def __init__(
        self,
        source_cluster_alias: str,
        name: str,
        store: ClusterStore,
        informer_factory: Optional[InformerFactory] = None,
        capabilities: Optional[Dict[str, bool]] = None,
    ):
        self.source_cluster_alias = source_cluster_alias
        self.name = name
        self.store = store
        # Advertised capabilities of this shard cluster (e.g. accelerator
        # generation / topology of its TPU slice pools); consulted by
        # controller.placement when a template's workgroup constrains
        # placement (BASELINE config #5).
        self.capabilities: Dict[str, bool] = dict(capabilities or {})
        self.informers = informer_factory or InformerFactory(store)

        self.template_informer = self.informers.informer(NexusAlgorithmTemplate.KIND)
        self.workgroup_informer = self.informers.informer(NexusAlgorithmWorkgroup.KIND)
        self.secret_informer = self.informers.informer(Secret.KIND)
        self.config_map_informer = self.informers.informer(ConfigMap.KIND)
        # workload plane: the materialized Jobs/Services this controller
        # applies to the shard, plus the Job-status watch the controller
        # consumes to back-propagate workload phase into template status
        self.job_informer = self.informers.informer(Job.KIND)
        self.service_informer = self.informers.informer(Service.KIND)

        # Reference field surface: {Template,Workgroup,Secret,ConfigMap}Lister
        # + *Synced readiness funcs (controller.go:516,578,792,722,867).
        self.template_lister: Lister = self.template_informer.lister
        self.workgroup_lister: Lister = self.workgroup_informer.lister
        self.secret_lister: Lister = self.secret_informer.lister
        self.config_map_lister: Lister = self.config_map_informer.lister
        self.job_lister: Lister = self.job_informer.lister
        self.service_lister: Lister = self.service_informer.lister
        self.templates_synced: Callable[[], bool] = self.template_informer.has_synced
        self.workgroups_synced: Callable[[], bool] = self.workgroup_informer.has_synced
        self.secrets_synced: Callable[[], bool] = self.secret_informer.has_synced
        self.config_maps_synced: Callable[[], bool] = self.config_map_informer.has_synced
        self.jobs_synced: Callable[[], bool] = self.job_informer.has_synced
        self.services_synced: Callable[[], bool] = self.service_informer.has_synced

    # --------------------------------------------------------------- plumbing
    def provenance_labels(self) -> Dict[str, str]:
        return {
            LABEL_CONTROLLER_APP: CONTROLLER_APP_NAME,
            LABEL_CONFIGURATION_OWNER: self.source_cluster_alias,
        }

    def _resolve_shard_template(
        self, namespace: str, name: str
    ) -> Optional[NexusAlgorithmTemplate]:
        """Owner re-resolution: find the shard-side template so owner refs use
        the shard-local UID (reference behavior: controller_test.go:198-212)."""
        try:
            obj = self.store.get(NexusAlgorithmTemplate.KIND, namespace, name)
            return obj  # type: ignore[return-value]
        except NotFoundError:
            return None

    def _template_owner_ref(
        self, owner: NexusAlgorithmTemplate
    ) -> OwnerReference:
        shard_side = self._resolve_shard_template(
            owner.metadata.namespace, owner.metadata.name
        )
        uid = shard_side.metadata.uid if shard_side is not None else owner.metadata.uid
        return OwnerReference(
            api_version=API_VERSION,
            kind=NexusAlgorithmTemplate.KIND,
            name=owner.metadata.name,
            uid=uid,
        )

    # -------------------------------------------------------------- templates
    def create_template(
        self,
        name: str,
        namespace: str,
        spec: NexusAlgorithmSpec,
        field_manager: str = "",
    ) -> NexusAlgorithmTemplate:
        tmpl = NexusAlgorithmTemplate(
            metadata=ObjectMeta(
                name=name, namespace=namespace, labels=self.provenance_labels()
            ),
            spec=spec,
        )
        return self.store.create(tmpl, field_manager=field_manager)  # type: ignore[return-value]

    def update_template(
        self,
        template: NexusAlgorithmTemplate,
        spec: NexusAlgorithmSpec,
        field_manager: str = "",
    ) -> NexusAlgorithmTemplate:
        updated = template.deepcopy()
        updated.spec = spec
        updated.metadata.labels.update(self.provenance_labels())
        return self.store.update(updated, field_manager=field_manager)  # type: ignore[return-value]

    def delete_template(self, template: NexusAlgorithmTemplate) -> None:
        self.store.delete(
            NexusAlgorithmTemplate.KIND,
            template.metadata.namespace,
            template.metadata.name,
        )

    # ------------------------------------------------------------- workgroups
    def create_workgroup(
        self,
        name: str,
        namespace: str,
        spec: NexusAlgorithmWorkgroupSpec,
        field_manager: str = "",
    ) -> NexusAlgorithmWorkgroup:
        wg = NexusAlgorithmWorkgroup(
            metadata=ObjectMeta(
                name=name, namespace=namespace, labels=self.provenance_labels()
            ),
            spec=spec,
        )
        return self.store.create(wg, field_manager=field_manager)  # type: ignore[return-value]

    def update_workgroup(
        self,
        workgroup: NexusAlgorithmWorkgroup,
        spec: NexusAlgorithmWorkgroupSpec,
        field_manager: str = "",
    ) -> NexusAlgorithmWorkgroup:
        updated = workgroup.deepcopy()
        updated.spec = spec
        updated.metadata.labels.update(self.provenance_labels())
        return self.store.update(updated, field_manager=field_manager)  # type: ignore[return-value]

    # ----------------------------------------------------- secrets/configmaps
    def _create_dependent(self, owner, source, field_manager):
        """Shared secret/configmap create: fresh shard copy with provenance
        labels + shard-side owner reference."""
        shard_obj = source.deepcopy()
        shard_obj.metadata = ObjectMeta(
            name=source.metadata.name,
            namespace=source.metadata.namespace,
            labels=self.provenance_labels(),
            owner_references=[self._template_owner_ref(owner)],
        )
        return self.store.create(shard_obj, field_manager=field_manager)

    def _update_dependent(self, obj, data, owner, field_manager):
        """Shared secret/configmap update: ``data=None`` keeps existing data;
        when ``owner`` is given, append its owner reference (the adoption
        write — reference: controller.go:541,552). Owner dedup is by uid —
        the same identity the controller's ownership check uses — so a stale
        same-name/different-uid ref can't block adoption from converging."""
        updated = obj.deepcopy()
        if data is not None:
            updated.data = dict(data)
        updated.metadata.labels.update(self.provenance_labels())
        if owner is not None:
            ref = self._template_owner_ref(owner)
            if not any(r.uid == ref.uid for r in updated.metadata.owner_references):
                updated.metadata.owner_references.append(ref)
        return self.store.update(updated, field_manager=field_manager)

    def create_secret(
        self,
        owner: NexusAlgorithmTemplate,
        secret: Secret,
        field_manager: str = "",
    ) -> Secret:
        return self._create_dependent(owner, secret, field_manager)  # type: ignore[return-value]

    def update_secret(
        self,
        secret: Secret,
        data: Optional[Dict[str, str]] = None,
        owner: Optional[NexusAlgorithmTemplate] = None,
        field_manager: str = "",
    ) -> Secret:
        return self._update_dependent(secret, data, owner, field_manager)  # type: ignore[return-value]

    def create_config_map(
        self,
        owner: NexusAlgorithmTemplate,
        config_map: ConfigMap,
        field_manager: str = "",
    ) -> ConfigMap:
        return self._create_dependent(owner, config_map, field_manager)  # type: ignore[return-value]

    def update_config_map(
        self,
        config_map: ConfigMap,
        data: Optional[Dict[str, str]] = None,
        owner: Optional[NexusAlgorithmTemplate] = None,
        field_manager: str = "",
    ) -> ConfigMap:
        return self._update_dependent(config_map, data, owner, field_manager)  # type: ignore[return-value]

    # -------------------------------------------------------------- workloads
    _UNRESOLVED = object()  # sentinel: caller did not pre-resolve `existing`

    def apply_job(
        self,
        owner: NexusAlgorithmTemplate,
        manifest: Dict,
        field_manager: str = "",
        existing=_UNRESOLVED,
    ) -> Job:
        """Create-or-update a materialized Job on this shard.

        Job specs are immutable after creation in Kubernetes (other than
        suspend/parallelism); on pod-template drift the old Job is deleted
        and recreated — the same converge contract the template sync uses,
        adapted to batch/v1 semantics.

        ``existing`` lets a caller that already listed the shard's Jobs
        (the reconcile hot path batches one LIST per kind per shard) hand
        over the current object (or ``None``), skipping the per-job GET
        round trip."""
        job = Job.from_manifest(manifest)
        job.metadata.labels.update(self.provenance_labels())
        job.metadata.owner_references = [self._template_owner_ref(owner)]
        if existing is Shard._UNRESOLVED:
            try:
                existing = self.store.get(
                    Job.KIND, job.metadata.namespace, job.metadata.name
                )
            except NotFoundError:
                existing = None
        if existing is None:
            try:
                return self.store.create(job, field_manager=field_manager)  # type: ignore[return-value]
            except ConflictError:
                # name collision with an object the caller's label-filtered
                # LIST could not see (foreign/unlabeled same-name Job):
                # point-GET it and converge below instead of requeue-looping
                existing = self.store.get(
                    Job.KIND, job.metadata.namespace, job.metadata.name
                )
        from nexus_tpu.api.types import deep_equal

        if deep_equal(existing.spec, job.spec):
            return existing  # type: ignore[return-value]
        try:
            self.store.delete(
                Job.KIND, job.metadata.namespace, job.metadata.name
            )
        except NotFoundError:
            pass  # raced a concurrent delete; create below converges
        return self.store.create(job, field_manager=field_manager)  # type: ignore[return-value]

    def apply_service(
        self,
        owner: NexusAlgorithmTemplate,
        manifest: Dict,
        field_manager: str = "",
        existing=_UNRESOLVED,
    ) -> Service:
        svc = Service.from_manifest(manifest)
        svc.metadata.labels.update(self.provenance_labels())
        svc.metadata.owner_references = [self._template_owner_ref(owner)]
        if existing is Shard._UNRESOLVED:
            try:
                existing = self.store.get(
                    Service.KIND, svc.metadata.namespace, svc.metadata.name
                )
            except NotFoundError:
                existing = None
        if existing is None:
            try:
                return self.store.create(svc, field_manager=field_manager)  # type: ignore[return-value]
            except ConflictError:
                # same label-blind collision fallback as apply_job
                existing = self.store.get(
                    Service.KIND, svc.metadata.namespace, svc.metadata.name
                )
        from nexus_tpu.api.types import deep_equal

        if deep_equal(existing.spec, svc.spec):
            return existing  # type: ignore[return-value]
        updated = existing.deepcopy()
        updated.spec = dict(svc.spec)
        updated.metadata.labels.update(self.provenance_labels())
        return self.store.update(updated, field_manager=field_manager)  # type: ignore[return-value]

    # ------------------------------------------------------------------- misc
    def start(self) -> None:
        self.informers.start()

    def wait_for_cache_sync(self, timeout: float = 10.0) -> bool:
        return self.informers.wait_for_cache_sync(timeout)
