"""Platform utilities: config loading, telemetry, signals, build metadata.

Equivalent of the reference's nexus-core ``pkg/configurations``,
``pkg/telemetry``, ``pkg/signals`` and ``pkg/buildmeta`` packages
(reconstructed from call sites, see SURVEY.md §2b).
"""
