"""Metrics + structured logging.

Equivalent of nexus-core ``pkg/telemetry`` (reconstructed API:
``ConfigureLogger``, ``WithStatsd``, ``GetClient``, ``Gauge``,
``GaugeDuration`` — reference call sites main.go:43-44, controller.go:375,
389-390). Metrics are emitted in DogStatsD wire format over UDP when a statsd
address is configured, and always mirrored into an in-process registry that
tests and the benchmark harness can read.

Metric names match the reference constants (controller.go:50-56):
``reconcile_latency`` and ``workqueue_length``.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple, Union


class GaugeSample(NamedTuple):
    """One gauge series' last emission, with its freshness record — the
    TYPED read path consumers (the fleet router/autoscaler) use instead
    of parsing Prometheus text.

    ``seq`` is the registry's global emission counter at publish time
    (strictly monotone across ALL series — two reads of one series with
    equal ``seq`` mean NOTHING was published in between). ``stamp`` is
    the emitter's own publish clock when it provided one (the serving
    engine stamps its wave count via LiveGauges), 0.0 otherwise. A
    consumer that polls and sees seq/stamp frozen across its polls is
    looking at a WEDGED emitter — the staleness signal that keeps a
    frozen engine's last-known-good gauges from masquerading as live
    health (the fleet autoscaler's trust gate)."""

    value: float
    seq: int
    stamp: float

METRIC_RECONCILE_LATENCY = "reconcile_latency"
METRIC_WORKQUEUE_LENGTH = "workqueue_length"
# Burst-visibility gauges for the parallel reconcile hot path:
# ``workqueue_depth`` is the same series as ``workqueue_length`` under the
# name the coalescing queue exposes natively; ``coalesced_total`` counts
# duplicate keys absorbed by the queue's dedup during bursts; and
# ``shard_sync_latency`` times each per-shard fan-out task (tagged
# ``shard:<name>``) so slow shards are visible individually.
METRIC_WORKQUEUE_DEPTH = "workqueue_depth"
METRIC_COALESCED_TOTAL = "workqueue_coalesced_total"
METRIC_SHARD_SYNC_LATENCY = "shard_sync_latency"
# TPU-native workload-plane metrics (the BASELINE config #3 north-star
# latency): seconds from template creation to its materialized Jobs first
# observed Running, per template + rolling p50 across templates.
METRIC_TEMPLATE_TO_RUNNING = "template_to_running_seconds"
METRIC_TEMPLATE_TO_RUNNING_P50 = "template_to_running_p50"
# Failover subsystem gauges (nexus_tpu/ha/): per-shard health as seen by
# the failure detector, cumulative confirmed failovers, seconds from first
# missed deadline (or first API error) to confirmation, and training steps
# between the failed worker's last heartbeat and the checkpoint the
# re-placed job resumed from.
METRIC_SHARD_HEALTHY = "shard_healthy"
METRIC_FAILOVERS_TOTAL = "failovers_total"
METRIC_FAILOVER_DETECTION_SECONDS = "failover_detection_seconds"
METRIC_FAILOVER_STEPS_LOST = "failover_steps_lost"
# Serve-plane live gauges (nexus_tpu/obs/gauges.py publishes these at
# every wave boundary of a running engine — the PR 12 replacement for
# end-of-run-only visibility; docs/observability.md has the catalogue):
# wait-queue depth, occupied decode rows, free pool blocks, host-tier
# resident bytes, cumulative committed tokens / wave count, and the
# rolling nearest-rank ttft/queue-wait percentiles.
METRIC_SERVE_QUEUE_DEPTH = "serve_queue_depth"
METRIC_SERVE_RUNNING_ROWS = "serve_running_rows"
METRIC_SERVE_FREE_BLOCKS = "serve_free_pool_blocks"
METRIC_SERVE_HOST_BYTES = "serve_host_cache_bytes"
METRIC_SERVE_COMMITTED = "serve_committed_tokens"
METRIC_SERVE_WAVES = "serve_waves_total"
METRIC_SERVE_TTFT_P50 = "serve_ttft_p50_s"
METRIC_SERVE_TTFT_P95 = "serve_ttft_p95_s"
METRIC_SERVE_QUEUE_P50 = "serve_queue_p50_s"
METRIC_SERVE_QUEUE_P95 = "serve_queue_p95_s"
# Per-replica affinity economics, published by the fleet after each
# replica serve call (tagged ``engine:<id>``): radix-matched prompt
# tokens over prompt tokens served — the router's locality yield.
METRIC_SERVE_AFFINITY_HIT_RATE = "serve_affinity_hit_rate"
# Fleet-level federated gauges (nexus_tpu/obs/federation.py rolls the
# per-replica ``engine:<id>``-tagged serve gauges up at every fleet
# monitor poll; docs/observability.md): aggregate backlog/pool headroom/
# committed totals across live replicas, the live replica count, and
# MERGED-SAMPLE nearest-rank percentiles over every replica's finished
# requests (fed per stitched result — not an average of per-replica
# percentiles, which would not be a percentile of anything).
METRIC_FLEET_QUEUE_DEPTH = "fleet_queue_depth_total"
METRIC_FLEET_FREE_BLOCKS = "fleet_free_pool_blocks_total"
METRIC_FLEET_COMMITTED = "fleet_committed_tokens_total"
METRIC_FLEET_REPLICAS = "fleet_replicas_alive"
METRIC_FLEET_TTFT_P50 = "fleet_ttft_p50_s"
METRIC_FLEET_TTFT_P95 = "fleet_ttft_p95_s"
METRIC_FLEET_LATENCY_P50 = "fleet_latency_p50_s"
METRIC_FLEET_LATENCY_P95 = "fleet_latency_p95_s"
# goodput-under-SLO: fraction of finished requests served ok within the
# configured SLO (published only when the fleet was given an SLO)
METRIC_FLEET_SLO_ATTAINMENT = "fleet_slo_attainment"


def percentile_nearest_rank(xs: Sequence[float], q: float) -> float:
    """Nearest-rank percentile of a sequence — serve latency/ttft/queue
    populations are a handful of values per run (or a bounded rolling
    window), so the simple estimator is the honest one. THE one shared
    rank formula: the engine's end-of-run rollups, the entrypoint's
    request-latency rollups, the outage bench, and the obs layer's
    rolling gauges all call this, so the estimator can't diverge
    between them (moved here from runtime/serving.py in PR 12).

    An EMPTY population returns NaN, never 0.0: an all-shed round must
    not report a perfect p95 (callers omit the metric instead)."""
    if not xs:
        return float("nan")
    s = sorted(xs)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


def configure_logger(
    level: str = "INFO",
    extra_tags: Optional[Dict[str, str]] = None,
    datadog_api_key: str = "",
    datadog_site: str = "datadoghq.com",
    datadog_endpoint: str = "",
    service: str = "nexus-tpu",
) -> logging.Logger:
    """Configure root logging (the ConfigureLogger equivalent).

    With a Datadog API key (or explicit endpoint), a
    :class:`DatadogLogHandler` ships every record to the Datadog logs
    intake as well — the slog-datadog sink equivalent (reference:
    main.go:43, go.mod:46)."""
    tag_str = " ".join(f"{k}={v}" for k, v in (extra_tags or {}).items())
    fmt = "%(asctime)s %(levelname)s %(name)s"
    if tag_str:
        fmt += f" [{tag_str}]"
    fmt += " %(message)s"
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO), format=fmt, force=True
    )
    root = logging.getLogger()
    if datadog_api_key or datadog_endpoint:
        handler = DatadogLogHandler(
            api_key=datadog_api_key,
            site=datadog_site,
            endpoint=datadog_endpoint,
            service=service,
            tags=dict(extra_tags or {}),
        )
        handler.setLevel(getattr(logging, level.upper(), logging.INFO))
        root.addHandler(handler)
    return logging.getLogger("nexus_tpu")


class DatadogLogHandler(logging.Handler):
    """Ship log records to the Datadog logs intake (HTTP, batched).

    Stdlib-only (http.client): records are buffered and a background
    thread POSTs JSON batches to ``/api/v2/logs`` with the ``DD-API-KEY``
    header. ``endpoint`` overrides the intake URL (tests point it at a
    local server); delivery is best-effort — intake failures are dropped
    after one retry, never raised into the logging call site."""

    def __init__(
        self,
        api_key: str = "",
        site: str = "datadoghq.com",
        endpoint: str = "",
        service: str = "nexus-tpu",
        tags: Optional[Dict[str, str]] = None,
        flush_interval: float = 2.0,
        max_batch: int = 100,
    ):
        import urllib.parse

        super().__init__()
        self.api_key = api_key
        self.endpoint = endpoint or f"https://http-intake.logs.{site}/api/v2/logs"
        self._parsed = urllib.parse.urlparse(self.endpoint)
        if not self._parsed.hostname:
            raise ValueError(f"invalid Datadog log endpoint {self.endpoint!r}")
        self.service = service
        self.ddtags = ",".join(f"{k}:{v}" for k, v in (tags or {}).items())
        self.flush_interval = flush_interval
        self.max_batch = max_batch
        self._buf: List[dict] = []
        self._buf_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._pump, daemon=True, name="nexus-dd-logs"
        )
        self._thread.start()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            entry = {
                "message": self.format(record),
                "status": record.levelname.lower(),
                "service": self.service,
                "ddsource": "nexus-tpu",
                "ddtags": self.ddtags,
                "logger": {"name": record.name},
                "timestamp": int(record.created * 1000),
            }
        except Exception:  # noqa: BLE001 — formatting must never raise
            return
        with self._buf_lock:
            self._buf.append(entry)
            if len(self._buf) > 10 * self.max_batch:
                # intake unreachable: bound memory, drop oldest
                self._buf = self._buf[-5 * self.max_batch :]

    def _drain(self) -> List[dict]:
        with self._buf_lock:
            batch, self._buf = self._buf[: self.max_batch], self._buf[self.max_batch :]
            return batch

    def _pump(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush_once()
        # final best-effort flush on close: drain everything, not one batch
        while self.flush_once():
            pass

    def flush_once(self) -> bool:
        """Send one batch. Returns True if a batch was sent successfully;
        on intake failure the batch is put back at the head of the buffer
        (emit()'s drop-oldest bound then caps memory during long outages)."""
        import http.client as http_client
        import json as _json
        import ssl as _ssl

        batch = self._drain()
        if not batch:
            return False
        parsed = self._parsed
        try:
            if parsed.scheme == "https":
                conn = http_client.HTTPSConnection(
                    parsed.hostname, parsed.port or 443, timeout=5,
                    context=_ssl.create_default_context(),
                )
            else:
                conn = http_client.HTTPConnection(
                    parsed.hostname, parsed.port or 80, timeout=5
                )
            headers = {"Content-Type": "application/json"}
            if self.api_key:
                headers["DD-API-KEY"] = self.api_key
            conn.request("POST", parsed.path or "/", _json.dumps(batch), headers)
            resp = conn.getresponse()
            resp.read()
            conn.close()
            if 400 <= resp.status < 500 and resp.status != 429:
                # client error (bad key, malformed entry): retrying the same
                # batch forever would head-of-line-block all newer logs —
                # drop it
                return True
            if resp.status >= 300:  # 429 / 5xx: transient, requeue
                raise OSError(f"intake rejected batch: {resp.status}")
            return True
        except Exception:  # noqa: BLE001 — telemetry must not break the app
            with self._buf_lock:
                self._buf = batch + self._buf
                if len(self._buf) > 10 * self.max_batch:
                    self._buf = self._buf[-5 * self.max_batch :]
            return False

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=self.flush_interval + 6)
        super().close()


class StatsdClient:
    """Minimal DogStatsD client: gauges with tags, fire-and-forget UDP.

    With no address configured it is a pure in-memory registry (the test /
    no-Datadog path).

    CONCURRENCY (PR 12 hardening): the registry is written from the
    serve engine's wave loop, controller threads, and the failover
    supervisor at once, and read by the exposition renderer while they
    emit. Every mutable structure is guarded by ``_lock``, the history
    is a bounded deque (append is O(1) — the old list-slice trim copied
    10k entries per emission once full), and readers that need a
    CONSISTENT view use :meth:`snapshot` (one lock hold, deep-enough
    copies) instead of iterating the live dicts.
    ``tools/race_smoke_telemetry.py`` hammers exactly this contract."""

    #: history ring capacity (bounded — telemetry must never grow RSS)
    HISTORY_CAP = 10000

    def __init__(
        self, app_name: str = "nexus-tpu", address: Optional[str] = None
    ):
        self.app_name = app_name
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        # UDP (host, port) tuple or a unix-socket path string
        self._addr: Optional[Union[Tuple[str, int], str]] = None
        self.gauges: Dict[str, float] = {}  # guarded-by: _lock
        # last value per (name, tags) SERIES — the exposition surface:
        # the plain ``gauges`` dict collapses differently-tagged
        # emissions of one metric into a single cell, which is fine for
        # tests but loses the per-series values Prometheus text needs
        self.tagged: Dict[Tuple[str, Tuple[str, ...]], float] = {}  # guarded-by: _lock
        # per-series freshness record behind the typed read path
        # (get_tagged / tagged_series): same keys as ``tagged``, values
        # carry (value, global emission seq, emitter stamp)
        self._tagged_meta: Dict[Tuple[str, Tuple[str, ...]], GaugeSample] = {}  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock — global emission counter
        self.history: deque = deque(maxlen=self.HISTORY_CAP)  # guarded-by: _lock
        address = address or os.environ.get("NEXUS__STATSD_ADDRESS", "")
        if address.startswith("unix://"):
            # DogStatsD unix socket (the Datadog agent socket the reference
            # chart mounts, .helm/templates/deployment.yaml:109-113)
            self._addr = address[len("unix://"):]
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        elif address:
            host, _, port = address.partition(":")
            self._addr = (host, int(port or 8125))
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def gauge(
        self, name: str, value: float, tags: Optional[List[str]] = None,
        rate: float = 1.0, stamp: Optional[float] = None,
    ) -> None:
        """``stamp`` is an OPTIONAL emitter-side publish clock (e.g. the
        serving engine's wave count) recorded per series for the typed
        read path's staleness signal; it never reaches the wire."""
        full = f"{self.app_name}.{name}"
        tag_tuple = tuple(tags or [])
        with self._lock:
            self._seq += 1
            self.gauges[full] = value
            self.tagged[(full, tag_tuple)] = value
            self._tagged_meta[(full, tag_tuple)] = GaugeSample(
                float(value), self._seq,
                float(stamp) if stamp is not None else 0.0,
            )
            self.history.append((full, value, tag_tuple))
        if self._sock and self._addr:
            tag_str = f"|#{','.join(tags)}" if tags else ""
            payload = f"{full}:{value}|g|@{rate}{tag_str}".encode()
            try:
                self._sock.sendto(payload, self._addr)
            except OSError:
                pass

    def gauge_duration(
        self,
        name: str,
        since: float,
        tags: Optional[List[str]] = None,
        rate: float = 1.0,
    ) -> None:
        """Gauge of elapsed seconds since a ``time.monotonic()`` stamp
        (GaugeDuration equivalent, reference controller.go:389)."""
        self.gauge(name, time.monotonic() - since, tags=tags, rate=rate)

    def get_tagged(
        self, name: str, tags: Optional[Sequence[str]] = None
    ) -> Optional[GaugeSample]:
        """Typed last-emission read of ONE series: the gauge ``name``
        (bare, without the app prefix) as published with exactly
        ``tags`` — None when that series never emitted. The fleet
        router reads per-engine load this way
        (``get_tagged("serve_queue_depth", ["engine:r0"])``) instead of
        parsing exposition text; compare two polls' ``seq`` to detect a
        frozen emitter."""
        full = f"{self.app_name}.{name}"
        with self._lock:
            return self._tagged_meta.get((full, tuple(tags or [])))

    def tagged_series(self, tag: str) -> Dict[str, GaugeSample]:
        """Every series carrying ``tag`` (exact tag-member match), as
        ``{bare metric name: GaugeSample}`` — one engine replica's whole
        live-gauge snapshot in one lock hold
        (``tagged_series("engine:r0")``). Series published under several
        tags are keyed by bare name; when one metric name was emitted
        with DIFFERENT tag sets that both contain ``tag``, the
        highest-seq (latest) emission wins."""
        prefix = f"{self.app_name}."
        out: Dict[str, GaugeSample] = {}
        with self._lock:
            for (full, tag_tuple), sample in self._tagged_meta.items():
                if tag not in tag_tuple:
                    continue
                bare = full[len(prefix):] if full.startswith(prefix) else full
                prior = out.get(bare)
                if prior is None or sample.seq > prior.seq:
                    out[bare] = sample
        return out

    def snapshot(self) -> Dict[str, object]:
        """One CONSISTENT copy of the registry (single lock hold): the
        exposition renderer's read path. ``gauges`` is the untagged
        last-value map, ``series`` the per-(name, tags) map — returned
        as plain copies so the caller can iterate while emitters keep
        writing."""
        with self._lock:
            return {
                "gauges": dict(self.gauges),
                "series": dict(self.tagged),
                "history_len": len(self.history),
            }


_default_client: Optional[StatsdClient] = None
_client_lock = threading.Lock()


def with_statsd(app_name: str, address: Optional[str] = None) -> StatsdClient:
    """Install the process-default client (WithStatsd equivalent)."""
    global _default_client
    with _client_lock:
        _default_client = StatsdClient(app_name, address)
        return _default_client


def get_client() -> StatsdClient:
    """Fetch the process-default client (GetClient equivalent)."""
    global _default_client
    with _client_lock:
        if _default_client is None:
            _default_client = StatsdClient()
        return _default_client
