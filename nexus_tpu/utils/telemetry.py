"""Metrics + structured logging.

Equivalent of nexus-core ``pkg/telemetry`` (reconstructed API:
``ConfigureLogger``, ``WithStatsd``, ``GetClient``, ``Gauge``,
``GaugeDuration`` — reference call sites main.go:43-44, controller.go:375,
389-390). Metrics are emitted in DogStatsD wire format over UDP when a statsd
address is configured, and always mirrored into an in-process registry that
tests and the benchmark harness can read.

Metric names match the reference constants (controller.go:50-56):
``reconcile_latency`` and ``workqueue_length``.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

METRIC_RECONCILE_LATENCY = "reconcile_latency"
METRIC_WORKQUEUE_LENGTH = "workqueue_length"
# TPU-native workload-plane metrics (the BASELINE config #3 north-star
# latency): seconds from template creation to its materialized Jobs first
# observed Running, per template + rolling p50 across templates.
METRIC_TEMPLATE_TO_RUNNING = "template_to_running_seconds"
METRIC_TEMPLATE_TO_RUNNING_P50 = "template_to_running_p50"


def configure_logger(
    level: str = "INFO", extra_tags: Optional[Dict[str, str]] = None
) -> logging.Logger:
    """Configure root logging (the ConfigureLogger equivalent)."""
    tag_str = " ".join(f"{k}={v}" for k, v in (extra_tags or {}).items())
    fmt = "%(asctime)s %(levelname)s %(name)s"
    if tag_str:
        fmt += f" [{tag_str}]"
    fmt += " %(message)s"
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO), format=fmt, force=True
    )
    return logging.getLogger("nexus_tpu")


class StatsdClient:
    """Minimal DogStatsD client: gauges with tags, fire-and-forget UDP.

    With no address configured it is a pure in-memory registry (the test /
    no-Datadog path)."""

    def __init__(
        self, app_name: str = "nexus-tpu", address: Optional[str] = None
    ):
        self.app_name = app_name
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._addr: Optional[Tuple[str, int]] = None
        self.gauges: Dict[str, float] = {}
        self.history: List[Tuple[str, float, Tuple[str, ...]]] = []
        address = address or os.environ.get("NEXUS__STATSD_ADDRESS", "")
        if address:
            host, _, port = address.partition(":")
            self._addr = (host, int(port or 8125))
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def gauge(
        self, name: str, value: float, tags: Optional[List[str]] = None, rate: float = 1.0
    ) -> None:
        full = f"{self.app_name}.{name}"
        with self._lock:
            self.gauges[full] = value
            self.history.append((full, value, tuple(tags or [])))
            if len(self.history) > 10000:
                self.history = self.history[-10000:]
        if self._sock and self._addr:
            tag_str = f"|#{','.join(tags)}" if tags else ""
            payload = f"{full}:{value}|g|@{rate}{tag_str}".encode()
            try:
                self._sock.sendto(payload, self._addr)
            except OSError:
                pass

    def gauge_duration(
        self,
        name: str,
        since: float,
        tags: Optional[List[str]] = None,
        rate: float = 1.0,
    ) -> None:
        """Gauge of elapsed seconds since a ``time.monotonic()`` stamp
        (GaugeDuration equivalent, reference controller.go:389)."""
        self.gauge(name, time.monotonic() - since, tags=tags, rate=rate)


_default_client: Optional[StatsdClient] = None
_client_lock = threading.Lock()


def with_statsd(app_name: str, address: Optional[str] = None) -> StatsdClient:
    """Install the process-default client (WithStatsd equivalent)."""
    global _default_client
    with _client_lock:
        _default_client = StatsdClient(app_name, address)
        return _default_client


def get_client() -> StatsdClient:
    """Fetch the process-default client (GetClient equivalent)."""
    global _default_client
    with _client_lock:
        if _default_client is None:
            _default_client = StatsdClient()
        return _default_client
