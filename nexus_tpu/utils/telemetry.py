"""Metrics + structured logging.

Equivalent of nexus-core ``pkg/telemetry`` (reconstructed API:
``ConfigureLogger``, ``WithStatsd``, ``GetClient``, ``Gauge``,
``GaugeDuration`` — reference call sites main.go:43-44, controller.go:375,
389-390). Metrics are emitted in DogStatsD wire format over UDP when a statsd
address is configured, and always mirrored into an in-process registry that
tests and the benchmark harness can read.

Metric names match the reference constants (controller.go:50-56):
``reconcile_latency`` and ``workqueue_length``.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple, Union

METRIC_RECONCILE_LATENCY = "reconcile_latency"
METRIC_WORKQUEUE_LENGTH = "workqueue_length"
# Burst-visibility gauges for the parallel reconcile hot path:
# ``workqueue_depth`` is the same series as ``workqueue_length`` under the
# name the coalescing queue exposes natively; ``coalesced_total`` counts
# duplicate keys absorbed by the queue's dedup during bursts; and
# ``shard_sync_latency`` times each per-shard fan-out task (tagged
# ``shard:<name>``) so slow shards are visible individually.
METRIC_WORKQUEUE_DEPTH = "workqueue_depth"
METRIC_COALESCED_TOTAL = "workqueue_coalesced_total"
METRIC_SHARD_SYNC_LATENCY = "shard_sync_latency"
# TPU-native workload-plane metrics (the BASELINE config #3 north-star
# latency): seconds from template creation to its materialized Jobs first
# observed Running, per template + rolling p50 across templates.
METRIC_TEMPLATE_TO_RUNNING = "template_to_running_seconds"
METRIC_TEMPLATE_TO_RUNNING_P50 = "template_to_running_p50"
# Failover subsystem gauges (nexus_tpu/ha/): per-shard health as seen by
# the failure detector, cumulative confirmed failovers, seconds from first
# missed deadline (or first API error) to confirmation, and training steps
# between the failed worker's last heartbeat and the checkpoint the
# re-placed job resumed from.
METRIC_SHARD_HEALTHY = "shard_healthy"
METRIC_FAILOVERS_TOTAL = "failovers_total"
METRIC_FAILOVER_DETECTION_SECONDS = "failover_detection_seconds"
METRIC_FAILOVER_STEPS_LOST = "failover_steps_lost"


def configure_logger(
    level: str = "INFO",
    extra_tags: Optional[Dict[str, str]] = None,
    datadog_api_key: str = "",
    datadog_site: str = "datadoghq.com",
    datadog_endpoint: str = "",
    service: str = "nexus-tpu",
) -> logging.Logger:
    """Configure root logging (the ConfigureLogger equivalent).

    With a Datadog API key (or explicit endpoint), a
    :class:`DatadogLogHandler` ships every record to the Datadog logs
    intake as well — the slog-datadog sink equivalent (reference:
    main.go:43, go.mod:46)."""
    tag_str = " ".join(f"{k}={v}" for k, v in (extra_tags or {}).items())
    fmt = "%(asctime)s %(levelname)s %(name)s"
    if tag_str:
        fmt += f" [{tag_str}]"
    fmt += " %(message)s"
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO), format=fmt, force=True
    )
    root = logging.getLogger()
    if datadog_api_key or datadog_endpoint:
        handler = DatadogLogHandler(
            api_key=datadog_api_key,
            site=datadog_site,
            endpoint=datadog_endpoint,
            service=service,
            tags=dict(extra_tags or {}),
        )
        handler.setLevel(getattr(logging, level.upper(), logging.INFO))
        root.addHandler(handler)
    return logging.getLogger("nexus_tpu")


class DatadogLogHandler(logging.Handler):
    """Ship log records to the Datadog logs intake (HTTP, batched).

    Stdlib-only (http.client): records are buffered and a background
    thread POSTs JSON batches to ``/api/v2/logs`` with the ``DD-API-KEY``
    header. ``endpoint`` overrides the intake URL (tests point it at a
    local server); delivery is best-effort — intake failures are dropped
    after one retry, never raised into the logging call site."""

    def __init__(
        self,
        api_key: str = "",
        site: str = "datadoghq.com",
        endpoint: str = "",
        service: str = "nexus-tpu",
        tags: Optional[Dict[str, str]] = None,
        flush_interval: float = 2.0,
        max_batch: int = 100,
    ):
        import urllib.parse

        super().__init__()
        self.api_key = api_key
        self.endpoint = endpoint or f"https://http-intake.logs.{site}/api/v2/logs"
        self._parsed = urllib.parse.urlparse(self.endpoint)
        if not self._parsed.hostname:
            raise ValueError(f"invalid Datadog log endpoint {self.endpoint!r}")
        self.service = service
        self.ddtags = ",".join(f"{k}:{v}" for k, v in (tags or {}).items())
        self.flush_interval = flush_interval
        self.max_batch = max_batch
        self._buf: List[dict] = []
        self._buf_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._pump, daemon=True, name="nexus-dd-logs"
        )
        self._thread.start()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            entry = {
                "message": self.format(record),
                "status": record.levelname.lower(),
                "service": self.service,
                "ddsource": "nexus-tpu",
                "ddtags": self.ddtags,
                "logger": {"name": record.name},
                "timestamp": int(record.created * 1000),
            }
        except Exception:  # noqa: BLE001 — formatting must never raise
            return
        with self._buf_lock:
            self._buf.append(entry)
            if len(self._buf) > 10 * self.max_batch:
                # intake unreachable: bound memory, drop oldest
                self._buf = self._buf[-5 * self.max_batch :]

    def _drain(self) -> List[dict]:
        with self._buf_lock:
            batch, self._buf = self._buf[: self.max_batch], self._buf[self.max_batch :]
            return batch

    def _pump(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush_once()
        # final best-effort flush on close: drain everything, not one batch
        while self.flush_once():
            pass

    def flush_once(self) -> bool:
        """Send one batch. Returns True if a batch was sent successfully;
        on intake failure the batch is put back at the head of the buffer
        (emit()'s drop-oldest bound then caps memory during long outages)."""
        import http.client as http_client
        import json as _json
        import ssl as _ssl

        batch = self._drain()
        if not batch:
            return False
        parsed = self._parsed
        try:
            if parsed.scheme == "https":
                conn = http_client.HTTPSConnection(
                    parsed.hostname, parsed.port or 443, timeout=5,
                    context=_ssl.create_default_context(),
                )
            else:
                conn = http_client.HTTPConnection(
                    parsed.hostname, parsed.port or 80, timeout=5
                )
            headers = {"Content-Type": "application/json"}
            if self.api_key:
                headers["DD-API-KEY"] = self.api_key
            conn.request("POST", parsed.path or "/", _json.dumps(batch), headers)
            resp = conn.getresponse()
            resp.read()
            conn.close()
            if 400 <= resp.status < 500 and resp.status != 429:
                # client error (bad key, malformed entry): retrying the same
                # batch forever would head-of-line-block all newer logs —
                # drop it
                return True
            if resp.status >= 300:  # 429 / 5xx: transient, requeue
                raise OSError(f"intake rejected batch: {resp.status}")
            return True
        except Exception:  # noqa: BLE001 — telemetry must not break the app
            with self._buf_lock:
                self._buf = batch + self._buf
                if len(self._buf) > 10 * self.max_batch:
                    self._buf = self._buf[-5 * self.max_batch :]
            return False

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=self.flush_interval + 6)
        super().close()


class StatsdClient:
    """Minimal DogStatsD client: gauges with tags, fire-and-forget UDP.

    With no address configured it is a pure in-memory registry (the test /
    no-Datadog path)."""

    def __init__(
        self, app_name: str = "nexus-tpu", address: Optional[str] = None
    ):
        self.app_name = app_name
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        # UDP (host, port) tuple or a unix-socket path string
        self._addr: Optional[Union[Tuple[str, int], str]] = None
        self.gauges: Dict[str, float] = {}
        self.history: List[Tuple[str, float, Tuple[str, ...]]] = []
        address = address or os.environ.get("NEXUS__STATSD_ADDRESS", "")
        if address.startswith("unix://"):
            # DogStatsD unix socket (the Datadog agent socket the reference
            # chart mounts, .helm/templates/deployment.yaml:109-113)
            self._addr = address[len("unix://"):]
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        elif address:
            host, _, port = address.partition(":")
            self._addr = (host, int(port or 8125))
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def gauge(
        self, name: str, value: float, tags: Optional[List[str]] = None, rate: float = 1.0
    ) -> None:
        full = f"{self.app_name}.{name}"
        with self._lock:
            self.gauges[full] = value
            self.history.append((full, value, tuple(tags or [])))
            if len(self.history) > 10000:
                self.history = self.history[-10000:]
        if self._sock and self._addr:
            tag_str = f"|#{','.join(tags)}" if tags else ""
            payload = f"{full}:{value}|g|@{rate}{tag_str}".encode()
            try:
                self._sock.sendto(payload, self._addr)
            except OSError:
                pass

    def gauge_duration(
        self,
        name: str,
        since: float,
        tags: Optional[List[str]] = None,
        rate: float = 1.0,
    ) -> None:
        """Gauge of elapsed seconds since a ``time.monotonic()`` stamp
        (GaugeDuration equivalent, reference controller.go:389)."""
        self.gauge(name, time.monotonic() - since, tags=tags, rate=rate)


_default_client: Optional[StatsdClient] = None
_client_lock = threading.Lock()


def with_statsd(app_name: str, address: Optional[str] = None) -> StatsdClient:
    """Install the process-default client (WithStatsd equivalent)."""
    global _default_client
    with _client_lock:
        _default_client = StatsdClient(app_name, address)
        return _default_client


def get_client() -> StatsdClient:
    """Fetch the process-default client (GetClient equivalent)."""
    global _default_client
    with _client_lock:
        if _default_client is None:
            _default_client = StatsdClient()
        return _default_client
