"""Build stamping.

Equivalent of nexus-core ``pkg/buildmeta`` whose ``AppVersion`` /
``BuildNumber`` vars are injected via ``-ldflags -X`` in the reference image
build (reference: .container/Dockerfile:14). Here the values come from
environment variables set at image build time, with dev defaults.
"""

import os

APP_VERSION: str = os.environ.get("NEXUS_TPU_APP_VERSION", "0.1.0-dev")
BUILD_NUMBER: str = os.environ.get("NEXUS_TPU_BUILD_NUMBER", "0")
