"""Tokenizer loading for LM inference: HF ``tokenizer.json`` byte-level BPE.

Completes the "Llama-3-8B inference" story (BASELINE config #3): an infer
template can carry a prompt STRING; the runtime tokenizes it with the
checkpoint's own tokenizer and detokenizes the decoded ids.

Two engines behind one surface:
  * the ``tokenizers`` Rust library when importable (exact HF behavior —
    it is part of this image's transformers install);
  * a pure-Python byte-level BPE fallback (`PureBpeTokenizer`) implementing
    the same tokenizer.json subset Llama-3 uses — byte-to-unicode mapping
    (the GPT-2 table), regex pre-tokenization, greedy lowest-rank merges,
    added/special tokens — so tokenization works even without the package.
    Cross-checked against the Rust engine in tests/test_weights.py.

The reference has no tokenizer (it is a config-sync controller, SURVEY.md);
this is workload-plane capability the north star adds.
"""

from __future__ import annotations

import json
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple


@lru_cache(maxsize=1)
def _byte_to_unicode() -> Dict[int, str]:
    """GPT-2's reversible byte↔unicode table: printable bytes map to
    themselves, the rest to private-ish codepoints ≥256."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


# Llama-3's pre-tokenization pattern (tiktoken cl100k lineage; also what
# its tokenizer.json carries in pre_tokenizer.pattern.Regex)
_LLAMA3_PATTERN = (
    r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}|"
    r" ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+"
)


class PureBpeTokenizer:
    """Self-contained byte-level BPE over a parsed tokenizer.json."""

    def __init__(
        self,
        vocab: Dict[str, int],
        merges: Sequence[Tuple[str, str]],
        added_tokens: Optional[Dict[str, int]] = None,
        pattern: str = _LLAMA3_PATTERN,
    ):
        self.vocab = dict(vocab)
        self.id_to_token = {i: t for t, i in self.vocab.items()}
        self.ranks = {tuple(m): r for r, m in enumerate(merges)}
        self.added = dict(added_tokens or {})
        self.id_to_token.update({i: t for t, i in self.added.items()})
        import regex

        self._pat = regex.compile(pattern)
        self._b2u = _byte_to_unicode()
        self._u2b = {u: b for b, u in self._b2u.items()}

    @classmethod
    def from_file(cls, path: str) -> "PureBpeTokenizer":
        with open(path) as f:
            doc = json.load(f)
        model = doc.get("model") or {}
        if model.get("type") != "BPE":
            raise ValueError(
                f"tokenizer.json model.type {model.get('type')!r} "
                "unsupported (BPE only)"
            )
        merges_raw = model.get("merges") or []
        merges = [
            tuple(m.split(" ", 1)) if isinstance(m, str) else tuple(m)
            for m in merges_raw
        ]
        added = {
            t["content"]: t["id"] for t in doc.get("added_tokens") or []
        }
        pattern = _LLAMA3_PATTERN
        pre = doc.get("pre_tokenizer") or {}
        # accept both a bare Split pre-tokenizer and a Sequence of them
        candidates = pre.get("pretokenizers") or [pre]
        for p in candidates:
            pat = ((p or {}).get("pattern") or {}).get("Regex")
            if pat:
                pattern = pat
                break
        return cls(model.get("vocab") or {}, merges, added, pattern)

    # ------------------------------------------------------------------ BPE
    def _bpe(self, piece: str) -> List[str]:
        parts = list(piece)
        if len(parts) < 2:
            return parts
        while True:
            best = None
            best_rank = None
            for i in range(len(parts) - 1):
                r = self.ranks.get((parts[i], parts[i + 1]))
                if r is not None and (best_rank is None or r < best_rank):
                    best, best_rank = i, r
            if best is None:
                return parts
            parts = (
                parts[:best]
                + [parts[best] + parts[best + 1]]
                + parts[best + 2:]
            )
            if len(parts) < 2:
                return parts

    def encode(self, text: str) -> List[int]:
        """Text → token ids. Added/special tokens match as whole pieces
        first (longest-first), the rest goes through byte-level BPE."""
        if not text:
            return []
        if self.added:
            for tok in sorted(self.added, key=len, reverse=True):
                if tok in text:
                    left, _, right = text.partition(tok)
                    return (
                        self.encode(left)
                        + [self.added[tok]]
                        + self.encode(right)
                    )
        ids: List[int] = []
        for piece in self._pat.findall(text):
            mapped = "".join(self._b2u[b] for b in piece.encode("utf-8"))
            for unit in self._bpe(mapped):
                try:
                    ids.append(self.vocab[unit])
                except KeyError:
                    # merges/vocab disagree (malformed file): emit per-char
                    ids.extend(
                        self.vocab[c] for c in unit if c in self.vocab
                    )
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        out = bytearray()
        for i in ids:
            tok = self.id_to_token.get(int(i))
            if tok is None:
                continue
            if tok in self.added:
                out += tok.encode("utf-8")
                continue
            for ch in tok:
                b = self._u2b.get(ch)
                if b is not None:
                    out.append(b)
                else:  # not a byte-level char (shouldn't happen for BPE)
                    out += ch.encode("utf-8")
        return out.decode("utf-8", errors="replace")


class _RustTokenizer:
    """Thin adapter over the HF ``tokenizers`` engine."""

    def __init__(self, path: str):
        from tokenizers import Tokenizer

        self._tk = Tokenizer.from_file(path)

    def encode(self, text: str) -> List[int]:
        return self._tk.encode(text, add_special_tokens=False).ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tk.decode(list(map(int, ids)), skip_special_tokens=False)


def load_tokenizer(path: str, engine: str = "auto"):
    """Load a tokenizer.json. ``engine``: 'auto' (Rust when importable,
    else pure), 'rust', or 'pure'."""
    if engine not in ("auto", "rust", "pure"):
        raise ValueError(f"unknown tokenizer engine {engine!r}")
    if engine in ("auto", "rust"):
        try:
            return _RustTokenizer(path)
        except ImportError:
            if engine == "rust":
                raise
    return PureBpeTokenizer.from_file(path)
