"""Graceful shutdown: SIGTERM/SIGINT → cancellation token.

Equivalent of nexus-core ``signals.SetupSignalHandler() context.Context``
(reference call site main.go:40). Python has no context.Context; the
equivalent is a :class:`CancelToken` whose event is set on the first signal —
a second signal force-exits, matching the upstream sample-controller contract.
"""

from __future__ import annotations

import os
import signal
import threading


class CancelToken:
    def __init__(self):
        self._event = threading.Event()
        # hard=True models an ungraceful kill (chaos "kill worker", a node
        # vanishing mid-step): the run stops at the next step boundary but
        # the graceful-shutdown courtesies — final checkpoint, heartbeat
        # completion marker — are SKIPPED, so failover recovery starts from
        # the last interval checkpoint, exactly like a real preemption
        # without a SIGTERM grace window.
        self.hard = False

    def cancel(self, hard: bool = False) -> None:
        if hard:
            self.hard = True
        self._event.set()

    def cancelled(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        # Untimed Event.wait() can delay signal delivery by seconds on the
        # main thread; poll in short slices so SIGINT/SIGTERM act promptly.
        if timeout is not None:
            return self._event.wait(timeout)
        while not self._event.wait(0.2):
            pass
        return True


def setup_signal_handler() -> CancelToken:
    token = CancelToken()

    def _handler(signum, frame):
        if token.cancelled():
            os._exit(1)  # second signal: exit directly
        token.cancel()

    signal.signal(signal.SIGINT, _handler)
    signal.signal(signal.SIGTERM, _handler)
    return token
