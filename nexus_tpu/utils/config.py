"""Config system: yaml file + ``NEXUS__*`` env overrides.

Equivalent of nexus-core ``configurations.LoadConfig[T]`` (reference call site
main.go:41): binds a typed config struct from a yaml file, overridable by
``NEXUS__<UPPER_SNAKE>`` environment variables (reference:
.helm/templates/deployment.yaml:50-69), with ``APPLICATION_ENVIRONMENT``
selecting an overlay file (``appconfig.<env>.yaml`` next to the base file,
reference: build.yaml:79).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, fields
from typing import Any, Dict, Optional, Type, TypeVar

import yaml

T = TypeVar("T")

ENV_PREFIX = "NEXUS__"


@dataclass
class AppConfig:
    """Application config — field set matches the reference
    ``models.AppConfig`` (reference: pkg/models/app_config.go:21-32)."""

    alias: str = ""
    controller_config_path: str = ""
    shard_config_path: str = ""
    controller_namespace: str = "default"
    log_level: str = "INFO"
    workers: int = 2
    failure_rate_base_delay: float = 0.030  # seconds (reference default 30ms)
    failure_rate_max_delay: float = 5.0  # seconds (reference default 5s)
    rate_limit_elements_per_second: float = 50.0
    rate_limit_elements_burst: int = 300
    # TPU-native extensions:
    statsd_address: str = ""  # "host:port" UDP or "unix:///path" DogStatsD
    use_finalizers: bool = True
    resync_period_seconds: float = 30.0
    queue_backend: str = "auto"  # auto | native (C++) | python
    # Parallel shard fan-out: size of the bounded per-controller shard-sync
    # executor. 0 = auto (min(8, shard count)); 1 = sequential reference
    # behavior; N>1 = explicit bound on concurrent per-shard syncs.
    shard_sync_workers: int = 0
    # Content-hash write-skip cache: unchanged specs/data skip the per-shard
    # compare + write on re-reconciles (resync churn, burst duplicates).
    write_skip_cache: bool = True
    # Datadog log sink (the slog-datadog equivalent, reference main.go:43):
    # api key enables shipping logs to the intake; site picks the region;
    # endpoint overrides the intake URL outright (tests / proxies).
    datadog_api_key: str = ""
    datadog_site: str = "datadoghq.com"
    datadog_log_endpoint: str = ""
    # Leader election (BEYOND the reference, which is pinned to a single
    # Recreate replica): when enabled, N replicas race for a
    # coordination.k8s.io Lease and only the holder reconciles
    # (controller/leaderelect.py). identity defaults to hostname+suffix.
    leader_election: bool = False
    leader_election_lease_name: str = "nexus-configuration-controller"
    leader_election_identity: str = ""
    leader_election_lease_duration: float = 15.0
    leader_election_renew_period: float = 5.0
    # Shard health & job failover (nexus_tpu/ha/, docs/failover.md): when
    # enabled, the controller probes each shard's heartbeat leases, confirms
    # worker/shard failures (flap-suppressed deadlines), and re-places
    # failed workloads on healthy shards resuming from the latest durable
    # checkpoint. TTL is the worker renew deadline; a failure is confirmed
    # after `failover_suspect_misses` whole TTL windows of silence (so one
    # missed renewal never migrates a job), or
    # `failover_api_failure_threshold` consecutive probe errors for a shard
    # API outage (probing backs off exponentially up to
    # `failover_backoff_max_seconds` while it lasts).
    failover_enabled: bool = False
    heartbeat_ttl_seconds: float = 15.0
    failover_probe_interval_seconds: float = 5.0
    failover_suspect_misses: int = 2
    failover_api_failure_threshold: int = 3
    failover_backoff_max_seconds: float = 60.0
    failover_recovery_probes: int = 2


def _coerce(value: Any, target_type: Any) -> Any:
    if target_type is bool and isinstance(value, str):
        return value.strip().lower() in ("1", "true", "yes", "on")
    if target_type in (int, float, str):
        return target_type(value)
    return value


def load_config(
    cls: Type[T] = AppConfig,  # type: ignore[assignment]
    config_path: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
) -> T:
    """Layered load: defaults ← yaml ← environment overlay ← NEXUS__ env."""
    env = dict(os.environ if env is None else env)
    values: Dict[str, Any] = {}

    def merge_yaml(path: str) -> None:
        if path and os.path.isfile(path):
            with open(path) as f:
                doc = yaml.safe_load(f) or {}
            for k, v in doc.items():
                values[_normalize_key(k)] = v

    config_path = config_path or env.get("NEXUS_TPU_CONFIG", "")
    if config_path:
        merge_yaml(config_path)
        app_env = env.get("APPLICATION_ENVIRONMENT", "")
        if app_env:
            base, ext = os.path.splitext(config_path)
            merge_yaml(f"{base}.{app_env}{ext}")

    for key, value in env.items():
        if key.startswith(ENV_PREFIX):
            values[key[len(ENV_PREFIX) :].lower()] = value

    # resolve string annotations (`from __future__ import annotations` makes
    # f.type a string) so coercion follows the declared type, not the default
    try:
        import typing

        hints = typing.get_type_hints(cls)
    except Exception:
        hints = {}

    kwargs: Dict[str, Any] = {}
    for f in fields(cls):  # type: ignore[arg-type]
        if f.name in values:
            target = hints.get(f.name)
            if not isinstance(target, type):
                target = type(f.default) if f.default is not None else str
            kwargs[f.name] = _coerce(values[f.name], target)
    return cls(**kwargs)  # type: ignore[call-arg]


def _normalize_key(key: str) -> str:
    """yaml keys may be camelCase or snake_case; normalize to snake_case."""
    out = []
    for i, ch in enumerate(key):
        if ch.isupper() and i > 0 and not key[i - 1].isupper() and key[i - 1] != "_":
            out.append("_")
        out.append(ch.lower())
    return "".join(out)
