"""Accelerator detection helpers.

The TPU may be attached through a PJRT plugin whose backend name is not
"tpu" (e.g. the tunneled platform in this environment), so feature dispatch
keys off device_kind, not backend name.
"""

from __future__ import annotations

import jax


def is_tpu() -> bool:
    try:
        return "tpu" in jax.devices()[0].device_kind.lower()
    except Exception:
        return False


def device_kind() -> str:
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"
