"""Accelerator detection helpers.

The TPU may be attached through a PJRT plugin whose backend name is not
"tpu" (e.g. the tunneled platform in this environment), so feature dispatch
keys off device_kind, not backend name.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def force_platform(platform: str, num_cpu_devices: Optional[int] = None) -> None:
    """Point JAX at ``platform`` before the first backend initialization.

    The axon/TPU sitecustomize sets ``jax_platforms="axon,cpu"`` via
    jax.config, which silently overrides a ``JAX_PLATFORMS`` env var — so
    selecting CPU (e.g. for the driver's virtual-device dry run) requires
    re-applying the choice through jax.config. No-op (best-effort) if the
    backend is already initialized.
    """
    try:
        jax.config.update("jax_platforms", platform)
    except Exception:
        pass
    if num_cpu_devices:
        # skip only when XLA_FLAGS already forces at least as many host
        # devices (setting both can conflict in some JAX versions)
        import re

        m = re.search(
            r"xla_force_host_platform_device_count=(\d+)",
            os.environ.get("XLA_FLAGS", ""),
        )
        if m is None or int(m.group(1)) < num_cpu_devices:
            try:
                jax.config.update("jax_num_cpu_devices", num_cpu_devices)
            except Exception:
                pass


def honor_env_platforms() -> None:
    """Re-apply an explicit ``JAX_PLATFORMS`` env choice over sitecustomize's
    jax.config override. Leaves the ambient axon/TPU default alone."""
    env_plat = os.environ.get("JAX_PLATFORMS", "").strip()
    if env_plat and env_plat != "axon":
        force_platform(env_plat)


def is_tpu() -> bool:
    try:
        return "tpu" in jax.devices()[0].device_kind.lower()
    except Exception:
        return False


def device_kind() -> str:
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def sync_host(tree) -> None:
    """Bound a host-side timing window on the computation producing ``tree``.

    ``jax.block_until_ready`` is NOT a reliable window close on every
    platform: on the tunneled axon backend it has been observed returning
    without awaiting the computation (docs/PERF.md round-3 "measurement
    gotchas" — a seq-8192 flash forward "completed" in 9 µs against a
    ~71 ms round-trip link). Fetching bytes to the host cannot complete
    before the computation that produced them, so every timing loop closes
    with a one-element ``device_get`` of one leaf in addition to the block.
    """
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    jax.block_until_ready(tree)
    for leaf in leaves:
        if hasattr(leaf, "dtype"):
            jax.device_get(jnp.ravel(leaf)[:1])
            break
