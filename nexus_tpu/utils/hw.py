"""Accelerator detection helpers.

The TPU may be attached through a PJRT plugin whose backend name is not
"tpu" (e.g. the tunneled platform in this environment), so feature dispatch
keys off device_kind, not backend name.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def force_platform(platform: str, num_cpu_devices: Optional[int] = None) -> None:
    """Point JAX at ``platform`` before the first backend initialization.

    The axon/TPU sitecustomize sets ``jax_platforms="axon,cpu"`` via
    jax.config, which silently overrides a ``JAX_PLATFORMS`` env var — so
    selecting CPU (e.g. for the driver's virtual-device dry run) requires
    re-applying the choice through jax.config. No-op (best-effort) if the
    backend is already initialized.
    """
    try:
        jax.config.update("jax_platforms", platform)
    except Exception:
        pass
    if num_cpu_devices:
        # skip only when XLA_FLAGS already forces at least as many host
        # devices (setting both can conflict in some JAX versions)
        import re

        m = re.search(
            r"xla_force_host_platform_device_count=(\d+)",
            os.environ.get("XLA_FLAGS", ""),
        )
        if m is None or int(m.group(1)) < num_cpu_devices:
            try:
                jax.config.update("jax_num_cpu_devices", num_cpu_devices)
            except Exception:
                pass


def honor_env_platforms() -> None:
    """Re-apply an explicit ``JAX_PLATFORMS`` env choice over sitecustomize's
    jax.config override. Leaves the ambient axon/TPU default alone."""
    env_plat = os.environ.get("JAX_PLATFORMS", "").strip()
    if env_plat and env_plat != "axon":
        force_platform(env_plat)


def enable_persistent_compilation_cache(
    cache_dir: Optional[str] = None,
    min_compile_secs: float = 2.0,
    repo_default: bool = False,
) -> Optional[str]:
    """Point XLA's persistent compilation cache at ``cache_dir`` (or the
    ``NEXUS_XLA_CACHE_DIR`` env var). Executables serialized by one
    process are reused by the next — on the tunneled TPU backend a cold
    compile costs 20-40 s per program, so a shared cache turns repeat
    bench/probe runs from compile-bound into run-bound. Returns the
    directory actually configured, or None (disabled/unsupported).

    ``repo_default=True`` supplies the shared repo-local ``.jax_cache``
    when nothing else is configured — but ONLY on a resolved TPU backend
    (``is_tpu()``; call sites invoke this after backend init): XLA:CPU
    AOT reloads warn about machine-feature mismatches (SIGILL risk) and
    CPU compiles are cheap anyway, so an ambient axon,cpu run that fell
    back to CPU must not populate the shared cache.

    Must be called before the programs of interest are compiled; safe to
    call more than once. ``NEXUS_XLA_CACHE_DIR=off`` disables."""
    cache_dir = cache_dir or os.environ.get("NEXUS_XLA_CACHE_DIR") or ""
    if cache_dir == "off":
        return None
    if not cache_dir:
        if not (repo_default and is_tpu()):
            return None
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            ".jax_cache",
        )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_secs
        )
        return cache_dir
    except Exception:  # noqa: BLE001 — older jax / unsupported backend
        return None


def is_tpu() -> bool:
    try:
        return "tpu" in jax.devices()[0].device_kind.lower()
    except Exception:
        return False


_DONATION_SUPPORTED: Optional[bool] = None


def supports_donation() -> bool:
    """True when the resolved backend actually honors jit buffer donation.

    Probed ONCE by compiling a trivial donated program and checking the
    donated input was really consumed (``is_deleted``): a backend that
    ignores donation leaves the buffer alive (and warns), so keying off
    the platform name would either miss real support (CPU donates fine
    on current jax — the serving engine's per-dispatch cache copy was
    pure waste there) or silently lose it on an exotic plugin backend.
    """
    global _DONATION_SUPPORTED
    if _DONATION_SUPPORTED is None:
        try:
            import warnings

            import jax.numpy as jnp

            probe = jax.jit(lambda x: x + 1, donate_argnums=(0,))
            x = jnp.zeros((8,), jnp.float32)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                probe(x).block_until_ready()
            _DONATION_SUPPORTED = bool(x.is_deleted())
        except Exception:  # noqa: BLE001 — absent probe APIs = no donation
            _DONATION_SUPPORTED = False
    return _DONATION_SUPPORTED


def device_kind() -> str:
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def sync_host(tree) -> None:
    """Bound a host-side timing window on the computation producing ``tree``.

    ``jax.block_until_ready`` is NOT a reliable window close on every
    platform: on the tunneled axon backend it has been observed returning
    without awaiting the computation (docs/PERF.md round-3 "measurement
    gotchas" — a seq-8192 flash forward "completed" in 9 µs against a
    ~71 ms round-trip link). Fetching bytes to the host cannot complete
    before the computation that produced them, so every timing loop closes
    with a one-element ``device_get`` of one leaf in addition to the block.
    """
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    jax.block_until_ready(tree)
    for leaf in leaves:
        if hasattr(leaf, "dtype"):
            jax.device_get(jnp.ravel(leaf)[:1])
            break
