"""Test doubles shipped with the framework (envtest-style): an in-process
Kubernetes API server backed by :class:`~nexus_tpu.cluster.store.ClusterStore`
so the real-cluster client stack (kubeapi + KubeClusterStore) can be
exercised end-to-end without a cluster."""

from nexus_tpu.testing.fakekube import FakeKubeApiServer  # noqa: F401
