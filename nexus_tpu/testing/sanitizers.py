"""Runtime sanitizers for the serving engine (``NEXUS_SANITIZE=1``).

Static analysis (tools/nexuslint) catches what the AST can prove; the
two failure modes it cannot prove are exactly the ones that cost real
money on TPUs:

  * **silent recompiles** — the engine's contract is ONE compiled decode
    program per jitted callable for the whole serve loop (static shapes;
    runtime/serving.py module docstring). A shape or dtype leak turns
    that into a compile per wave: the run still produces correct tokens,
    just 100× slower — no test asserts on wall time, so nothing fails.
  * **leaked KV blocks** — ``BlockAllocator.pool_partition`` documents
    the invariant (every block free, parked, or referenced; nothing
    allocated or reserved once every lease released). PR 6's failover
    tests assert it after kill-mid-decode, but ordinary serve paths had
    no audit: a leak introduced on the happy path permanently shrinks
    the pool one request at a time.

Round 9 adds a third: the **radix prefix-tree invariant**
(PrefixCacheIndex.audit — structure, parked ⊆ indexed, descendant
closure), checked after every serve and at every admission wave inside
the engine, because a tree-bookkeeping slip (an orphaned chain, a parked
interior with referenced tails) silently degrades hit rates or strands
pool capacity without ever failing a token-exactness test.

Round 11 adds the **committed-publication audit**
(``audit_committed_publication``): every digest the radix tree indexes
after a serve must be a hash-chain prefix of text some request actually
committed — the tree-side proof that a speculation round's rejected
tokens (whose K/V the verify window wrote before acceptance was known)
can never be published to the prefix tree or, through it, the host
tier.

Round 10 adds a fourth: **host spill-tier coherence** — the pool
partition audit gains the spilled slot (spilled tree entries must
account 1:1 against host-store payloads; free + parked + referenced
still partition the POOL, spilled blocks live outside it in host RAM),
and ``audit_host_cache`` cross-checks the store's digest set against
the tree's spilled markers plus the store's byte accounting, because a
one-sided spill (marker without payload, or payload without marker) is
either an unmatchable promise or a slow host-RAM leak.

With ``NEXUS_SANITIZE=1`` (tier-1 conftest wires this), every
``ServingEngine.serve()`` call is followed by these audits; a violation
raises :class:`SanitizerError` inside whatever test drove the engine —
cheap enough to leave on for the whole suite (two dict reads and five
``_cache_size()`` probes per serve run).

Knobs:

  NEXUS_SANITIZE               truthy → conftest installs the audits
  NEXUS_SANITIZE_MAX_PROGRAMS  per-callable compiled-program bound
                               (default 2: the program itself, plus one
                               slot of slack for dtype-promotion drift
                               between jax versions)
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional

ENV_FLAG = "NEXUS_SANITIZE"
ENV_MAX_PROGRAMS = "NEXUS_SANITIZE_MAX_PROGRAMS"
DEFAULT_MAX_PROGRAMS = 2

#: the serving engine's compiled surface — every jax.jit callable it
#: constructs (runtime/serving.py __init__). An attr absent on the
#: engine (or a jax without ``_cache_size``) is skipped, not an error.
ENGINE_JIT_ATTRS = (
    "_decode_chunk",
    "_decode_chunk_narrow",
    "_insert_fn",
    "_copy_fn",
    "_spec_chunk",
    "_spill_gather_fn",
    "_restore_write_fn",
    # round 11: the draft-tier row-reset program (draft engines only —
    # absent attrs are skipped); the verify-window program itself is
    # `_spec_chunk`, shared by both speculation tiers
    "_draft_reset_fn",
)


class SanitizerError(AssertionError):
    """A runtime invariant the sanitizers watch for was violated."""


def sanitizers_enabled(env: Optional[Dict[str, str]] = None) -> bool:
    raw = (env if env is not None else os.environ).get(ENV_FLAG, "")
    return raw.strip().lower() not in ("", "0", "off", "false", "no")


def max_programs(env: Optional[Dict[str, str]] = None) -> int:
    raw = (env if env is not None else os.environ).get(ENV_MAX_PROGRAMS, "")
    try:
        return max(1, int(raw))
    except ValueError:
        return DEFAULT_MAX_PROGRAMS


# ---------------------------------------------------------------------------
# audit 1: pool-partition leak check


def audit_pool_partition(metrics: Dict[str, Any], context: str = "serve") -> None:
    """Assert the end-of-serve block-pool partition is leak-free.

    Reads the ledger ``serve()`` already publishes (kv_*_blocks_final):
    free + parked must cover the whole pool, and with every lease
    released nothing may remain allocated or reserved — a non-zero
    residue is a leaked lease (or a reservation refund that never
    happened). Dense-layout runs carry no pool and are skipped.
    """
    if metrics.get("kv_layout") != "paged":
        return
    free = metrics.get("kv_free_blocks_final")
    parked = metrics.get("kv_parked_blocks_final")
    allocated = metrics.get("kv_allocated_blocks_final")
    reserved = metrics.get("kv_reserved_blocks_final")
    total = metrics.get("kv_num_blocks")
    if None in (free, parked, allocated, reserved, total):
        raise SanitizerError(
            f"{context}: paged serve metrics are missing the pool-partition "
            "ledger (kv_*_blocks_final) — the leak audit has nothing to check"
        )
    partition = f"free={free} parked={parked} allocated={allocated} " \
                f"reserved={reserved} total={total}"
    if allocated != 0:
        raise SanitizerError(
            f"{context}: {allocated} KV block(s) still allocated after every "
            f"lease should have released — leaked lease ({partition})"
        )
    if reserved != 0:
        raise SanitizerError(
            f"{context}: {reserved} reserved KV block(s) never refunded "
            f"({partition})"
        )
    if free + parked != total:
        raise SanitizerError(
            f"{context}: free+parked != pool — block(s) fell out of the "
            f"partition entirely ({partition})"
        )
    # the SPILLED tier (round 10): spilled entries are NOT pool blocks
    # (their K/V live in host RAM), but they must account 1:1 against
    # the host store — a spilled marker without a payload is an
    # unmatchable promise, a payload without a marker is a host-RAM
    # leak. Absent keys = host tier off, nothing to check.
    if metrics.get("host_cache_enabled"):
        spilled = metrics.get("kv_spilled_blocks_final")
        entries = metrics.get("host_cache_entries_final")
        if spilled is None or entries is None:
            raise SanitizerError(
                f"{context}: host tier enabled but the spilled-tier "
                "ledger (kv_spilled_blocks_final / "
                "host_cache_entries_final) is missing"
            )
        if spilled != entries:
            raise SanitizerError(
                f"{context}: {spilled} spilled tree entr(y/ies) vs "
                f"{entries} host-store payload(s) — the spilled tier "
                "leaked (tree and store must transition together)"
            )


# ---------------------------------------------------------------------------
# audit 2: radix-tree invariant (prefix cache)


def audit_prefix_tree(engine: Any, context: str = "serve") -> None:
    """Assert the radix prefix index's structural invariant after a
    serve run (PrefixCacheIndex.audit): runs/accelerator-map agreement,
    parked ⊆ indexed, and descendant closure (a parked block's cached
    descendants are parked too — the property that makes leaf-first
    eviction always able to progress and every parked block honestly
    reclaimable capacity). Engines without a prefix index (dense layout
    or cache off) are skipped."""
    index = getattr(engine, "last_prefix_index", None)
    if index is None:
        return
    try:
        index.audit()
    except AssertionError as e:
        raise SanitizerError(
            f"{context}: radix prefix-tree invariant violated — {e}"
        ) from e


# ---------------------------------------------------------------------------
# audit 2b: host spill tier ⟺ radix tree coherence


def audit_host_cache(engine: Any, context: str = "serve") -> None:
    """Assert the host spill tier and the radix tree agree bit for bit
    after a serve run: the store's digests equal the tree's spilled
    entries exactly (a one-sided entry is either an unmatchable promise
    or leaked host RAM), and the store's byte accounting reproduces
    from its live payloads. Engines without a host tier are skipped."""
    store = getattr(engine, "last_host_store", None)
    if store is None:
        return
    index = getattr(engine, "last_prefix_index", None)
    store_keys = set(store.keys())
    tree_keys = set(getattr(index, "_spilled", {})) if index else set()
    if store_keys != tree_keys:
        only_store = len(store_keys - tree_keys)
        only_tree = len(tree_keys - store_keys)
        raise SanitizerError(
            f"{context}: host store and radix tree disagree on the "
            f"spilled set ({only_store} payload(s) without a tree "
            f"marker, {only_tree} marker(s) without a payload)"
        )
    try:
        store.audit()
    except AssertionError as e:
        raise SanitizerError(
            f"{context}: host cache byte accounting violated — {e}"
        ) from e


# ---------------------------------------------------------------------------
# audit 2c: committed-text publication (rollback-never-publishes)


def audit_committed_publication(
    engine: Any, requests, results, context: str = "serve"
) -> None:
    """Assert every digest the radix tree indexes after a serve run is
    a full-block hash-chain prefix of text some request actually
    COMMITTED — its prompt (published block by block as prefill writes
    them) or its prompt + emitted tokens up to ONE short of the newest
    (whose K/V may never have landed; runtime/serving.py::
    register_completion_blocks).

    This is the tree-side proof that speculation's rollback is airtight
    (round 11): a verify window writes K/V for proposed-then-REJECTED
    tokens into a row's tail blocks before acceptance is known, and the
    rollback is a pointer rewind — the garbage stays in the pool until
    overwritten. A publication path that indexed a block spanning
    rejected positions would therefore serve OTHER requests rejected-
    draft K/V under a digest that looks committed, and no token-
    exactness test of the publishing request would ever notice. The
    invariant isn't speculation-specific (plain engines are audited
    too); speculation is just the mechanism most likely to break it.

    Drained rows (engine death) are covered through ``last_drain`` —
    their committed snapshots publish at release exactly like finished
    rows.

    Engine-lifetime trees (round 16): digests indexed by PREVIOUS
    serve() calls persist by design — they were proven against their
    own call's committed text when published, and the engine snapshots
    them (``last_preexisting_keys``) at each call boundary, so only
    THIS call's publications are checked here. Streamed arrivals are
    covered through ``last_requests`` (the call's full request list,
    source deliveries included)."""
    index = getattr(engine, "last_prefix_index", None)
    bs = int(getattr(engine, "_block_size", 0) or 0)
    if index is None or bs <= 0:
        return
    requests = list(getattr(engine, "last_requests", None) or requests)
    preexisting = getattr(engine, "last_preexisting_keys", None) or frozenset()
    from nexus_tpu.runtime.prefix_cache import chain_keys

    allowed = set()

    def admit_text(toks) -> None:
        for key in chain_keys([int(t) for t in toks], bs):
            allowed.add(key)

    for req, res in zip(requests, results or []):
        if res is None:
            continue
        toks = [int(t) for t in res.tokens]
        p = len(list(req.prompt))
        if len(toks) > p:
            # one chain covers both publication sites: its first
            # floor(p/bs) digests ARE the prompt chain (hash chains of
            # a shared prefix are identical)
            admit_text(toks[:-1])
        else:
            admit_text(toks[:p])
    for d in (getattr(engine, "last_drain", None) or []):
        req = requests[d.request_idx]
        prompt = [int(t) for t in req.prompt]
        committed = [int(t) for t in d.committed]
        if committed:
            admit_text((prompt + committed)[:-1])
        else:
            admit_text(prompt)
    stray = [
        k for k in index.indexed_keys()
        if k not in allowed and k not in preexisting
    ]
    if stray:
        raise SanitizerError(
            f"{context}: {len(stray)} indexed radix digest(s) match no "
            "request's committed text — a block whose tokens were never "
            "committed (e.g. a partially-rejected speculation window) "
            "was published to the prefix tree"
        )


# ---------------------------------------------------------------------------
# audit 2d: engine-lifetime call-boundary state (round 16)


def audit_warm_boundary(engine: Any, context: str = "warm-entry") -> None:
    """Assert a WARM engine's persisted KV state is clean at a serve()
    call boundary — the engine-lifetime analogue of the post-serve
    audits, run against whatever happened BETWEEN calls: with every
    lease released, the pool must partition into free + parked exactly
    (nothing allocated or reserved), the radix tree must satisfy its
    structural invariant, and the host spill tier must agree with the
    tree bit for bit. ``ServingEngine.serve`` calls this under
    NEXUS_SANITIZE before building on inherited state, so a dirty tree
    or pool trips HERE with a boundary-named error instead of
    corrupting a mid-wave admission. Dense-layout engines carry no pool
    and are skipped."""
    alloc = getattr(engine, "_alloc", None)
    if alloc is None:
        return
    part = alloc.pool_partition()
    partition = (
        f"free={part['free']} parked={part['parked']} "
        f"allocated={part['allocated']} reserved={part['reserved']} "
        f"total={alloc.num_blocks}"
    )
    if part["allocated"] != 0:
        raise SanitizerError(
            f"{context}: {part['allocated']} KV block(s) still "
            f"allocated at the call boundary — a previous call leaked "
            f"a lease ({partition})"
        )
    if part["reserved"] != 0:
        raise SanitizerError(
            f"{context}: {part['reserved']} reserved KV block(s) never "
            f"refunded at the call boundary ({partition})"
        )
    if part["free"] + part["parked"] != alloc.num_blocks:
        raise SanitizerError(
            f"{context}: free+parked != pool at the call boundary — "
            f"block(s) fell out of the partition ({partition})"
        )
    audit_prefix_tree(engine, context=context)
    audit_host_cache(engine, context=context)


# ---------------------------------------------------------------------------
# audit 3: bounded jit recompiles


def jit_program_counts(engine: Any) -> Dict[str, int]:
    """Compiled-program count per engine jit callable (best-effort:
    attrs or ``_cache_size`` absent on this jax version are skipped)."""
    counts: Dict[str, int] = {}
    seen = set()
    for attr in ENGINE_JIT_ATTRS:
        fn = getattr(engine, attr, None)
        if fn is None or id(fn) in seen:
            continue  # narrow may alias the wide program at T == 1
        seen.add(id(fn))
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            continue
        try:
            counts[attr] = int(probe())
        except Exception:  # noqa: BLE001 — introspection must never crash serving
            continue
    return counts


def audit_recompiles(
    engine: Any, bound: Optional[int] = None, context: str = "serve"
) -> Dict[str, int]:
    """Assert every engine jit callable stayed within its program bound.

    The steady-state contract is ONE program per callable (each is built
    for exactly one static shape signature); the default bound of 2
    leaves a slack slot so a jax-version dtype-promotion quirk doesn't
    hard-fail the suite, while a genuine per-wave recompile storm (tens
    of programs) is caught immediately. Returns the observed counts so
    callers can log them.
    """
    bound = max_programs() if bound is None else bound
    counts = jit_program_counts(engine)
    for attr, n in sorted(counts.items()):
        if n > bound:
            raise SanitizerError(
                f"{context}: {attr} compiled {n} programs (bound {bound}) — "
                "a shape or dtype is leaking into the decode wave; the "
                "one-compiled-program serving contract is broken "
                f"(all counts: {counts})"
            )
    return counts


# ---------------------------------------------------------------------------
# installation


_INSTALLED_FLAG = "_nexus_sanitize_wrapped"


def install(engine_cls: Optional[type] = None) -> bool:
    """Wrap ``ServingEngine.serve`` with both audits (idempotent).

    Returns True when the wrap is active (already-installed counts).
    Audits run only on serve() calls that RETURN — a serve that raises
    keeps its original traceback untouched.
    """
    if engine_cls is None:
        from nexus_tpu.runtime.serving import ServingEngine as engine_cls  # noqa: N813
    if getattr(engine_cls, _INSTALLED_FLAG, False):
        return True
    original: Callable = engine_cls.serve

    def serve_with_audits(self, requests, cancel=None, heartbeat=None,
                          tracer=None, **kw):
        results, metrics = original(
            self, requests, cancel=cancel, heartbeat=heartbeat,
            tracer=tracer, **kw,
        )
        audit_pool_partition(metrics, context="sanitizer[pool]")
        audit_prefix_tree(self, context="sanitizer[radix]")
        audit_host_cache(self, context="sanitizer[host-cache]")
        audit_committed_publication(
            self, requests, results, context="sanitizer[spec-publish]"
        )
        audit_recompiles(self, context="sanitizer[recompile]")
        return results, metrics

    serve_with_audits._nexus_sanitize_original = original  # type: ignore[attr-defined]
    engine_cls.serve = serve_with_audits
    setattr(engine_cls, _INSTALLED_FLAG, True)
    return True


def uninstall(engine_cls: Optional[type] = None) -> bool:
    """Undo :func:`install` (tests that exercise the sanitizer itself)."""
    if engine_cls is None:
        from nexus_tpu.runtime.serving import ServingEngine as engine_cls  # noqa: N813
    wrapped = engine_cls.serve
    original = getattr(wrapped, "_nexus_sanitize_original", None)
    if original is None:
        return False
    engine_cls.serve = original
    setattr(engine_cls, _INSTALLED_FLAG, False)
    return True
