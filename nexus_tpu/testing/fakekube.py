"""In-process fake Kubernetes API server (HTTP) over a ClusterStore.

The envtest-equivalent for this framework: it speaks enough of the real API
server's REST protocol — typed CRUD with resourceVersion/conflict
semantics, LIST with a list resourceVersion, chunked WATCH streams with
replay-from-resourceVersion, 410 Gone after history compaction, the status
subresource, and v1 Events — that the production client stack
(:mod:`nexus_tpu.cluster.kubeapi` + ``KubeClusterStore``) runs against it
unmodified. Two of these make a two-cluster e2e
(tests/test_kube_e2e.py, the reference's Test_ControllerMain shape,
/root/reference/controller_test.go:1287-1336) without kind or a kubelet.

Storage/semantics come from :class:`~nexus_tpu.cluster.store.ClusterStore`
(optimistic concurrency, finalizers, owner-reference GC) — the server is a
wire-protocol shim, not a second implementation.

Fault injection (the failover subsystem's chaos surface — no hardware, no
real outage needed):

  * :class:`ChaosHooks` — deterministic per-verb/per-kind rules (error N
    times, delay, drop the connection) consulted by every HTTP handler;
    ``server.chaos.add("error", verbs="get,list")`` simulates a shard API
    outage the failure detector must confirm and back off from.
  * :class:`ChaosClusterStore` — the same rules over an in-process
    ClusterStore, for tests/benches that skip the HTTP layer.
  * ``kill worker`` lives on the LocalLauncher (``launcher.kill``),
    ``expire lease`` on ha.lease.freeze_heartbeat, and ``wedge engine``
    (a serving engine stops renewing its ``hb-serve-<template>`` lease
    while the process keeps serving — detector-confirm-without-crash) on
    ha.serve_failover.freeze_engine — re-exported here so testing code
    has one chaos namespace.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from nexus_tpu.api.template import NexusAlgorithmTemplate
from nexus_tpu.api.types import ConfigMap, Lease, Secret
from nexus_tpu.api.workgroup import NexusAlgorithmWorkgroup
from nexus_tpu.api.workload import Job, Service
from nexus_tpu.cluster.store import (
    AlreadyExistsError,
    ClusterStore,
    ConflictError,
    NotFoundError,
)
# chaos-namespace re-exports: "expire lease" lives with the lease
# protocol, "wedge engine" with the serve-failover planner
from nexus_tpu.ha.lease import freeze_heartbeat  # noqa: F401
from nexus_tpu.ha.serve_failover import freeze_engine  # noqa: F401

_TYPES = {
    "secrets": Secret,
    "configmaps": ConfigMap,
    "services": Service,
    "jobs": Job,
    "leases": Lease,
    "nexusalgorithmtemplates": NexusAlgorithmTemplate,
    "nexusalgorithmworkgroups": NexusAlgorithmWorkgroup,
}
_BY_KIND = {t.KIND: t for t in _TYPES.values()}
_LIST_KINDS = {
    Secret.KIND: "SecretList",
    ConfigMap.KIND: "ConfigMapList",
    Service.KIND: "ServiceList",
    Job.KIND: "JobList",
    Lease.KIND: "LeaseList",
    NexusAlgorithmTemplate.KIND: "NexusAlgorithmTemplateList",
    NexusAlgorithmWorkgroup.KIND: "NexusAlgorithmWorkgroupList",
}


class ChaosRule:
    """One deterministic fault: match (verb, kind) → act, ``count`` times.

    ``mode``: "error" (HTTP 5xx / raised OSError), "delay" (sleep
    ``delay_s`` then proceed), "drop" (close the connection / raise
    ConnectionError — the half-open-socket failure TCP clients hate most).
    ``count`` -1 means forever; otherwise each match consumes one charge,
    so "fail the next 3 LISTs then recover" is a one-liner.
    """

    def __init__(self, mode: str, verbs: str = "*", kinds: str = "*",
                 count: int = -1, error_code: int = 503, delay_s: float = 0.0):
        if mode not in ("error", "delay", "drop"):
            raise ValueError(f"unknown chaos mode {mode!r}")
        self.mode = mode
        self.verbs = {v.strip().lower() for v in verbs.split(",")}
        self.kinds = {k.strip() for k in kinds.split(",")}
        self.count = count
        self.error_code = error_code
        self.delay_s = delay_s
        self.hits = 0

    def matches(self, verb: str, kind: str) -> bool:
        if self.count == 0:
            return False
        if "*" not in self.verbs and verb.lower() not in self.verbs:
            return False
        if "*" not in self.kinds and kind not in self.kinds:
            return False
        return True

    def consume(self) -> None:
        self.hits += 1
        if self.count > 0:
            self.count -= 1


class ChaosHooks:
    """Rule registry shared by the HTTP server and ChaosClusterStore."""

    def __init__(self):
        self._lock = threading.Lock()
        self.rules: List[ChaosRule] = []

    def add(self, mode: str, verbs: str = "*", kinds: str = "*",
            count: int = -1, error_code: int = 503,
            delay_s: float = 0.0) -> ChaosRule:
        rule = ChaosRule(mode, verbs=verbs, kinds=kinds, count=count,
                         error_code=error_code, delay_s=delay_s)
        with self._lock:
            self.rules.append(rule)
        return rule

    def clear(self) -> None:
        with self._lock:
            self.rules = []

    def intercept(self, verb: str, kind: str) -> Optional[ChaosRule]:
        """First matching rule, its charge consumed. Delay rules sleep here
        (then fall through to normal handling); error/drop rules are
        returned for the caller to act on."""
        with self._lock:
            rule = next(
                (r for r in self.rules if r.matches(verb, kind)), None
            )
            if rule is not None:
                rule.consume()
        if rule is not None and rule.mode == "delay":
            import time

            time.sleep(rule.delay_s)
            return None
        return rule


class ChaosClusterStore:
    """ClusterStore proxy applying :class:`ChaosHooks` to every verb — the
    in-process twin of the HTTP server's fault injection, so detector /
    failover tests can wedge a shard without running a server. Shares the
    underlying store's objects and watch feed; only the *client-visible*
    verbs (the ones a remote API call would pay for) are interceptable."""

    def __init__(self, store: ClusterStore, chaos: Optional[ChaosHooks] = None):
        self._store = store
        self.chaos = chaos or ChaosHooks()

    def _gate(self, verb: str, kind: str) -> None:
        rule = self.chaos.intercept(verb, kind)
        if rule is None:
            return
        if rule.mode == "drop":
            raise ConnectionResetError(
                f"chaos: connection dropped ({verb} {kind})"
            )
        raise OSError(f"chaos: injected {rule.error_code} ({verb} {kind})")

    # ------------------------------------------------------ intercepted verbs
    def create(self, obj, field_manager: str = ""):
        self._gate("create", obj.KIND)
        return self._store.create(obj, field_manager=field_manager)

    def get(self, kind: str, namespace: str, name: str):
        self._gate("get", kind)
        return self._store.get(kind, namespace, name)

    def list(self, kind: str, namespace=None, label_selector=None):
        self._gate("list", kind)
        return self._store.list(kind, namespace, label_selector=label_selector)

    def update(self, obj, field_manager: str = ""):
        self._gate("update", obj.KIND)
        return self._store.update(obj, field_manager=field_manager)

    def update_status(self, obj, field_manager: str = ""):
        self._gate("update", obj.KIND)
        return self._store.update_status(obj, field_manager=field_manager)

    def delete(self, kind: str, namespace: str, name: str):
        self._gate("delete", kind)
        return self._store.delete(kind, namespace, name)

    # ------------------------------------------------------------ passthrough
    def __getattr__(self, attr):
        # subscribe/unsubscribe/seed/name/actions/_lock/... — everything
        # that is not a remote API verb goes straight through
        return getattr(self._store, attr)


class _History:
    """Watch event history with replay + compaction (the etcd window)."""

    def __init__(self):
        self.lock = threading.Condition()
        self.entries: List[Tuple[int, str, str, str, Dict[str, Any]]] = []
        # (rv, kind, namespace, type, object_dict)
        self.oldest_rv = 0  # events with rv <= oldest_rv are compacted away

    def append(self, rv: int, kind: str, namespace: str, etype: str, obj: Dict):
        with self.lock:
            self.entries.append((rv, kind, namespace, etype, obj))
            self.lock.notify_all()

    def compact(self):
        """Drop all retained history — any watch resuming from an old
        resourceVersion must now re-list (410 Gone), exactly the condition
        the client's reflector loop has to survive."""
        with self.lock:
            if self.entries:
                self.oldest_rv = max(e[0] for e in self.entries)
                self.entries = []
            self.lock.notify_all()


class FakeKubeApiServer:
    """HTTP API server over a ClusterStore. Start/stop per test."""

    def __init__(self, store: Optional[ClusterStore] = None, name: str = "fake",
                 required_token: str = "", latency_s: float = 0.0):
        self.store = store or ClusterStore(name)
        # Simulated request RTT (control-plane bench realism: a remote shard
        # cluster's API server is a network round trip away, not a
        # same-process call). Applied to every non-watch request, slept
        # before handling — real wall time, GIL released.
        self.latency_s = float(latency_s)
        self.events: List[Dict[str, Any]] = []  # posted v1 Events
        # when set, every request must carry `Authorization: Bearer <this>`
        # (exercises the client's auth plumbing, incl. exec plugins)
        self.required_token = required_token
        # fault-injection rules consulted by every handler (see ChaosHooks)
        self.chaos = ChaosHooks()
        self.history = _History()
        for plural, typ in _TYPES.items():
            self.store.subscribe(typ.KIND, self._make_recorder(typ.KIND))
        handler = self._handler_class()
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"fakekube-{name}",
        )

    # ---------------------------------------------------------------- control
    def start(self) -> "FakeKubeApiServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    def write_kubeconfig(self, path: str,
                         exec_command: Optional[List[str]] = None) -> str:
        """Emit a minimal kubeconfig pointing at this server.

        With ``exec_command`` the user block uses a
        client.authentication.k8s.io exec plugin (command + args) instead of
        a static token — the shape GKE/EKS kubeconfigs use."""
        if exec_command:
            user: Dict[str, Any] = {"exec": {
                "apiVersion": "client.authentication.k8s.io/v1",
                "command": exec_command[0],
                "args": list(exec_command[1:]),
                "interactiveMode": "Never",
            }}
        else:
            user = {"token": self.required_token or "fake-token"}
        doc = {
            "apiVersion": "v1",
            "kind": "Config",
            "current-context": "fake",
            "contexts": [
                {"name": "fake", "context": {"cluster": "fake", "user": "fake"}}
            ],
            "clusters": [{"name": "fake", "cluster": {"server": self.url}}],
            "users": [{"name": "fake", "user": user}],
        }
        import yaml

        with open(path, "w") as f:
            yaml.safe_dump(doc, f)
        return path

    def compact_watch_history(self) -> None:
        self.history.compact()

    # --------------------------------------------------------------- plumbing
    def _make_recorder(self, kind: str):
        def record(ev):
            obj = ev.obj
            rv = int(obj.metadata.resource_version or 0)
            self.history.append(
                rv, kind, obj.metadata.namespace, ev.type, obj.to_dict()
            )

        return record

    def _handler_class(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            # silence per-request stderr logging
            def log_message(self, fmt, *args):  # noqa: D401
                pass

            # ------------------------------------------------------- helpers
            def _send_json(self, code: int, body: Dict[str, Any]):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _status(self, code: int, reason: str, message: str):
                self._send_json(
                    code,
                    {
                        "apiVersion": "v1",
                        "kind": "Status",
                        "status": "Failure",
                        "code": code,
                        "reason": reason,
                        "message": message,
                    },
                )

            def _simulate_rtt(self):
                if server.latency_s > 0:
                    import time

                    time.sleep(server.latency_s)

            def _route(self):
                """path → (kind, namespace, name|None, subresource|None)."""
                parsed = urlparse(self.path)
                parts = [p for p in parsed.path.split("/") if p]
                # /api/v1/namespaces/{ns}/{plural}[/name[/status]]
                # /apis/{group}/{ver}/namespaces/{ns}/{plural}[/name[/status]]
                if parts[:1] == ["api"]:
                    rest = parts[2:]
                elif parts[:1] == ["apis"]:
                    rest = parts[3:]
                else:
                    return None
                if len(rest) < 2 or rest[0] != "namespaces":
                    return None
                ns = rest[1]
                if len(rest) < 3:
                    return None
                plural = rest[2]
                name = rest[3] if len(rest) > 3 else None
                sub = rest[4] if len(rest) > 4 else None
                if plural == "events":
                    return ("__events__", ns, name, sub)
                if plural not in _TYPES:
                    return None
                return (_TYPES[plural].KIND, ns, name, sub)

            def _read_body(self) -> Dict[str, Any]:
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b"{}"
                return json.loads(raw or b"{}")

            def _chaos(self, verb: str, kind: str) -> bool:
                """Apply fault-injection rules; True = request consumed."""
                rule = server.chaos.intercept(verb, kind)
                if rule is None:
                    return False
                if rule.mode == "drop":
                    # no response at all: the client sees the connection
                    # reset mid-request (the rudest real-world failure)
                    import socket as _socket

                    self.close_connection = True
                    try:
                        self.connection.shutdown(_socket.SHUT_RDWR)
                    except OSError:
                        pass
                    return True
                self._status(
                    rule.error_code, "ServiceUnavailable",
                    f"chaos: injected failure ({verb} {kind})",
                )
                return True

            def _authorized(self) -> bool:
                """401 unless the request carries the server's bearer token
                (no-op when the server doesn't require one)."""
                if not server.required_token:
                    return True
                got = self.headers.get("Authorization") or ""
                if got == f"Bearer {server.required_token}":
                    return True
                self._status(401, "Unauthorized", "invalid bearer token")
                return False

            # --------------------------------------------------------- verbs
            def do_GET(self):  # noqa: N802
                if not self._authorized():
                    return
                params = parse_qs(urlparse(self.path).query)
                if params.get("watch", ["0"])[0] not in ("1", "true"):
                    self._simulate_rtt()
                route = self._route()
                if route is None:
                    if urlparse(self.path).path == "/-/compact":
                        server.compact_watch_history()
                        self._send_json(200, {"compacted": True})
                        return
                    self._status(404, "NotFound", f"no route {self.path}")
                    return
                kind, ns, name, _sub = route
                params = parse_qs(urlparse(self.path).query)
                if name is None and params.get("watch", ["0"])[0] in ("1", "true"):
                    if self._chaos("watch", kind):
                        return
                    self._do_watch(kind, ns, params)
                    return
                if self._chaos("list" if name is None else "get", kind):
                    return
                try:
                    if name is None:
                        # list snapshot + resourceVersion must be atomic:
                        # an rv newer than the snapshot would make watch
                        # resumption skip the in-between events (RLock, so
                        # the nested list() locking is fine)
                        selector = None
                        raw_sel = params.get("labelSelector", [""])[0]
                        if raw_sel:
                            selector = dict(
                                part.split("=", 1)
                                for part in raw_sel.split(",")
                                if "=" in part
                            )
                        with server.store._lock:
                            items = server.store.list(
                                kind, ns, label_selector=selector
                            )
                            rv = str(server.store._rv_counter)
                        self._send_json(
                            200,
                            {
                                "apiVersion": "v1",
                                "kind": _LIST_KINDS[kind],
                                "metadata": {"resourceVersion": rv},
                                "items": [o.to_dict() for o in items],
                            },
                        )
                    else:
                        obj = server.store.get(kind, ns, name)
                        self._send_json(200, obj.to_dict())
                except NotFoundError as e:
                    self._status(404, "NotFound", str(e))

            def do_POST(self):  # noqa: N802
                if not self._authorized():
                    return
                self._simulate_rtt()
                route = self._route()
                if route is None:
                    self._status(404, "NotFound", f"no route {self.path}")
                    return
                kind, ns, _name, _sub = route
                if self._chaos("create", kind):
                    return
                body = self._read_body()
                if kind == "__events__":
                    server.events.append(body)
                    self._send_json(201, body)
                    return
                typ = _BY_KIND[kind]
                obj = typ.from_dict(body)
                obj.metadata.namespace = obj.metadata.namespace or ns
                try:
                    created = server.store.create(obj)
                except AlreadyExistsError as e:
                    self._status(409, "AlreadyExists", str(e))
                    return
                self._send_json(201, created.to_dict())

            def do_PUT(self):  # noqa: N802
                if not self._authorized():
                    return
                self._simulate_rtt()
                route = self._route()
                if route is None or route[2] is None:
                    self._status(404, "NotFound", f"no route {self.path}")
                    return
                kind, ns, name, sub = route
                if self._chaos("update", kind):
                    return
                body = self._read_body()
                typ = _BY_KIND[kind]
                obj = typ.from_dict(body)
                obj.metadata.namespace = obj.metadata.namespace or ns
                obj.metadata.name = obj.metadata.name or name
                try:
                    if sub == "status":
                        out = server.store.update_status(obj)
                    else:
                        out = server.store.update(obj)
                except NotFoundError as e:
                    self._status(404, "NotFound", str(e))
                    return
                except ConflictError as e:
                    self._status(409, "Conflict", str(e))
                    return
                self._send_json(200, out.to_dict())

            def do_DELETE(self):  # noqa: N802
                if not self._authorized():
                    return
                self._simulate_rtt()
                route = self._route()
                if route is None or route[2] is None:
                    self._status(404, "NotFound", f"no route {self.path}")
                    return
                kind, ns, name, _sub = route
                if self._chaos("delete", kind):
                    return
                try:
                    server.store.delete(kind, ns, name)
                except NotFoundError as e:
                    self._status(404, "NotFound", str(e))
                    return
                self._send_json(
                    200,
                    {"apiVersion": "v1", "kind": "Status", "status": "Success"},
                )

            # --------------------------------------------------------- watch
            def _write_chunk(self, data: bytes):
                self.wfile.write(f"{len(data):x}\r\n".encode())
                self.wfile.write(data)
                self.wfile.write(b"\r\n")
                self.wfile.flush()

            def _do_watch(self, kind: str, ns: str, params):
                import time

                rv = int(params.get("resourceVersion", ["0"])[0] or 0)
                timeout = float(params.get("timeoutSeconds", ["60"])[0])
                hist = server.history
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def emit(etype: str, obj: Dict[str, Any]) -> bool:
                    try:
                        self._write_chunk(
                            (json.dumps({"type": etype, "object": obj}) + "\n")
                            .encode()
                        )
                        return True
                    except (BrokenPipeError, ConnectionResetError):
                        return False

                deadline = time.monotonic() + timeout
                cursor = rv
                alive = True
                while alive and time.monotonic() < deadline:
                    with hist.lock:
                        if cursor and cursor < hist.oldest_rv:
                            # the window was compacted past the client's rv
                            alive = emit(
                                "ERROR",
                                {
                                    "apiVersion": "v1",
                                    "kind": "Status",
                                    "status": "Failure",
                                    "code": 410,
                                    "reason": "Expired",
                                    "message": "resourceVersion too old",
                                },
                            )
                            break
                        # entries are rv-ascending (appended in commit
                        # order): bisect past the cursor instead of
                        # re-scanning the whole window on every wakeup —
                        # O(window) scans per event per watcher dominated
                        # the server's CPU under burst load
                        import bisect

                        start = bisect.bisect_right(
                            hist.entries, cursor, key=lambda e: e[0]
                        )
                        pending = [
                            e
                            for e in hist.entries[start:]
                            if e[1] == kind and e[2] == ns
                        ]
                        if not pending:
                            hist.lock.wait(
                                timeout=min(0.25, max(0.0, deadline - time.monotonic()))
                            )
                            continue
                    for entry_rv, _k, _ns, etype, obj in pending:
                        cursor = max(cursor, entry_rv)
                        if not emit(etype, obj):
                            alive = False
                            break
                # terminate the chunked stream
                try:
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass

        return Handler
