"""Serve-plane failover: drain a dead engine's requests, requeue them
with committed tokens preserved, complete them on a replacement engine.

Training jobs already fail over step-exact (ha/failover.py); this module
is the SERVING analogue. A serving engine renews an
``hb-serve-<template>`` heartbeat lease at every wave boundary through
the exact same ConfigMap protocol trainers use (ha/lease.py), so the
existing :class:`~nexus_tpu.ha.detector.FailureDetector` confirms engine
death — wedged (lease frozen, process alive) or crashed (silence) — with
the same flap suppression and clock discipline. What differs is the
recovery unit: a trainer resumes from a checkpoint step; a serving
engine's durable state is each request's COMMITTED TOKEN PREFIX.

The :class:`ServeFailoverPlanner` turns an engine's drain snapshot
(``ServingEngine.last_drain`` — in-flight rows with their committed
tokens plus the still-queued tail) into a requeue plan: each in-flight
request re-enters the wait queue with its committed completion FOLDED
INTO THE PROMPT, so the replacement engine never re-decodes recovered
work — it chunk-prefills prompt + committed (cheap, parameter-bound) and
decodes only the unmatched tail. Exactness carries over unchanged:

  * greedy (temperature 0): token i+1 is a function of tokens 0..i
    alone, so decoding the remaining budget from prompt + committed
    reproduces the undisturbed stream token for token;
  * sampled: the engine's sampling key is (request seed, absolute buffer
    position) and the merged prompt preserves every absolute position,
    so the recovered sample stream is identical too;
  * with the prefix cache on, the merged prompts' full-block hash chains
    (prompt PLUS already-committed completion) dedupe across requeued
    requests on the replacement engine — a shared system preamble
    prefills once for the whole recovered cohort, exactly as on the
    engine that died.

The :class:`ServeEngineSupervisor` is the in-process harness that wires
the pieces end to end — renewer → detector → confirm → fence → drain →
requeue → replacement — for the chaos tests, ``make serve-chaos-smoke``,
and the ``bench-serve-outage`` lane. On real fleets the controller plays
this role through the same planner (the fleet-serving ROADMAP item).

Chaos surface: :func:`freeze_engine` wedges an engine's lease without
killing the process (the serve twin of ``freeze_heartbeat``), and a
launcher-style hard kill (CancelToken) stops renewals outright; the
detector must confirm both.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from nexus_tpu.ha.detector import EVENT_LEASE_EXPIRED, FailureDetector
from nexus_tpu.ha.lease import (
    LeaseRenewer,
    freeze_heartbeat,
    heartbeat_name,
    list_heartbeats,
)

logger = logging.getLogger("nexus_tpu.ha")

# A serving engine's lease is ``hb-serve-<template>``: the ``serve-``
# infix keeps engine liveness distinct from the template's own training
# heartbeat namespace while riding the identical ConfigMap protocol,
# detector, and chaos hooks.
SERVE_HB_PREFIX = "serve-"


def serve_heartbeat_template(template_name: str) -> str:
    """Template field of a serving engine's lease (ConfigMap name then
    becomes ``hb-serve-<template>`` via ha.lease.heartbeat_name)."""
    return SERVE_HB_PREFIX + template_name


def is_serve_lease(lease_template: str) -> bool:
    return lease_template.startswith(SERVE_HB_PREFIX)


def strip_serve_prefix(lease_template: str) -> str:
    """The workload template a (possibly serve-) lease belongs to — the
    name the failover planner must look up and label-select Jobs by."""
    if is_serve_lease(lease_template):
        return lease_template[len(SERVE_HB_PREFIX):]
    return lease_template


def serve_replica_template(template_name: str, replica_id: str) -> str:
    """Lease template of ONE fleet engine replica: each replica of a
    fleet serve workload (nexus_tpu/fleet/) renews its own
    ``hb-serve-<template>--<replica>`` lease, so the one detector the
    fleet monitor runs confirms deaths per REPLICA — the double dash
    keeps the replica id parseable out of the lease name even when the
    template name itself contains dashes."""
    return serve_heartbeat_template(f"{template_name}--{replica_id}")


def replica_of_serve_lease(lease_template: str,
                           template_name: str) -> Optional[str]:
    """The replica id a fleet serve lease belongs to, or None when the
    lease is not a replica lease of ``template_name`` (the inverse of
    :func:`serve_replica_template`)."""
    prefix = SERVE_HB_PREFIX + template_name + "--"
    if lease_template.startswith(prefix):
        return lease_template[len(prefix):]
    return None


def freeze_engine(store, namespace: str, template_name: str) -> None:
    """Chaos hook ("wedge engine"): freeze a serving engine's heartbeat
    lease so its renewer stops touching it while the engine process
    stays alive and serving — the detector must confirm the death
    WITHOUT a crash ever happening (mirrors ``freeze_heartbeat`` for
    trainers)."""
    freeze_heartbeat(store, namespace, serve_heartbeat_template(template_name))


@dataclass
class RequeueEntry:
    """One live entry of the (re)queue: the ORIGINAL queue index it
    answers, the request as the next engine generation should see it
    (committed tokens folded into the prompt, budget reduced, retries
    bumped), every token recovered from prior generations, and the
    serve time those dead generations already spent (``elapsed_s`` —
    added back into the stitched latency so failover can never make a
    request look FASTER than an undisturbed run)."""

    request_idx: int
    request: Any  # ServeRequest (imported lazily — keep jax out of ha/)
    committed: List[int] = field(default_factory=list)
    elapsed_s: float = 0.0
    # when the entry ARRIVED, seconds on the fleet's streaming clock
    # (round 16 open-loop admission; None = closed-loop entry, queue
    # time anchors at serve() entry as before). The fleet rebases this
    # onto each engine call's own clock so ServeResult.queue_s measures
    # from true arrival; a requeued entry is restamped at requeue time
    # (the engine clock pauses while nothing serves — docs/failover.md)
    arrival_s: Optional[float] = None


class ServeFailoverPlanner:
    """Pure planner: drain snapshot → requeue plan → stitched results.

    Stateless between calls and free of clocks, threads, and stores —
    every path unit-tests in microseconds (the detector's design). The
    supervisor (below) and the controller own orchestration."""

    def fresh(self, requests: Sequence[Any]) -> List[RequeueEntry]:
        """The generation-0 queue: every request verbatim — except that
        a request without a ``journey`` id gets one stamped here
        (``j<queue index>``, on a COPY; caller objects are never
        mutated). The journey id is the fleet-stable identity the obs
        layer stitches cross-replica span timelines by
        (nexus_tpu/obs/journey.py); ``requeue`` carries it through
        every migration, so one id names the request on every engine
        that ever served it."""
        import dataclasses

        out: List[RequeueEntry] = []
        for i, req in enumerate(requests):
            if (dataclasses.is_dataclass(req)
                    and hasattr(req, "journey")
                    and not getattr(req, "journey")):
                req = dataclasses.replace(req, journey=f"j{i}")
            out.append(RequeueEntry(request_idx=i, request=req))
        return out

    def requeue(self, entries: Sequence[RequeueEntry],
                drained: Sequence[Any]) -> List[RequeueEntry]:
        """Drained requests (``DrainedRequest``, indices into THIS
        generation's queue) → the next generation's queue. Committed
        tokens fold into the prompt (never re-decoded; absolute buffer
        positions — and therefore sampled streams — are preserved), the
        decode budget shrinks by exactly what was recovered, and
        ``retries`` increments. Queue order is preserved: in-flight rows
        requeue ahead of the never-admitted tail, matching the FIFO
        order the dead engine was serving."""
        from nexus_tpu.runtime.serving import ServeRequest

        out: List[RequeueEntry] = []
        for d in drained:
            base = entries[d.request_idx]
            req = base.request
            committed = [int(t) for t in d.committed]
            # the deadline budget is cumulative SERVE time: charge the
            # dead generation's elapsed clock so engine deaths can never
            # extend a request's deadline indefinitely (an exhausted
            # budget requeues with an epsilon deadline — the replacement
            # terminates it `deadline_exceeded` at its first boundary
            # instead of silently serving past the SLA). Detection /
            # restart wall time is NOT charged — the engine clock pauses
            # while nothing is being served (documented in
            # docs/failover.md).
            deadline = float(req.deadline_s or 0.0)
            if deadline > 0:
                deadline = max(1e-9, deadline - float(d.elapsed_s or 0.0))
            remaining = int(req.max_new_tokens) - len(committed)
            if remaining < 1:
                # can't happen off a consistent drain (a budget-complete
                # row finishes before any boundary snapshot), but a
                # malformed snapshot must not crash recovery
                logger.warning(
                    "drained request %d arrived budget-complete; "
                    "requeueing 1-token tail", base.request_idx,
                )
                remaining = 1
            merged = ServeRequest(
                prompt=[int(t) for t in req.prompt] + committed,
                max_new_tokens=remaining,
                temperature=req.temperature,
                seed=req.seed,
                deadline_s=deadline,
                priority=req.priority,
                retries=int(req.retries) + 1,
                journey=str(getattr(req, "journey", "") or ""),
            )
            out.append(RequeueEntry(
                request_idx=base.request_idx,
                request=merged,
                committed=list(base.committed) + committed,
                elapsed_s=float(base.elapsed_s) + float(d.elapsed_s or 0.0),
            ))
        return out

    def stitch(self, entry: RequeueEntry, result: Any) -> Any:
        """A recovered entry's engine result → the final ServeResult the
        ORIGINAL caller sees: ``new_tokens`` counts recovered + fresh
        tokens against the original prompt, ``latency_s`` adds the serve
        time the dead generations already spent (failover must never
        make a request look FASTER than an undisturbed run; detection /
        restart wall time between generations is still excluded — the
        supervisor reports it separately as recover_s), ``status``
        becomes ``failed_over`` for requests that survived an engine
        death and completed (shed / deadline statuses propagate
        unchanged — a failover must not launder a miss into a success),
        and the retry count rides along. ttft_s/queue_s remain the
        FINAL generation's observations (the true first token of a
        requeued request landed on an engine that no longer exists)."""
        from nexus_tpu.runtime.serving import (
            STATUS_FAILED_OVER,
            STATUS_OK,
            ServeResult,
        )

        if result is None:
            return None
        status = result.status
        if status == STATUS_OK and entry.request.retries > 0:
            status = STATUS_FAILED_OVER
        return ServeResult(
            tokens=list(result.tokens),
            new_tokens=len(entry.committed) + result.new_tokens,
            finished_by_stop=result.finished_by_stop,
            latency_s=round(float(entry.elapsed_s) + result.latency_s, 6),
            ttft_s=result.ttft_s,
            queue_s=result.queue_s,
            status=status,
            retries=int(result.retries),
        )


class ServeEngineSupervisor:
    """Drive one serve queue to completion across engine deaths.

    One generation = one engine (``make_engine()``) serving the current
    queue in a worker thread while renewing its ``hb-serve-<template>``
    lease at wave boundaries; the supervisor probes the store and feeds
    the :class:`FailureDetector` exactly as the FailoverManager probes
    trainer shards. A confirmed expiry FENCES the engine (cancel token —
    a wedged engine must stop committing before its requests re-enter
    the queue), drains it, requeues through the planner (stale/frozen
    lease reaped so the replacement starts clean), and starts the next
    generation. Requests that finished before a death keep their results
    (with ``failed_over`` stamped on recovered completions).

    ``kill_current(hard=True)`` is the launcher-style chaos kill for the
    RUNNING generation (the engine stops renewing and exits — silence
    the detector must confirm); ``freeze_engine`` wedges the lease with
    the process alive. Both recovery paths are exercised by
    ``make serve-chaos-smoke`` and the ``bench-serve-outage`` lane.
    """

    def __init__(
        self,
        make_engine: Callable[[], Any],
        store,
        namespace: str,
        template: str,
        ttl_seconds: float = 0.25,
        shard: str = "serve-shard",
        max_restarts: int = 3,
        poll_s: Optional[float] = None,
        pace_s: float = 0.0,
        detector: Optional[FailureDetector] = None,
        planner: Optional[ServeFailoverPlanner] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.make_engine = make_engine
        self.store = store
        self.namespace = namespace
        self.template = template
        self.ttl = float(ttl_seconds)
        self.shard = shard
        self.max_restarts = int(max_restarts)
        self.poll_s = float(poll_s) if poll_s else max(0.01, self.ttl / 5.0)
        # pace_s > 0 sleeps per wave boundary — gives CPU-instant stub
        # chunks a wall-clock duration so chaos can land mid-run (the
        # LocalLauncher.step_pace_s pattern)
        self.pace_s = float(pace_s)
        self.detector = detector or FailureDetector(
            ttl_seconds=self.ttl,
            suspect_misses=2,
            probe_interval=self.poll_s,
        )
        self.planner = planner or ServeFailoverPlanner()
        # injectable clock + sleeper (the detector's pattern): every
        # deadline, poll wait, and recover_s measurement below reads
        # _clock/_sleep, so supervision logic unit-tests without real time
        self._clock = clock
        self._sleep = sleep
        self._current_cancel = None
        self._last_heartbeats: List[Any] = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ chaos
    def kill_current(self, hard: bool = True) -> bool:
        """Launcher-style kill of the RUNNING engine generation (the
        renewer stops with it — the detector sees silence). Returns True
        if a generation was running."""
        with self._lock:
            cancel = self._current_cancel
        if cancel is None:
            return False
        cancel.cancel(hard=hard)
        return True

    # ------------------------------------------------------------- mechanics
    def _serve_lease_template(self) -> str:
        return serve_heartbeat_template(self.template)

    def _probe(self) -> List:
        """One detector probe of the store's heartbeats (API errors are
        observations, exactly as in FailoverManager.probe_once)."""
        try:
            heartbeats = list_heartbeats(self.store)
        except Exception as e:  # noqa: BLE001 — outage is an observation
            return self.detector.observe_api_error(self.shard, e)
        self._last_heartbeats = heartbeats
        return self.detector.observe(self.shard, heartbeats)

    def _confirmed(self, events) -> Optional[float]:
        tpl = self._serve_lease_template()
        for ev in events:
            if (ev.kind == EVENT_LEASE_EXPIRED and ev.lease is not None
                    and ev.lease.template == tpl):
                return float(ev.detection_seconds)
        return None

    def _reap_lease(self) -> None:
        """Delete the dead generation's (possibly frozen) lease so the
        replacement's renewer starts from a clean ConfigMap — a frozen
        lease left behind would instantly re-freeze the new renewer (the
        serve mirror of FailoverManager._cleanup_failed_shard)."""
        from nexus_tpu.api.types import ConfigMap
        from nexus_tpu.cluster.store import NotFoundError

        try:
            self.store.delete(
                ConfigMap.KIND, self.namespace,
                heartbeat_name(self._serve_lease_template()),
            )
        except NotFoundError:
            pass
        except Exception:  # noqa: BLE001 — cleanup is advisory
            logger.debug("serve lease reap incomplete", exc_info=True)

    # ------------------------------------------------------------------- run
    def run(self, requests: Sequence[Any], timeout_s: float = 180.0):
        """Serve ``requests`` to terminal results, surviving up to
        ``max_restarts`` engine deaths → ``(results, report)``.

        ``results[i]`` answers ``requests[i]`` — None only for requests
        genuinely lost (the acceptance gate requires zero). ``report``:
        ``restarts``, per-death ``detection_seconds`` and
        ``recover_s`` (confirmation → replacement engine's lease live
        again), ``requeued`` request count, ``fenced_alive`` (a
        confirmed-dead engine was still running — the freeze_engine
        case), ``requests_lost``, and per-generation engine metrics
        (``generations`` — the kill-side pool-partition audit reads the
        dead generation's ledger here)."""
        from nexus_tpu.utils.signals import CancelToken

        results: List[Optional[Any]] = [None] * len(requests)
        queue = self.planner.fresh(requests)
        report: Dict[str, Any] = {
            "restarts": 0,
            "detections_s": [],
            "recover_s": [],
            "requeued": 0,
            "fenced_alive": False,
            "generations": [],
            # one flight-recorder dump per drained generation (the
            # engine trips its ring on drain; nexus_tpu/obs/recorder.py)
            # — the kill-mid-decode postmortem record
            "flight_dumps": [],
        }
        deadline = self._clock() + float(timeout_s)
        pending_recover_t0: Optional[float] = None
        attempt = 0
        while queue:
            engine = self.make_engine()
            cancel = CancelToken()
            with self._lock:
                self._current_cancel = cancel
            holder = f"engine-{attempt}"
            renewer = LeaseRenewer(
                self.store, self.namespace, self._serve_lease_template(),
                holder=holder, ttl_seconds=self.ttl,
            )

            def hb(step, _renewer=renewer):
                _renewer.renew(step)
                if self.pace_s > 0:
                    self._sleep(self.pace_s)

            box: Dict[str, Any] = {}
            gen_queue = queue

            def work(_engine=engine, _cancel=cancel, _hb=hb,
                     _queue=gen_queue, _box=box):
                try:
                    _box["results"], _box["metrics"] = _engine.serve(
                        [e.request for e in _queue],
                        cancel=_cancel, heartbeat=_hb,
                    )
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    _box["error"] = e

            thread = threading.Thread(
                target=work, daemon=True,
                name=f"serve-engine-{self.template}-{attempt}",
            )
            thread.start()

            confirmed_detection: Optional[float] = None
            while thread.is_alive():
                if self._clock() > deadline:
                    cancel.cancel(hard=True)
                    thread.join(timeout=10.0)
                    raise TimeoutError(
                        f"supervised serve of {self.template!r} exceeded "
                        f"{timeout_s}s"
                    )
                events = self._probe()
                if pending_recover_t0 is not None and any(
                    hb_.template == self._serve_lease_template()
                    and hb_.holder == holder
                    for hb_ in self._last_heartbeats
                ):
                    # the replacement engine's lease is live again —
                    # confirmation → back-in-service, the serving half
                    # of time-to-recover
                    report["recover_s"].append(
                        self._clock() - pending_recover_t0
                    )
                    pending_recover_t0 = None
                confirmed_detection = self._confirmed(events)
                if confirmed_detection is not None:
                    # confirmed death with the process still running: a
                    # WEDGED engine (frozen lease) — fence it before its
                    # requests can be requeued anywhere else
                    report["fenced_alive"] = True
                    cancel.cancel(hard=True)
                    break
                self._sleep(self.poll_s)
            thread.join(timeout=30.0)
            with self._lock:
                self._current_cancel = None
            if thread.is_alive():
                # a fenced engine that won't reach its next wave
                # boundary within the join window is a zombie — its
                # drain snapshot never materialized, so treating this
                # as clean completion would silently abandon every
                # recoverable request. Fail loudly instead.
                raise RuntimeError(
                    f"serve engine {self.template!r} (generation "
                    f"{attempt}) did not stop within 30s of fencing; "
                    "its requests cannot be drained in-process"
                )
            if "error" in box:
                raise box["error"]
            gen_results = box.get("results") or [None] * len(gen_queue)
            gen_metrics = box.get("metrics") or {}
            report["generations"].append(gen_metrics)
            # harvest everything this generation finished (including
            # terminal shed / deadline statuses — those are answers)
            for entry, res in zip(gen_queue, gen_results):
                if res is not None:
                    results[entry.request_idx] = self.planner.stitch(
                        entry, res
                    )
            drained = getattr(engine, "last_drain", None) or []
            if drained:
                self._collect_flight_dump(engine, report, attempt)
            if not drained:
                if pending_recover_t0 is not None:
                    # the generation completed before the monitor ever
                    # saw its lease — bound recover time by completion
                    report["recover_s"].append(
                        self._clock() - pending_recover_t0
                    )
                    pending_recover_t0 = None
                if confirmed_detection is None:
                    renewer.complete(
                        int(gen_metrics.get("committed_tokens", -1) or -1)
                    )
                break  # clean completion — nothing to fail over
            # death path: the detector must CONFIRM before requeue (a
            # crash stops renewals; confirmation arrives by silence)
            if confirmed_detection is None:
                confirmed_detection = self._await_confirmation(deadline)
            report["detections_s"].append(confirmed_detection)
            report["restarts"] += 1
            if report["restarts"] > self.max_restarts:
                raise RuntimeError(
                    f"serve failover gave up after {self.max_restarts} "
                    f"restarts with {len(drained)} requests outstanding"
                )
            queue = self.planner.requeue(gen_queue, drained)
            report["requeued"] += len(queue)
            self._reap_lease()
            pending_recover_t0 = self._clock()
            attempt += 1
        report["requests_lost"] = sum(1 for r in results if r is None)
        return results, report

    def _collect_flight_dump(self, engine, report: Dict[str, Any],
                             attempt: int) -> None:
        """Harvest the dead generation's flight-recorder dump (the
        engine tripped its ring at the drain boundary) into the report,
        and — when ``NEXUS_FLIGHT_DUMP_DIR`` is set — persist it as a
        JSON postmortem artifact. Best-effort by design: a missing or
        unwritable dump must never block recovery."""
        dump = getattr(engine, "last_flight_dump", None)
        if dump is None:
            return
        report["flight_dumps"].append(dump)
        dump_dir = os.environ.get("NEXUS_FLIGHT_DUMP_DIR", "")
        if not dump_dir:
            return
        try:
            from nexus_tpu.obs.recorder import write_dump

            write_dump(dump, os.path.join(
                dump_dir,
                f"flight-{self.template}-gen{attempt}.json",
            ))
        except Exception:  # noqa: BLE001 — telemetry must not block recovery
            logger.debug("flight dump not persisted", exc_info=True)

    def _await_confirmation(self, deadline: float) -> float:
        """Probe until the detector confirms the serve lease expired (a
        crashed engine is confirmed by silence, after the flap
        suppression's full window count)."""
        while self._clock() < deadline:
            detection = self._confirmed(self._probe())
            if detection is not None:
                return detection
            self._sleep(self.poll_s)
        raise TimeoutError(
            "failure detector never confirmed the death of serve "
            f"engine {self.template!r}"
        )
