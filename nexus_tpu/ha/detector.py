"""Per-shard failure detector: deadline-based with flap suppression.

Two distinct failure modes, deliberately kept apart because they demand
different planner responses:

  * **API unreachable** — the probe itself (a heartbeat LIST against the
    shard's API server) raises. The shard may be partitioned while its
    workers keep training happily, so confirmation requires
    ``api_failure_threshold`` consecutive probe errors, probing backs off
    exponentially while the outage lasts (no retry storm into a dead
    tunnel), and the planner both excludes the shard from placement and
    abandons (rather than deletes) its Jobs.
  * **Worker lease expired** — the API answers but a worker's heartbeat
    stopped moving. The shard itself stays healthy; only that workload is
    failed over, and its dead Job CAN be deleted (the API is up).

Flap suppression in both directions: a single missed renewal (one TTL
window) only makes a lease SUSPECT — confirmation needs
``suspect_misses`` full windows; an unreachable shard needs
``recovery_probes`` consecutive clean probes before it is trusted again
(so a flapping tunnel cannot thrash placement).

The detector is a pure state machine over injected observations with an
injectable clock — every path unit-tests in milliseconds without threads
or sleeps. The FailoverManager (ha/failover.py) owns the probe loop.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from nexus_tpu.ha.lease import HeartbeatLease

# shard states
HEALTHY = "Healthy"
API_UNREACHABLE = "ApiUnreachable"
# lease states
FRESH = "Fresh"
SUSPECT = "Suspect"
EXPIRED = "Expired"

# event kinds
EVENT_SHARD_UNHEALTHY = "shard_unhealthy"
EVENT_SHARD_RECOVERED = "shard_recovered"
EVENT_LEASE_EXPIRED = "lease_expired"
EVENT_LEASE_RECOVERED = "lease_recovered"


@dataclass
class DetectorEvent:
    kind: str
    shard: str
    lease: Optional[HeartbeatLease] = None
    # seconds from the first missed deadline (or first probe error) to
    # confirmation — the detection half of time-to-recover
    detection_seconds: float = 0.0


@dataclass
class _LeaseTrack:
    renew_value: str = ""
    observed_at: float = 0.0  # local monotonic clock, last CHANGE observed
    state: str = FRESH
    last: Optional[HeartbeatLease] = None


@dataclass
class _ShardTrack:
    state: str = HEALTHY
    consecutive_errors: int = 0
    consecutive_ok: int = 0
    first_error_at: float = 0.0
    backoff: float = 0.0
    next_probe_at: float = 0.0
    leases: Dict[str, _LeaseTrack] = field(default_factory=dict)


class FailureDetector:
    """Deadline failure detector over heartbeat observations.

    Drive it with one of::

        events = detector.observe(shard_name, heartbeats)
        events = detector.observe_api_error(shard_name, err)

    per probe; consult :meth:`next_probe_delay` for the (backoff-aware)
    wait before the next probe of that shard.
    """

    def __init__(
        self,
        ttl_seconds: float = 15.0,
        suspect_misses: int = 2,
        api_failure_threshold: int = 3,
        probe_interval: float = 5.0,
        backoff_max: float = 60.0,
        recovery_probes: int = 2,
        clock: Callable[[], float] = time.monotonic,
    ):
        if suspect_misses < 1:
            raise ValueError("suspect_misses must be >= 1")
        if api_failure_threshold < 1:
            raise ValueError("api_failure_threshold must be >= 1")
        self.ttl_seconds = float(ttl_seconds)
        self.suspect_misses = int(suspect_misses)
        self.api_failure_threshold = int(api_failure_threshold)
        self.probe_interval = float(probe_interval)
        self.backoff_max = float(backoff_max)
        self.recovery_probes = int(recovery_probes)
        self.clock = clock
        self._shards: Dict[str, _ShardTrack] = {}

    # ------------------------------------------------------------------- state
    def _track(self, shard: str) -> _ShardTrack:
        return self._shards.setdefault(shard, _ShardTrack())

    def shard_state(self, shard: str) -> str:
        return self._track(shard).state

    def lease_state(self, shard: str, namespace: str, template: str) -> str:
        lt = self._track(shard).leases.get(f"{namespace}/{template}")
        return lt.state if lt is not None else FRESH

    def last_heartbeat(self, shard: str, namespace: str, template: str
                       ) -> Optional[HeartbeatLease]:
        """Last heartbeat observed for a workload on a shard — the real
        progress record the planner should report for shard-level failures
        (the probe that confirmed an outage never saw a fresh lease)."""
        lt = self._track(shard).leases.get(f"{namespace}/{template}")
        return lt.last if lt is not None else None

    def next_probe_delay(self, shard: str) -> float:
        """Seconds to wait before probing this shard again — the base
        interval while healthy, exponentially backed off while unreachable
        (capped at ``backoff_max``)."""
        t = self._track(shard)
        return t.backoff if t.backoff > 0 else self.probe_interval

    # ------------------------------------------------------------ observations
    def observe_api_error(self, shard: str, err: Optional[BaseException] = None
                          ) -> List[DetectorEvent]:
        now = self.clock()
        t = self._track(shard)
        t.consecutive_ok = 0
        t.consecutive_errors += 1
        if t.consecutive_errors == 1:
            t.first_error_at = now
        # exponential backoff while the outage lasts: interval, 2x, 4x, ...
        t.backoff = min(
            self.backoff_max,
            self.probe_interval * (2 ** (t.consecutive_errors - 1)),
        )
        events: List[DetectorEvent] = []
        if (
            t.state != API_UNREACHABLE
            and t.consecutive_errors >= self.api_failure_threshold
        ):
            t.state = API_UNREACHABLE
            events.append(DetectorEvent(
                EVENT_SHARD_UNHEALTHY, shard,
                detection_seconds=max(now - t.first_error_at, 0.0),
            ))
        return events

    def observe(self, shard: str, heartbeats: List[HeartbeatLease]
                ) -> List[DetectorEvent]:
        """A successful probe: the shard API answered with its heartbeats."""
        now = self.clock()
        t = self._track(shard)
        events: List[DetectorEvent] = []

        # ---- shard-level recovery (flap-suppressed)
        t.consecutive_errors = 0
        t.consecutive_ok += 1
        # an ANSWERING API ends the backoff immediately (backoff protects a
        # dead endpoint from a retry storm, not a live one) — probation
        # probes run at the normal cadence so recovery isn't starved by the
        # outage's final backoff value
        t.backoff = 0.0
        if t.state == API_UNREACHABLE:
            if t.consecutive_ok >= self.recovery_probes:
                t.state = HEALTHY
                # a reconnected shard may have lost state; re-baseline every
                # lease observation so stale renew values don't instantly
                # re-confirm expiry
                for lt in t.leases.values():
                    lt.observed_at = now
                events.append(DetectorEvent(EVENT_SHARD_RECOVERED, shard))
            else:
                return events  # still on probation: don't judge leases yet

        # ---- per-lease deadlines
        seen = set()
        for hb in heartbeats:
            key = f"{hb.namespace}/{hb.template}"
            seen.add(key)
            lt = t.leases.get(key)
            if lt is None:
                lt = t.leases[key] = _LeaseTrack(
                    renew_value=hb.renew_time, observed_at=now, last=hb,
                )
                continue
            lt.last = hb
            if hb.done:
                # graceful completion: silence is expected from here on
                if lt.state != FRESH:
                    lt.state = FRESH
                lt.renew_value = hb.renew_time
                lt.observed_at = now
                continue
            if hb.renew_time != lt.renew_value:
                was = lt.state
                lt.renew_value = hb.renew_time
                lt.observed_at = now
                lt.state = FRESH
                if was == EXPIRED:
                    events.append(DetectorEvent(EVENT_LEASE_RECOVERED, shard, hb))
                continue
            ttl = hb.ttl_seconds or self.ttl_seconds
            age = now - lt.observed_at
            misses = int(age // ttl) if ttl > 0 else 0
            if misses <= 0:
                continue
            if misses < self.suspect_misses:
                # one missed renewal is NOT a failure — a single slow write,
                # a GC pause, or a throttled renewer all look exactly like
                # this (the flap the suppression exists for)
                if lt.state == FRESH:
                    lt.state = SUSPECT
                continue
            if lt.state != EXPIRED:
                lt.state = EXPIRED
                events.append(DetectorEvent(
                    EVENT_LEASE_EXPIRED, shard, hb,
                    # from the first missed deadline to this confirmation
                    detection_seconds=max(age - ttl, 0.0),
                ))

        # leases that vanished from the listing (ConfigMap deleted — job
        # cleaned up or failed over) simply stop being tracked
        for key in list(t.leases):
            if key not in seen:
                del t.leases[key]
        return events
