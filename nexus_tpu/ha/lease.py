"""Heartbeat lease protocol — the worker-liveness half of shard failover.

A running worker renews a **heartbeat lease** at every training-step
boundary; the controller-side failure detector (ha/detector.py) judges
worker liveness from how long ago it last *observed* the lease move. The
lease is a plain ConfigMap (not coordination.k8s.io/v1 Lease) on purpose:
it rides the existing shard clients and fakekube routes unchanged, it is
visible to `kubectl get cm`, and it can carry workload progress (the last
completed step) that the failover planner uses to compute
``failover_steps_lost``.

Clock discipline mirrors controller/leaderelect.py: nobody compares their
wall clock to the timestamp *in* the lease — the detector only measures
how long ago it last saw ``renewTime`` CHANGE (local monotonic clock), so
wall-clock skew between worker pods and the controller cannot produce
false expiries.

Data contract (ConfigMap ``hb-<template>`` in the template's namespace,
labeled ``science.sneaksanddata.com/heartbeat=true``):

  holder      — worker identity (shard + pid/thread)
  renewTime   — RFC3339, informational only (see clock note above)
  step        — last completed training step (int as str)
  ttlSeconds  — the renew deadline the worker signed up for
  phase       — "running" | "done"; "done" is the graceful-completion
                marker, after which expiry is meaningless
  frozen      — chaos hook (testing/fakekube.py): "true" makes the
                renewer stop touching the lease, simulating a wedged
                worker without killing it
"""

from __future__ import annotations

import datetime
import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from nexus_tpu.api.types import GROUP, ConfigMap, ObjectMeta

logger = logging.getLogger("nexus_tpu.ha")

LABEL_HEARTBEAT = f"{GROUP}/heartbeat"
HB_PREFIX = "hb-"

PHASE_RUNNING = "running"
PHASE_DONE = "done"


def heartbeat_name(template_name: str) -> str:
    return HB_PREFIX + template_name


def _now_str() -> str:
    # renewTime is INFORMATIONAL only (module docstring: nobody compares a
    # wall clock to it — the detector watches for the value to CHANGE), so
    # the wall-clock read here is deliberate, not a discipline hole.
    return datetime.datetime.now(  # nexuslint: disable=NX-CLOCK001
        datetime.timezone.utc
    ).isoformat(timespec="microseconds")


@dataclass
class HeartbeatLease:
    """Typed view over a heartbeat ConfigMap's data."""

    template: str
    namespace: str
    holder: str = ""
    renew_time: str = ""
    step: int = 0
    ttl_seconds: float = 15.0
    phase: str = PHASE_RUNNING

    @property
    def done(self) -> bool:
        return self.phase == PHASE_DONE

    @classmethod
    def from_config_map(cls, cm: ConfigMap) -> "HeartbeatLease":
        data = cm.data or {}
        name = cm.metadata.name
        template = name[len(HB_PREFIX):] if name.startswith(HB_PREFIX) else name
        try:
            step = int(data.get("step", "0") or 0)
        except ValueError:
            step = 0
        try:
            ttl = float(data.get("ttlSeconds", "15") or 15)
        except ValueError:
            ttl = 15.0
        return cls(
            template=template,
            namespace=cm.metadata.namespace,
            holder=data.get("holder", ""),
            renew_time=data.get("renewTime", ""),
            step=step,
            ttl_seconds=ttl,
            phase=data.get("phase", PHASE_RUNNING) or PHASE_RUNNING,
        )


def list_heartbeats(store, namespace: Optional[str] = None) -> List[HeartbeatLease]:
    """One label-filtered LIST per probe — the detector's only read. Any
    store error propagates to the caller: the detector counts it as an
    API-unreachable observation, NOT as lease expiry (the two failure
    modes have different confirmation deadlines and different planner
    responses)."""
    return [
        HeartbeatLease.from_config_map(cm)
        for cm in store.list(
            ConfigMap.KIND, namespace, label_selector={LABEL_HEARTBEAT: "true"}
        )
    ]


class LeaseRenewer:
    """Worker-side heartbeat writer.

    ``renew(step)`` is called at every step boundary (Trainer ``on_step``)
    but self-throttles to one write per ``ttl/3`` seconds so sub-millisecond
    CPU steps don't turn the shard API into a write firehose — three renew
    opportunities per deadline window is the classic lease margin
    (leaderelect.py uses the same 15s/5s ratio).

    Renewal is best-effort by design: one failed or skipped write is
    exactly what the detector's flap suppression absorbs. Only repeated
    silence (``suspect_misses`` full TTL windows) confirms a failure.
    """

    def __init__(
        self,
        store,
        namespace: str,
        template_name: str,
        holder: str = "",
        ttl_seconds: float = 15.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.store = store
        self.namespace = namespace
        self.name = heartbeat_name(template_name)
        self.holder = holder or f"worker-{threading.get_ident()}"
        self.ttl_seconds = float(ttl_seconds)
        self._min_interval = self.ttl_seconds / 3.0
        # injectable clock (the detector's pattern) drives the write
        # throttle, so throttle behavior unit-tests without sleeps
        self._clock = clock
        self._last_renew = 0.0
        self._frozen = False
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ writes
    def renew(self, step: int) -> bool:
        """Renew the lease if the throttle window has elapsed. Returns True
        when a write was attempted (successful or not)."""
        now = self._clock()
        with self._lock:
            if self._frozen:
                return False
            if now - self._last_renew < self._min_interval:
                return False
            self._last_renew = now
        self._write(step, PHASE_RUNNING)
        return True

    def complete(self, step: int = -1) -> None:
        """Graceful-completion marker: a final write with phase=done so the
        detector never misreads a finished job's silence as a failure."""
        with self._lock:
            if self._frozen:
                return
        self._write(step, PHASE_DONE)

    def _write(self, step: int, phase: str) -> None:
        from nexus_tpu.cluster.store import ConflictError, NotFoundError

        data = {
            "holder": self.holder,
            "renewTime": _now_str(),
            "ttlSeconds": str(self.ttl_seconds),
            "phase": phase,
        }
        if step >= 0:
            data["step"] = str(int(step))
        for _ in range(2):  # one conflict retry; then give up until next tick
            try:
                existing = self.store.get(ConfigMap.KIND, self.namespace, self.name)
            except NotFoundError:
                existing = None
            except Exception:  # noqa: BLE001 — liveness writes must not kill training
                logger.debug("heartbeat get failed", exc_info=True)
                return
            try:
                if existing is None:
                    self.store.create(ConfigMap(
                        metadata=ObjectMeta(
                            name=self.name,
                            namespace=self.namespace,
                            labels={LABEL_HEARTBEAT: "true"},
                        ),
                        data=data,
                    ))
                else:
                    if (existing.data or {}).get("frozen") == "true":
                        # chaos hook: a frozen lease is never renewed again —
                        # the injected "worker wedged" condition
                        with self._lock:
                            self._frozen = True
                        return
                    updated = existing.deepcopy()
                    if "step" not in data and "step" in (existing.data or {}):
                        data["step"] = existing.data["step"]
                    updated.data = data
                    updated.metadata.labels[LABEL_HEARTBEAT] = "true"
                    self.store.update(updated)
                return
            except ConflictError:
                continue  # re-get and retry once
            except Exception:  # noqa: BLE001
                logger.debug("heartbeat write failed", exc_info=True)
                return


def freeze_heartbeat(store, namespace: str, template_name: str) -> None:
    """Chaos hook ("expire lease"): mark the heartbeat frozen so the worker's
    renewer stops touching it and the detector sees it expire — a wedged
    worker simulated without killing anything."""
    from nexus_tpu.cluster.store import NotFoundError

    name = heartbeat_name(template_name)
    try:
        cm = store.get(ConfigMap.KIND, namespace, name)
    except NotFoundError:
        return
    updated = cm.deepcopy()
    updated.data = dict(updated.data or {}, frozen="true")
    store.update(updated)
