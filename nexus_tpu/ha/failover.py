"""Failover planner: confirmed failure → re-place → checkpoint-exact resume.

The FailoverManager owns the probe loop that feeds the per-shard
:class:`~nexus_tpu.ha.detector.FailureDetector` and executes its verdicts:

  * **lease expired** (worker dead, shard API fine): compute the restore
    point from the template's checkpoint directory
    (``train.checkpoint.latest_step`` — durable steps only, partial saves
    excluded), stamp it on the template as the restore-step annotation
    (materializer → ``NEXUS_RESTORE_STEP``), delete the dead Job and the
    stale heartbeat on the failed shard, evict the template's sticky home
    so placement re-runs *excluding* the shard it just died on, and
    enqueue the template — the normal reconcile then re-materializes it on
    a healthy shard.
  * **shard API unreachable**: mark the shard unhealthy (placement skips
    it; ``_remove_from_unselected_shards`` defers its cleanup), then fail
    over every template homed there the same way — except dead Jobs are
    *abandoned*, not deleted (the API is down; provenance labels let the
    normal reconcile prune them when the shard returns).
  * **shard recovered**: mark healthy, drop the shard's WriteSkipCache
    entries (a reconnected shard may have lost state the cache still
    believes is written), and enqueue every template so the level-
    triggered reconcile re-converges it.

Telemetry: ``shard_healthy`` (per shard), ``failovers_total``,
``failover_detection_seconds``, ``failover_steps_lost``.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

from nexus_tpu.api.template import NexusAlgorithmTemplate
from nexus_tpu.cluster.store import ConflictError, NotFoundError
from nexus_tpu.ha.detector import (
    EVENT_LEASE_EXPIRED,
    EVENT_LEASE_RECOVERED,
    EVENT_SHARD_RECOVERED,
    EVENT_SHARD_UNHEALTHY,
    DetectorEvent,
    FailureDetector,
)
from nexus_tpu.ha.lease import HeartbeatLease, heartbeat_name, list_heartbeats
from nexus_tpu.utils.telemetry import (
    METRIC_FAILOVER_DETECTION_SECONDS,
    METRIC_FAILOVER_STEPS_LOST,
    METRIC_FAILOVERS_TOTAL,
    METRIC_SHARD_HEALTHY,
)

logger = logging.getLogger("nexus_tpu.ha")

REASON_FAILOVER = "FailedOver"
REASON_SHARD_UNHEALTHY = "ShardUnhealthy"


@dataclass
class FailoverConfig:
    """Detector/planner tuning knobs (helm: controller.failover*)."""

    heartbeat_ttl: float = 15.0
    probe_interval: float = 5.0
    suspect_misses: int = 2
    api_failure_threshold: int = 3
    backoff_max: float = 60.0
    recovery_probes: int = 2


class FailoverManager:
    """Probe loop + planner, owned by (and wired through) the Controller."""

    def __init__(self, controller, config: Optional[FailoverConfig] = None,
                 clock=time.monotonic):
        self.controller = controller
        self.config = config or FailoverConfig()
        self.detector = FailureDetector(
            ttl_seconds=self.config.heartbeat_ttl,
            suspect_misses=self.config.suspect_misses,
            api_failure_threshold=self.config.api_failure_threshold,
            probe_interval=self.config.probe_interval,
            backoff_max=self.config.backoff_max,
            recovery_probes=self.config.recovery_probes,
            clock=clock,
        )
        self.clock = clock
        self.failovers_total = 0
        self._next_probe: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        for shard in self.controller.shards:
            self.controller.statsd.gauge(
                METRIC_SHARD_HEALTHY, 1.0, tags=[f"shard:{shard.name}"]
            )
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="nexus-failover"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------ probe loop
    def _run(self) -> None:
        tick = max(0.02, min(self.config.probe_interval / 4.0, 0.5))
        while not self._stop.wait(tick):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — the monitor must outlive bugs
                logger.exception("failover probe iteration failed")

    def probe_once(self) -> None:
        """Probe every shard whose (backoff-aware) deadline has passed."""
        now = self.clock()
        for shard in self.controller.shards:
            if now < self._next_probe.get(shard.name, 0.0):
                continue
            try:
                heartbeats = list_heartbeats(shard.store)
            except Exception as e:  # noqa: BLE001 — API outage is an observation
                events = self.detector.observe_api_error(shard.name, e)
            else:
                events = self.detector.observe(shard.name, heartbeats)
            self._next_probe[shard.name] = (
                self.clock() + self.detector.next_probe_delay(shard.name)
            )
            for event in events:
                self._handle(shard, event)

    # --------------------------------------------------------------- planner
    def _handle(self, shard, event: DetectorEvent) -> None:
        if event.kind == EVENT_LEASE_EXPIRED:
            logger.warning(
                "heartbeat lease for %s/%s expired on shard %s "
                "(confirmed after %.2fs, last step %d)",
                event.lease.namespace, event.lease.template, shard.name,
                event.detection_seconds, event.lease.step,
            )
            self._fail_over_template(shard, event.lease, event, api_ok=True)
        elif event.kind == EVENT_SHARD_UNHEALTHY:
            logger.warning(
                "shard %s API unreachable (confirmed after %.2fs); "
                "excluding from placement and failing its workloads over",
                shard.name, event.detection_seconds,
            )
            self.controller.set_shard_health(shard.name, False)
            self.controller.statsd.gauge(
                METRIC_SHARD_HEALTHY, 0.0, tags=[f"shard:{shard.name}"]
            )
            from nexus_tpu.ha.serve_failover import (
                serve_heartbeat_template,
            )

            for template in self._templates_on_shard(shard.name):
                # the detector's last observation carries the real progress
                # (step) — fabricating a fresh lease would report 0 steps
                # lost for every API-outage failover. A serve template's
                # engine renews under the serve-prefixed key, so check
                # both before giving up.
                lease = self.detector.last_heartbeat(
                    shard.name, template.metadata.namespace,
                    template.metadata.name,
                ) or self.detector.last_heartbeat(
                    shard.name, template.metadata.namespace,
                    serve_heartbeat_template(template.metadata.name),
                ) or HeartbeatLease(
                    template=template.metadata.name,
                    namespace=template.metadata.namespace,
                )
                self._fail_over_template(shard, lease, event, api_ok=False)
        elif event.kind == EVENT_SHARD_RECOVERED:
            logger.info("shard %s recovered; re-converging", shard.name)
            self.controller.set_shard_health(shard.name, True)
            self.controller.statsd.gauge(
                METRIC_SHARD_HEALTHY, 1.0, tags=[f"shard:{shard.name}"]
            )
            # a reconnected shard may have lost state the write-skip cache
            # still believes is written — every entry for it is suspect
            self.controller.write_skip_cache.invalidate_shard(shard.name)
            for template in self.controller.template_lister.list(None):
                self.controller.enqueue_resource(template)
        elif event.kind == EVENT_LEASE_RECOVERED:
            logger.info(
                "heartbeat for %s/%s on shard %s resumed renewing",
                event.lease.namespace, event.lease.template, shard.name,
            )

    def _templates_on_shard(self, shard_name: str):
        out = []
        for template in self.controller.template_lister.list(None):
            if template.spec.runtime is None:
                continue
            if template.status.workload_phase == "Succeeded":
                # a completed workload has nothing to fail over — re-running
                # it on another shard would burn TPU on a finished job
                continue
            synced = template.status.synced_to_clusters or []
            home = self.controller.home_of(
                template.metadata.namespace, template.metadata.name
            )
            if shard_name in synced or home == shard_name:
                out.append(template)
        return out

    def _fail_over_template(self, shard, lease: HeartbeatLease,
                            event: DetectorEvent, api_ok: bool) -> None:
        from nexus_tpu.controller.events import EVENT_TYPE_WARNING
        from nexus_tpu.ha.serve_failover import (
            is_serve_lease,
            strip_serve_prefix,
        )

        # a serving engine's lease carries the ``serve-`` infix
        # (hb-serve-<template>, ha/serve_failover.py) — the workload to
        # re-place is the underlying template either way: a re-placed
        # serve template re-runs its deterministic queue, and in-process
        # drain/requeue (committed-token recovery) is the
        # ServeFailoverPlanner's job, not this planner's. Resolution
        # tries the lease's OWN name first so a workload that happens to
        # be literally named ``serve-<x>`` is never misrouted onto
        # ``<x>``; only an unresolved serve-prefixed lease falls back to
        # the stripped name.
        template = None
        workload = lease.template
        try:
            template = self.controller.template_lister.get(
                lease.namespace, workload
            )
        except NotFoundError:
            if is_serve_lease(lease.template):
                workload = strip_serve_prefix(lease.template)
                try:
                    template = self.controller.template_lister.get(
                        lease.namespace, workload
                    )
                except NotFoundError:
                    template = None
        if template is None:
            # template gone (deleted mid-run): just clean the stale lease
            if api_ok:
                self._cleanup_failed_shard(shard, lease, workload)
            return
        if template.spec.runtime is None:
            return
        home = self.controller.home_of(
            template.metadata.namespace, template.metadata.name
        )
        synced = template.status.synced_to_clusters or []
        if (
            template.status.workload_phase == "Succeeded"
            or (home is not None and home != shard.name
                and shard.name not in synced)
        ):
            # stale lease: the workload finished, or it was already failed
            # over elsewhere and this shard's abandoned heartbeat only
            # expired now (e.g. the shard just recovered from an outage).
            # Failing over a healthy/finished workload would re-run it —
            # just reap the leftovers.
            if api_ok:
                self._cleanup_failed_shard(shard, lease, workload)
            return

        restore_step = self._restore_step(template)
        steps_lost = max(lease.step - (restore_step or 0), 0)
        self.failovers_total += 1
        self.controller.statsd.gauge(
            METRIC_FAILOVERS_TOTAL, self.failovers_total
        )
        self.controller.statsd.gauge(
            METRIC_FAILOVER_DETECTION_SECONDS, event.detection_seconds,
            tags=[f"shard:{shard.name}"],
        )
        self.controller.statsd.gauge(
            METRIC_FAILOVER_STEPS_LOST, steps_lost,
            tags=[f"template:{template.metadata.name}"],
        )

        # FIRST: placement must not hand the job back to the shard it died
        # on — and every write below (annotation, job delete) can trigger a
        # concurrent reconcile, so the eviction has to land before any of
        # them or a racing reconcile re-places on the dead shard
        self.controller.evict_home(
            template.metadata.namespace, template.metadata.name, shard.name
        )
        if restore_step is not None:
            template = self._annotate_restore_step(template, restore_step) or template
        if api_ok:
            # worker dead but shard API up: reap the dead Job so it stops
            # holding TPU, and the stale heartbeat so the detector forgets it
            self._cleanup_failed_shard(shard, lease, workload)
        self.controller.recorder.event(
            template, EVENT_TYPE_WARNING, REASON_FAILOVER,
            f"Workload on shard {shard.name!r} "
            f"{'lost its worker (lease expired)' if api_ok else 'abandoned (shard API unreachable)'}"
            "; re-placing with restore step "
            f"{restore_step if restore_step is not None else 'none (fresh start)'}"
            f" ({steps_lost} steps lost)",
        )
        self.controller.enqueue_resource(template)

    # ------------------------------------------------------------- mechanics
    @staticmethod
    def _restore_step(template: NexusAlgorithmTemplate) -> Optional[int]:
        ck = template.spec.runtime.checkpoint
        if not (ck.enabled and ck.directory):
            return None
        from nexus_tpu.train.checkpoint import latest_step

        return latest_step(ck.directory)

    def _annotate_restore_step(
        self, template: NexusAlgorithmTemplate, step: int
    ) -> Optional[NexusAlgorithmTemplate]:
        from nexus_tpu.runtime.materializer import ANNOTATION_RESTORE_STEP

        for _ in range(3):  # optimistic-concurrency retries
            try:
                fresh = self.controller.store.get(
                    NexusAlgorithmTemplate.KIND,
                    template.metadata.namespace, template.metadata.name,
                )
            except NotFoundError:
                return None
            if fresh.metadata.annotations.get(ANNOTATION_RESTORE_STEP) == str(step):
                return fresh  # already stamped (repeat confirmation)
            updated = fresh.deepcopy()
            updated.metadata.annotations[ANNOTATION_RESTORE_STEP] = str(step)
            try:
                stored = self.controller.store.update(updated)
            except ConflictError:
                continue
            self.controller.template_lister._set_if_newer(stored)
            return stored
        logger.warning(
            "could not stamp restore-step annotation on %s/%s (conflicts); "
            "the re-placed worker will auto-resume from latest instead",
            template.metadata.namespace, template.metadata.name,
        )
        return None

    def _cleanup_failed_shard(self, shard, lease: HeartbeatLease,
                              workload: Optional[str] = None) -> None:
        """Best-effort: delete the dead Jobs + stale heartbeat on the failed
        shard (lease-expiry path only — the shard API is known reachable).
        ``workload`` is the RESOLVED template name the lease belongs to
        (the caller's collision-safe resolution); Jobs are label-selected
        by it, while the heartbeat ConfigMap keeps the lease's own
        (possibly serve-prefixed) name."""
        from nexus_tpu.api.types import (
            CONTROLLER_APP_NAME,
            ConfigMap,
            LABEL_CONTROLLER_APP,
        )
        from nexus_tpu.api.workload import Job
        from nexus_tpu.runtime.materializer import LABEL_TEMPLATE

        selector = {
            LABEL_CONTROLLER_APP: CONTROLLER_APP_NAME,
            LABEL_TEMPLATE: workload or lease.template,
        }
        try:
            for job in shard.store.list(
                Job.KIND, lease.namespace, label_selector=selector
            ):
                try:
                    shard.store.delete(Job.KIND, job.metadata.namespace,
                                       job.metadata.name)
                except NotFoundError:
                    pass
            shard.store.delete(
                ConfigMap.KIND, lease.namespace, heartbeat_name(lease.template)
            )
        except NotFoundError:
            pass
        except Exception:  # noqa: BLE001 — cleanup is advisory
            logger.debug("failed-shard cleanup on %s incomplete", shard.name,
                         exc_info=True)
