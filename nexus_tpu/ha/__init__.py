"""Shard health & job failover: heartbeat leases, failure detection, and
checkpoint-resume migration across shard clusters (TPU slice pools).

Layer map:
  lease.py    — worker-side heartbeat protocol (ConfigMap-backed)
  detector.py — per-shard deadline failure detector (flap-suppressed,
                API-unreachable vs worker-lease-expired)
  failover.py — planner: confirmed failure → re-place excluding unhealthy
                shards → resume from the latest durable checkpoint

See docs/failover.md for the protocol, tuning knobs, and runbook.
"""

from nexus_tpu.ha.detector import (
    API_UNREACHABLE,
    EVENT_LEASE_EXPIRED,
    EVENT_LEASE_RECOVERED,
    EVENT_SHARD_RECOVERED,
    EVENT_SHARD_UNHEALTHY,
    EXPIRED,
    FRESH,
    HEALTHY,
    SUSPECT,
    DetectorEvent,
    FailureDetector,
)
from nexus_tpu.ha.failover import FailoverConfig, FailoverManager
from nexus_tpu.ha.lease import (
    LABEL_HEARTBEAT,
    HeartbeatLease,
    LeaseRenewer,
    freeze_heartbeat,
    heartbeat_name,
    list_heartbeats,
)

__all__ = [
    "API_UNREACHABLE",
    "EVENT_LEASE_EXPIRED",
    "EVENT_LEASE_RECOVERED",
    "EVENT_SHARD_RECOVERED",
    "EVENT_SHARD_UNHEALTHY",
    "EXPIRED",
    "FRESH",
    "HEALTHY",
    "SUSPECT",
    "DetectorEvent",
    "FailureDetector",
    "FailoverConfig",
    "FailoverManager",
    "LABEL_HEARTBEAT",
    "HeartbeatLease",
    "LeaseRenewer",
    "freeze_heartbeat",
    "heartbeat_name",
    "list_heartbeats",
]
