"""Shard health & job failover: heartbeat leases, failure detection, and
checkpoint-resume migration across shard clusters (TPU slice pools).

Layer map:
  lease.py          — worker-side heartbeat protocol (ConfigMap-backed)
  detector.py       — per-shard deadline failure detector (flap-suppressed,
                      API-unreachable vs worker-lease-expired)
  failover.py       — planner: confirmed failure → re-place excluding
                      unhealthy shards → resume from the latest durable
                      checkpoint
  serve_failover.py — serve-plane planner: engine heartbeats
                      (hb-serve-<template>), drain-and-requeue with
                      committed tokens preserved, freeze_engine chaos hook

See docs/failover.md for the protocol, tuning knobs, and runbook.
"""

from nexus_tpu.ha.detector import (
    API_UNREACHABLE,
    EVENT_LEASE_EXPIRED,
    EVENT_LEASE_RECOVERED,
    EVENT_SHARD_RECOVERED,
    EVENT_SHARD_UNHEALTHY,
    EXPIRED,
    FRESH,
    HEALTHY,
    SUSPECT,
    DetectorEvent,
    FailureDetector,
)
from nexus_tpu.ha.failover import FailoverConfig, FailoverManager
from nexus_tpu.ha.serve_failover import (
    SERVE_HB_PREFIX,
    RequeueEntry,
    ServeEngineSupervisor,
    ServeFailoverPlanner,
    freeze_engine,
    is_serve_lease,
    serve_heartbeat_template,
    strip_serve_prefix,
)
from nexus_tpu.ha.lease import (
    LABEL_HEARTBEAT,
    HeartbeatLease,
    LeaseRenewer,
    freeze_heartbeat,
    heartbeat_name,
    list_heartbeats,
)

__all__ = [
    "API_UNREACHABLE",
    "EVENT_LEASE_EXPIRED",
    "EVENT_LEASE_RECOVERED",
    "EVENT_SHARD_RECOVERED",
    "EVENT_SHARD_UNHEALTHY",
    "EXPIRED",
    "FRESH",
    "HEALTHY",
    "SUSPECT",
    "DetectorEvent",
    "FailureDetector",
    "FailoverConfig",
    "FailoverManager",
    "LABEL_HEARTBEAT",
    "SERVE_HB_PREFIX",
    "HeartbeatLease",
    "LeaseRenewer",
    "RequeueEntry",
    "ServeEngineSupervisor",
    "ServeFailoverPlanner",
    "freeze_engine",
    "freeze_heartbeat",
    "heartbeat_name",
    "is_serve_lease",
    "list_heartbeats",
    "serve_heartbeat_template",
    "strip_serve_prefix",
]
