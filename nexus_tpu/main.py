"""Process bootstrap — the ``main()`` equivalent (reference: main.go:35-109).

Wires: signals → config → logging/statsd → controller-cluster store +
informer factories → shard loading → controller construction → run.

The controller cluster itself is resolved the same way shards are: a
``controller_config_path`` pointing at a kubeconfig uses the stdlib
Kubernetes REST backend (cluster/kubeapi.py); empty path uses an in-process
local store — the local / test deployment mode (BASELINE configs #1/#2).
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Optional

from nexus_tpu.cluster.store import ClusterStore
from nexus_tpu.controller.controller import Controller
from nexus_tpu.shards.loader import get_local_store, load_shards
from nexus_tpu.utils.config import AppConfig, load_config
from nexus_tpu.utils.signals import CancelToken, setup_signal_handler
from nexus_tpu.utils.telemetry import configure_logger, with_statsd

logger = logging.getLogger("nexus_tpu.main")


def build_controller(config: AppConfig, controller_store: Optional[ClusterStore] = None) -> Controller:
    if controller_store is None:
        if config.controller_config_path:
            from nexus_tpu.cluster.kube import KubeClusterStore  # noqa: PLC0415

            controller_store = KubeClusterStore(
                "controller", config.controller_config_path, config.controller_namespace
            )
        else:
            controller_store = get_local_store("controller")

    shards = (
        load_shards(config.alias, config.shard_config_path, config.controller_namespace)
        if config.shard_config_path
        else []
    )
    failover = None
    if config.failover_enabled:
        from nexus_tpu.ha.failover import FailoverConfig

        failover = FailoverConfig(
            heartbeat_ttl=config.heartbeat_ttl_seconds,
            probe_interval=config.failover_probe_interval_seconds,
            suspect_misses=config.failover_suspect_misses,
            api_failure_threshold=config.failover_api_failure_threshold,
            backoff_max=config.failover_backoff_max_seconds,
            recovery_probes=config.failover_recovery_probes,
        )
    return Controller(
        controller_store=controller_store,
        shards=shards,
        failure_rate_base_delay=config.failure_rate_base_delay,
        failure_rate_max_delay=config.failure_rate_max_delay,
        rate_limit_elements_per_second=config.rate_limit_elements_per_second,
        rate_limit_elements_burst=config.rate_limit_elements_burst,
        use_finalizers=config.use_finalizers,
        resync_period=config.resync_period_seconds,
        queue_backend=config.queue_backend,
        shard_sync_workers=config.shard_sync_workers,
        write_skip_cache=config.write_skip_cache,
        failover=failover,
    )


def main(argv: Optional[list] = None, cancel: Optional[CancelToken] = None) -> int:
    parser = argparse.ArgumentParser(prog="nexus-tpu-controller")
    parser.add_argument("--config", default=None, help="path to appconfig yaml")
    args = parser.parse_args(argv)

    if cancel is None:
        cancel = setup_signal_handler()
    config = load_config(AppConfig, config_path=args.config)
    configure_logger(
        config.log_level,
        extra_tags={"alias": config.alias},
        datadog_api_key=config.datadog_api_key,
        datadog_site=config.datadog_site,
        datadog_endpoint=config.datadog_log_endpoint,
    )
    with_statsd("nexus-tpu", config.statsd_address or None)

    controller = build_controller(config)
    elector = None
    try:
        if config.leader_election:
            # HA mode (beyond the reference's single-Recreate-replica
            # limitation): only the Lease holder runs the reconcile loop;
            # a standby replica idles here until it wins the lease, and a
            # deposed leader stops its workers (the fencing rule)
            import socket as _socket

            from nexus_tpu.controller.leaderelect import LeaderElector

            identity = config.leader_election_identity or (
                f"{_socket.gethostname()}-{os.getpid()}"
            )

            def _started_leading():
                try:
                    controller.run(workers=config.workers)
                    logger.info("controller running (leader)")
                except Exception:
                    # a leader that cannot start reconciling must EXIT so
                    # the Deployment replaces it — idling while holding
                    # the lease would starve the whole fleet
                    logger.exception(
                        "controller failed to start after winning the "
                        "lease; exiting"
                    )
                    cancel.cancel()

            def _lost_leadership():
                # the controller's queue/workers are not restartable after
                # stop(); the correct HA behavior is to EXIT and let the
                # Deployment restart the pod as a fresh standby (the same
                # pattern client-go leader-elected controllers use)
                controller.stop()
                cancel.cancel()

            elector = LeaderElector(
                controller.store,
                lease_name=config.leader_election_lease_name,
                namespace=config.controller_namespace,
                identity=identity,
                lease_duration=config.leader_election_lease_duration,
                renew_period=config.leader_election_renew_period,
                on_started_leading=_started_leading,
                on_stopped_leading=_lost_leadership,
            ).run()
            logger.info(
                "leader election enabled (lease %s, identity %s); "
                "campaigning — reconcile starts if this replica wins",
                config.leader_election_lease_name, identity,
            )
        else:
            controller.run(workers=config.workers)
            logger.info("controller running")
        logger.info("waiting for shutdown signal")
        cancel.wait()
        logger.info("shutting down")
        if elector is not None:
            elector.stop()  # releases the lease; also stops the controller
        else:
            controller.stop()
    finally:
        # close the cluster backends the bootstrap created — ALSO on the
        # failure paths (a cache-sync error raised out of run() has
        # already started watch threads): real-Kubernetes stores run
        # reflector threads that must be cancelled + joined, or an
        # embedding process (the in-process e2e, a notebook) keeps
        # orphaned threads retrying against servers that may be gone
        for store in [controller.store] + [
            s.store for s in controller.shards
        ]:
            close = getattr(store, "close", None)
            if close is not None:
                close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
