"""Device-mesh construction from TPU slice topology + parallelism axes.

The mesh is the root object of the TPU execution model: every sharding in the
framework is a PartitionSpec over these named axes, and XLA lowers the
resulting communication onto ICI (within a slice) / DCN (across slices).

Axis order is chosen for ICI locality: the most communication-intensive axes
(``tensor``, then ``sequence``/``expert``) are placed innermost so their
collectives ride neighboring ICI links; ``pipeline`` and ``data`` are
outermost since their communication (activations between stages, gradient
all-reduce) tolerates DCN hops in multislice deployments.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from nexus_tpu.api.runtime_spec import ParallelismSpec

# Outer → inner. Keep in sync with ParallelismSpec fields.
AXES: Tuple[str, ...] = ("pipeline", "data", "fsdp", "expert", "sequence", "tensor")


@dataclass(frozen=True)
class MeshPlan:
    """A concrete axis-size assignment (product == device count)."""

    pipeline: int = 1
    data: int = 1
    fsdp: int = 1
    expert: int = 1
    sequence: int = 1
    tensor: int = 1

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXES)

    def total(self) -> int:
        return math.prod(self.shape)

    @classmethod
    def from_parallelism(cls, p: ParallelismSpec) -> "MeshPlan":
        return cls(
            pipeline=p.pipeline,
            data=p.data,
            fsdp=p.fsdp,
            expert=p.expert,
            sequence=p.sequence,
            tensor=p.tensor,
        )


def split_dcn_axes(
    plan_shape: Sequence[int], n_slices: int
) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """Factor a mesh shape into (ici_shape, dcn_shape) for multislice.

    ``dcn_shape`` absorbs the slice count on the outermost axes possible
    (axis order is outer→inner, so pipeline/data — the DCN-tolerant axes —
    are preferred), with ``ici_i * dcn_i == plan_i`` per axis and
    ``prod(dcn) == n_slices``. Raises if the plan can't split that way
    (e.g. all parallelism on an inner axis smaller than the slice count)."""
    remaining = n_slices
    dcn: List[int] = []
    for size in plan_shape:
        g = math.gcd(size, remaining)
        dcn.append(g)
        remaining //= g
    if remaining != 1:
        raise ValueError(
            f"cannot place {n_slices} slices onto mesh shape "
            f"{tuple(plan_shape)}: outer axes only absorb "
            f"{n_slices // remaining}; give the data/fsdp/pipeline axes a "
            "multiple of the slice count"
        )
    ici = tuple(s // d for s, d in zip(plan_shape, dcn))
    return ici, tuple(dcn)


def _hybrid_device_array(
    devices: Sequence, plan_shape: Sequence[int], n_slices: int
) -> np.ndarray:
    """Arrange slice-contiguous ``devices`` into a hybrid ICI/DCN mesh
    array: per mesh axis, the DCN factor is OUTER and the ICI factor inner
    (the create_hybrid_device_mesh layout), so slice boundaries land on the
    outermost strides of the axes that absorbed them.

    Assumes ``devices`` is ordered slice-major (slice 0's devices first) —
    true both for real multislice (process ids are slice-contiguous,
    runtime/worker.py::WorkerIdentity.process_id) and for the CPU
    emulation used in tests."""
    ici, dcn = split_dcn_axes(plan_shape, n_slices)
    arr = np.array(devices).reshape(tuple(dcn) + tuple(ici))
    n = len(plan_shape)
    # interleave (dcn_0, ici_0, dcn_1, ici_1, ...) then merge pairs
    order = []
    for i in range(n):
        order.extend([i, n + i])
    arr = arr.transpose(order)
    return arr.reshape(tuple(plan_shape))


def build_mesh(
    plan: MeshPlan,
    devices: Optional[Sequence] = None,
    n_slices: Optional[int] = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with the framework's named axes.

    ``devices`` defaults to ``jax.devices()``; its length must equal the
    plan's axis product. Size-1 axes are kept in the mesh so PartitionSpecs
    can always reference every logical axis.

    Multislice: when the devices span multiple slices (``slice_index``
    attribute), the mesh is built with ``mesh_utils.create_hybrid_device_mesh``
    so slice boundaries land on the outermost (DCN-tolerant) axes and
    intra-slice neighbors stay adjacent on the inner (ICI) axes.
    ``n_slices`` forces the same hybrid layout when the backend does not
    expose ``slice_index`` (the CPU multislice emulation: N processes
    standing in for slices' hosts, devices ordered slice-major)."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if plan.total() != len(devices):
        raise ValueError(
            f"mesh plan {plan.shape} (product {plan.total()}) does not tile "
            f"{len(devices)} devices"
        )
    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    if len(slice_ids) > 1:
        from jax.experimental import mesh_utils

        ici, dcn = split_dcn_axes(plan.shape, len(slice_ids))
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici, dcn, devices, allow_split_physical_axes=True
        )
        return Mesh(dev_array, AXES)
    if n_slices and n_slices > 1:
        return Mesh(
            _hybrid_device_array(devices, plan.shape, n_slices), AXES
        )
    dev_array = np.array(devices).reshape(plan.shape)
    return Mesh(dev_array, AXES)


def mesh_from_parallelism(
    p: ParallelismSpec, devices: Optional[Sequence] = None
) -> Mesh:
    return build_mesh(MeshPlan.from_parallelism(p), devices)


def plan_for_devices(
    n: int,
    prefer: Sequence[str] = ("fsdp", "tensor", "data"),
    max_tensor: int = 8,
) -> MeshPlan:
    """Heuristic plan for ``n`` devices when the user gave none.

    Factorizes ``n`` onto the preferred axes: tensor parallelism is capped
    (TP beyond one host's ICI neighborhood wastes bandwidth), the remainder
    goes to fsdp, then pure data parallelism."""
    sizes = {a: 1 for a in AXES}
    remaining = n
    if "tensor" in prefer and remaining > 1:
        # largest power-of-two divisor of n, capped
        t = 1
        while t * 2 <= max_tensor and remaining % (t * 2) == 0:
            t *= 2
        sizes["tensor"] = t
        remaining //= t
    if "fsdp" in prefer and remaining > 1:
        sizes["fsdp"] = remaining
        remaining = 1
    if remaining > 1:
        sizes["data"] = remaining
    return MeshPlan(**{a: sizes[a] for a in AXES})


def validate_plan_against_topology(plan: MeshPlan, chips: int) -> List[str]:
    errs = []
    if plan.total() != chips:
        errs.append(
            f"mesh plan product {plan.total()} != slice chip count {chips}"
        )
    return errs
