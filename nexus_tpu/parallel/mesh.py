"""Device-mesh construction from TPU slice topology + parallelism axes.

The mesh is the root object of the TPU execution model: every sharding in the
framework is a PartitionSpec over these named axes, and XLA lowers the
resulting communication onto ICI (within a slice) / DCN (across slices).

Axis order is chosen for ICI locality: the most communication-intensive axes
(``tensor``, then ``sequence``/``expert``) are placed innermost so their
collectives ride neighboring ICI links; ``pipeline`` and ``data`` are
outermost since their communication (activations between stages, gradient
all-reduce) tolerates DCN hops in multislice deployments.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from nexus_tpu.api.runtime_spec import ParallelismSpec

# Outer → inner. Keep in sync with ParallelismSpec fields.
AXES: Tuple[str, ...] = ("pipeline", "data", "fsdp", "expert", "sequence", "tensor")


@dataclass(frozen=True)
class MeshPlan:
    """A concrete axis-size assignment (product == device count)."""

    pipeline: int = 1
    data: int = 1
    fsdp: int = 1
    expert: int = 1
    sequence: int = 1
    tensor: int = 1

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXES)

    def total(self) -> int:
        return math.prod(self.shape)

    @classmethod
    def from_parallelism(cls, p: ParallelismSpec) -> "MeshPlan":
        return cls(
            pipeline=p.pipeline,
            data=p.data,
            fsdp=p.fsdp,
            expert=p.expert,
            sequence=p.sequence,
            tensor=p.tensor,
        )


def build_mesh(plan: MeshPlan, devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with the framework's named axes.

    ``devices`` defaults to ``jax.devices()``; its length must equal the
    plan's axis product. Size-1 axes are kept in the mesh so PartitionSpecs
    can always reference every logical axis."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if plan.total() != len(devices):
        raise ValueError(
            f"mesh plan {plan.shape} (product {plan.total()}) does not tile "
            f"{len(devices)} devices"
        )
    dev_array = np.array(devices).reshape(plan.shape)
    return Mesh(dev_array, AXES)


def mesh_from_parallelism(
    p: ParallelismSpec, devices: Optional[Sequence] = None
) -> Mesh:
    return build_mesh(MeshPlan.from_parallelism(p), devices)


def plan_for_devices(
    n: int,
    prefer: Sequence[str] = ("fsdp", "tensor", "data"),
    max_tensor: int = 8,
) -> MeshPlan:
    """Heuristic plan for ``n`` devices when the user gave none.

    Factorizes ``n`` onto the preferred axes: tensor parallelism is capped
    (TP beyond one host's ICI neighborhood wastes bandwidth), the remainder
    goes to fsdp, then pure data parallelism."""
    sizes = {a: 1 for a in AXES}
    remaining = n
    if "tensor" in prefer and remaining > 1:
        # largest power-of-two divisor of n, capped
        t = 1
        while t * 2 <= max_tensor and remaining % (t * 2) == 0:
            t *= 2
        sizes["tensor"] = t
        remaining //= t
    if "fsdp" in prefer and remaining > 1:
        sizes["fsdp"] = remaining
        remaining = 1
    if remaining > 1:
        sizes["data"] = remaining
    return MeshPlan(**{a: sizes[a] for a in AXES})


def validate_plan_against_topology(plan: MeshPlan, chips: int) -> List[str]:
    errs = []
    if plan.total() != chips:
        errs.append(
            f"mesh plan product {plan.total()} != slice chip count {chips}"
        )
    return errs
