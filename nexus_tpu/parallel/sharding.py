"""Sharding rules: logical tensor dimensions → mesh PartitionSpecs.

Models annotate parameters with *logical* dimension names ("vocab", "embed",
"mlp", "heads", …); this module maps them onto physical mesh axes. The map
implements the standard FSDP+TP layout (How-to-Scale-Your-Model recipe):

  * weight matrices split their input/output dims over ``tensor`` (megatron
    TP) and shard the remaining dim over ``fsdp`` (ZeRO-3 parameter
    sharding — XLA all-gathers just-in-time and reduce-scatters gradients);
  * activations shard batch over ``(data, fsdp)`` (+ ``expert`` when it is a
    pure-data axis for non-MoE tensors), sequence over ``sequence``
    (context parallelism), and attention heads / mlp features over
    ``tensor``;
  * MoE expert weights put their leading expert dim on ``expert``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical dim name → physical mesh axis (or tuple of axes)
DEFAULT_LOGICAL_RULES: Dict[str, Any] = {
    "batch": ("data", "fsdp"),
    "seq": "sequence",
    "vocab": "tensor",
    "embed": "fsdp",
    "mlp": "tensor",
    "heads": "tensor",
    "kv_heads": "tensor",
    "head_dim": None,
    "qkv": "tensor",
    "expert": "expert",
    "norm": None,
    # stacked-layer leading dim: unsharded by default; the runtime remaps it
    # to the 'pipeline' mesh axis when pipeline parallelism is active, so
    # each stage holds only its contiguous layer slice from init onward
    "layer": None,
    None: None,
}


def logical_to_spec(
    logical_dims: Tuple[Optional[str], ...],
    rules: Optional[Dict[str, Any]] = None,
) -> P:
    """("vocab", "embed") → PartitionSpec('tensor', 'fsdp')."""
    rules = rules or DEFAULT_LOGICAL_RULES
    return P(*(rules.get(d) for d in logical_dims))


def named_sharding(mesh: Mesh, *dims: Optional[str], rules=None) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(dims, rules))


def batch_spec(sequence_sharded: bool = False) -> P:
    """Activation sharding for a (batch, seq, ...) tensor."""
    return P(("data", "fsdp"), "sequence" if sequence_sharded else None)


def repin_tree(tree, template):
    """device_put every leaf whose sharding differs from the template's.

    ``template`` mirrors ``tree`` with either arrays (their ``.sharding`` is
    the target) or ``jax.sharding.Sharding`` objects at the leaves. Used to
    normalize device assignments: checkpoint restores can bring replicated
    scalars back single-device, and freshly-created optimizer leaves can
    land off-mesh — a jitted step rejects such mixed states."""
    import jax

    def _one(x, t):
        target = (
            t
            if isinstance(t, jax.sharding.Sharding)
            else getattr(t, "sharding", None)
        )
        if target is not None and getattr(x, "sharding", None) != target:
            return jax.device_put(x, target)
        return x

    return jax.tree_util.tree_map(_one, tree, template)


def shard_params(params, logical_tree, mesh: Mesh, rules=None):
    """Device-put a parameter pytree according to its logical-dims pytree.

    ``logical_tree`` mirrors ``params`` with tuples of logical dim names at
    the leaves (each model family exposes ``logical_axes(config)``)."""
    def _place(p, dims):
        return jax.device_put(p, NamedSharding(mesh, logical_to_spec(dims, rules)))

    return jax.tree_util.tree_map(
        _place, params, logical_tree, is_leaf=lambda x: x is None
    )


def sharding_tree(logical_tree, mesh: Mesh, rules=None):
    """Logical-dims pytree → NamedSharding pytree (for jit in/out_shardings)."""
    return jax.tree_util.tree_map(
        lambda dims: NamedSharding(mesh, logical_to_spec(dims, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) or x is None,
    )


def get_shard_map():
    """The shard_map entry point for this jax version."""
    try:
        return jax.shard_map
    except AttributeError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map

        return shard_map


def shard_map_unchecked_kwargs() -> Dict[str, bool]:
    """The kwargs that disable shard_map's replication/varying-manual-axes
    checking, across the jax versions that renamed the flag
    (``check_rep`` → ``check_vma``). Needed wherever a body's outputs are
    intentionally stage/device-varying (pipeline schedules) or where pallas
    lowering mixes varying and invariant operands (ring attention flash
    blocks)."""
    import inspect

    name = (
        "check_vma"
        if "check_vma" in inspect.signature(get_shard_map()).parameters
        else "check_rep"
    )
    return {name: False}
