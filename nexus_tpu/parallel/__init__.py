"""TPU parallelism layer: device meshes, sharding rules, sharded train steps.

This layer is NEW relative to the reference (which has no accelerator code —
SURVEY.md §2c) and implements the BASELINE north star the TPU way: pick a
mesh, annotate shardings, let XLA insert collectives over ICI/DCN.
"""

from nexus_tpu.parallel.mesh import (
    AXES,
    MeshPlan,
    build_mesh,
    mesh_from_parallelism,
    plan_for_devices,
)
from nexus_tpu.parallel.sharding import (
    batch_spec,
    logical_to_spec,
    named_sharding,
    shard_params,
)

__all__ = [
    "AXES",
    "MeshPlan",
    "build_mesh",
    "mesh_from_parallelism",
    "plan_for_devices",
    "batch_spec",
    "logical_to_spec",
    "named_sharding",
    "shard_params",
]
