"""Pipeline parallelism: GPipe-style microbatched stages over the
``pipeline`` mesh axis.

NOT PRESENT in the reference (SURVEY.md §2c — no model code at all); built
TPU-first rather than translated: the model's stacked-layer parameter layout
(models/llama.py) means a "stage" is just a contiguous slice of the stacked
layer dim, so sharding that dim with ``P('pipeline')`` inside ``shard_map``
gives each device its stage's weights with zero reshuffling. The schedule is
the classic bubble-filled GPipe loop:

    ticks t = 0 .. M + S - 2   (M microbatches, S stages)
      * stage 0 injects microbatch t (while t < M);
      * every stage applies its layer slice to its current activation;
      * activations hop stage→stage+1 via ``lax.ppermute`` (ICI/DCN
        neighbor hop — this is why 'pipeline' is the outermost mesh axis,
        parallel/mesh.py);
      * the last stage emits outputs for ticks t >= S-1.

All stages run identical SPMD code (shard_map requirement); stage identity
comes from ``lax.axis_index``. Autodiff flows through ppermute + scan, so
the same forward drives pipelined training (full-activation GPipe; no 1F1B
yet). Output is returned sharded ``P('pipeline')`` on a leading per-stage
dim — reading ``[-1]`` pulls only the last stage's shard, no collective.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    shard_map = jax.shard_map
except AttributeError:  # older jax
    from jax.experimental.shard_map import shard_map


def _pipeline_body(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis: str,
    local_params: Any,
    x_mb: jnp.ndarray,
) -> jnp.ndarray:
    """Per-device pipeline schedule. ``x_mb``: (M, ...) microbatched
    activations (replicated across the pipeline axis); returns (1, M, ...)
    — this stage's row of the per-stage output array."""
    n_stages = lax.axis_size(axis)
    stage = lax.axis_index(axis)
    m = x_mb.shape[0]
    n_ticks = m + n_stages - 1

    buf0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)

    def tick(carry, t):
        buf, out = carry
        # stage 0 injects microbatch t (clamped index; masked past M)
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, m - 1), axis=0, keepdims=False
        )
        live = (t < m).astype(x_mb.dtype)
        x_in = jnp.where(stage == 0, inject * live, buf)

        y = stage_fn(local_params, x_in)

        # last stage records its result at slot t-(S-1) (clamped; ticks
        # before the pipeline fills write into slot 0 and are overwritten
        # by the real slot-0 result at t = S-1)
        slot = jnp.clip(t - (n_stages - 1), 0, m - 1)
        record = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        cur = lax.dynamic_index_in_dim(out, slot, axis=0, keepdims=False)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(record, y, cur), slot, axis=0
        )

        # hop to the next stage (wrap-around hop into stage 0 is ignored —
        # stage 0 always injects)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        buf = lax.ppermute(y, axis, perm)
        return (buf, out), None

    (_, out), _ = lax.scan(tick, (buf0, out0), jnp.arange(n_ticks))
    return out[None]  # (1, M, ...) — per-stage leading dim


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    params: Any,
    x_mb: jnp.ndarray,
    mesh: Mesh,
    *,
    axis: str = "pipeline",
    params_spec: Any = None,
    x_spec: P = None,
) -> jnp.ndarray:
    """Run microbatched activations through pipeline stages.

    ``params`` must have a leading stacked-layer dim divisible by the
    pipeline axis size; it is sharded ``P('pipeline')`` so each device holds
    its stage's contiguous layer slice. ``x_mb`` is (M, ...) microbatches.
    Returns (M, ...) outputs of the final stage (lazily read from the last
    stage's shard)."""
    n_stages = mesh.shape[axis]
    layer_spec = params_spec or jax.tree_util.tree_map(
        lambda _: P(axis), params
    )
    in_x_spec = x_spec or P()
    # the per-stage output keeps whatever sharding the activations carry
    # (e.g. batch over (data, fsdp)), with the stage dim prepended
    x_entries = tuple(in_x_spec) + (None,) * (x_mb.ndim - len(tuple(in_x_spec)))
    kwargs = dict(
        mesh=mesh,
        in_specs=(layer_spec, in_x_spec),
        out_specs=P(axis, *x_entries),
    )
    # replication checking is off: output is intentionally stage-varying
    # (kwarg renamed check_rep → check_vma across jax versions)
    import inspect

    if "check_vma" in inspect.signature(shard_map).parameters:
        kwargs["check_vma"] = False
    else:
        kwargs["check_rep"] = False
    fn = shard_map(functools.partial(_pipeline_body, stage_fn, axis), **kwargs)
    staged = fn(params, x_mb)  # (S, M, ...)
    return staged[n_stages - 1]


# ----------------------------------------------------- llama integration


def llama_pipeline_hidden(
    params: Dict[str, Any],
    cfg,
    tokens: jnp.ndarray,
    mesh: Mesh,
    n_microbatches: int,
) -> jnp.ndarray:
    """Llama trunk with layers pipelined over the 'pipeline' mesh axis:
    tokens (B, S) → final-norm hidden (B, S, d).

    Embedding and the LM head are replicated (cheap vs the layer stack);
    the (B, S) batch is split into M microbatches along batch."""
    from nexus_tpu.models.llama import _block  # stacked-layer block
    from nexus_tpu.ops.norms import rms_norm
    from nexus_tpu.ops.rope import rope_cos_sin

    b, s = tokens.shape
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by microbatches {n_microbatches}")
    n_stages = mesh.shape["pipeline"]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by {n_stages} stages"
        )

    x = params["embed"].astype(cfg.dtype)[tokens]
    x_mb = x.reshape(n_microbatches, b // n_microbatches, s, cfg.d_model)
    cos, sin = rope_cos_sin(s, cfg.head_dim, cfg.rope_theta, dtype=jnp.float32)

    block = lambda h, layer: _block(cfg, h, layer, cos, sin)
    if getattr(cfg, "remat", False):
        # per-layer remat inside the stage: with M microbatches in flight a
        # stage holds M activation sets — rematerializing the block bounds
        # that at M×(layer I/O) instead of M×(full block internals), the
        # GPipe memory knob until a 1F1B schedule lands
        from nexus_tpu.ops.remat import checkpoint_block

        block = checkpoint_block(block, getattr(cfg, "remat_policy", "full"))

    def stage_fn(layers_local, h):
        def body(h, layer):
            return block(h, layer), None

        h, _ = lax.scan(body, h, layers_local)
        return h

    layer_spec = jax.tree_util.tree_map(lambda _: P("pipeline"), params["layers"])
    # microbatch dim replicated; per-microbatch batch dim keeps the data
    # sharding so the data axis parallelizes within each pipeline stage
    y_mb = pipeline_apply(
        stage_fn, params["layers"], x_mb, mesh,
        params_spec=layer_spec, x_spec=P(None, ("data", "fsdp")),
    )
    y = y_mb.reshape(b, s, cfg.d_model)
    return rms_norm(y, params["final_norm"], cfg.norm_eps)


def llama_pipeline_forward(
    params: Dict[str, Any],
    cfg,
    tokens: jnp.ndarray,
    mesh: Mesh,
    n_microbatches: int,
) -> jnp.ndarray:
    """tokens (B, S) → logits (B, S, V) f32 through the GPipe trunk."""
    y = llama_pipeline_hidden(params, cfg, tokens, mesh, n_microbatches)
    return (y @ params["lm_head"]).astype(jnp.float32)


def llama_pipeline_loss(
    params: Dict[str, Any], cfg, batch: Dict[str, jnp.ndarray],
    mesh: Mesh, n_microbatches: int,
) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """GPipe next-token CE; honors ``cfg.ce_chunk`` exactly like the
    non-pipelined loss (models/llama.py::loss_fn)."""
    from nexus_tpu.ops.losses import chunked_softmax_xent, dense_softmax_xent

    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    hidden = llama_pipeline_hidden(params, cfg, inputs, mesh, n_microbatches)
    if getattr(cfg, "ce_chunk", 0) > 0:
        loss = chunked_softmax_xent(
            hidden, params["lm_head"], targets, chunk=cfg.ce_chunk
        )
    else:
        loss = dense_softmax_xent(hidden, params["lm_head"], targets)
    return loss, {"loss": loss, "perplexity": jnp.exp(loss)}
