"""Pipeline parallelism: microbatched stages over the ``pipeline`` mesh
axis, with two schedules — GPipe and 1F1B.

NOT PRESENT in the reference (SURVEY.md §2c — no model code at all); built
TPU-first rather than translated: the model's stacked-layer parameter layout
(models/llama.py, models/gptneox.py) means a "stage" is just a contiguous
slice of the stacked layer dim, so sharding that dim with ``P('pipeline')``
inside ``shard_map`` gives each device its stage's weights with zero
reshuffling.

**GPipe** (``pipeline_apply``) is the classic bubble-filled loop:

    ticks t = 0 .. M + S - 2   (M microbatches, S stages)
      * stage 0 injects microbatch t (while t < M);
      * every stage applies its layer slice to its current activation;
      * activations hop stage→stage+1 via ``lax.ppermute`` (ICI/DCN
        neighbor hop — this is why 'pipeline' is the outermost mesh axis,
        parallel/mesh.py);
      * the last stage emits outputs for ticks t >= S-1.

Autodiff flows through ppermute + scan, so the same forward drives
pipelined training — but the scan saves every tick's activations, so peak
memory grows with M (microbatches).

**1F1B** (``pipeline_1f1b_loss_and_grads``) interleaves one forward with
one backward per tick so a stage holds at most ``2S-1`` in-flight
microbatch *inputs* (a static ring buffer) instead of all M — the
standard schedule's memory bound, independent of microbatch count. The
backward is hand-scheduled (autodiff cannot reorder its own backward):
each stage saves only the microbatch's stage INPUT and rematerializes the
stage forward inside ``jax.vjp`` at backward time (the same recompute cost
as full-block remat). Stage-to-stage activation hops and the reverse
gradient hops are both neighbor ``ppermute``s. The LM head runs inside the
last stage's tick under ``lax.cond`` (other stages skip the compute at
run time), so each microbatch's backward starts the tick after its
forward finishes — no full-batch logits ever materialize.

All stages run identical SPMD code (shard_map requirement); stage identity
comes from ``lax.axis_index``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    shard_map = jax.shard_map
except AttributeError:  # older jax
    from jax.experimental.shard_map import shard_map


def _pipeline_body(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis: str,
    local_params: Any,
    x_mb: jnp.ndarray,
) -> jnp.ndarray:
    """Per-device pipeline schedule. ``x_mb``: (M, ...) microbatched
    activations (replicated across the pipeline axis); returns (1, M, ...)
    — this stage's row of the per-stage output array."""
    n_stages = lax.axis_size(axis)
    stage = lax.axis_index(axis)
    m = x_mb.shape[0]
    n_ticks = m + n_stages - 1

    buf0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)

    def tick(carry, t):
        buf, out = carry
        # stage 0 injects microbatch t (clamped index; masked past M)
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, m - 1), axis=0, keepdims=False
        )
        live = (t < m).astype(x_mb.dtype)
        x_in = jnp.where(stage == 0, inject * live, buf)

        y = stage_fn(local_params, x_in)

        # last stage records its result at slot t-(S-1) (clamped; ticks
        # before the pipeline fills write into slot 0 and are overwritten
        # by the real slot-0 result at t = S-1)
        slot = jnp.clip(t - (n_stages - 1), 0, m - 1)
        record = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        cur = lax.dynamic_index_in_dim(out, slot, axis=0, keepdims=False)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(record, y, cur), slot, axis=0
        )

        # hop to the next stage (wrap-around hop into stage 0 is ignored —
        # stage 0 always injects)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        buf = lax.ppermute(y, axis, perm)
        return (buf, out), None

    (_, out), _ = lax.scan(tick, (buf0, out0), jnp.arange(n_ticks))
    return out[None]  # (1, M, ...) — per-stage leading dim


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    params: Any,
    x_mb: jnp.ndarray,
    mesh: Mesh,
    *,
    axis: str = "pipeline",
    params_spec: Any = None,
    x_spec: P = None,
) -> jnp.ndarray:
    """Run microbatched activations through pipeline stages.

    ``params`` must have a leading stacked-layer dim divisible by the
    pipeline axis size; it is sharded ``P('pipeline')`` so each device holds
    its stage's contiguous layer slice. ``x_mb`` is (M, ...) microbatches.
    Returns (M, ...) outputs of the final stage (lazily read from the last
    stage's shard)."""
    n_stages = mesh.shape[axis]
    layer_spec = params_spec or jax.tree_util.tree_map(
        lambda _: P(axis), params
    )
    in_x_spec = x_spec or P()
    # the per-stage output keeps whatever sharding the activations carry
    # (e.g. batch over (data, fsdp)), with the stage dim prepended
    x_entries = tuple(in_x_spec) + (None,) * (x_mb.ndim - len(tuple(in_x_spec)))
    from nexus_tpu.parallel.sharding import shard_map_unchecked_kwargs

    kwargs = dict(
        mesh=mesh,
        in_specs=(layer_spec, in_x_spec),
        out_specs=P(axis, *x_entries),
        # replication checking off: output is intentionally stage-varying
        **shard_map_unchecked_kwargs(),
    )
    fn = shard_map(functools.partial(_pipeline_body, stage_fn, axis), **kwargs)
    staged = fn(params, x_mb)  # (S, M, ...)
    return staged[n_stages - 1]


# ------------------------------------------------ model-family adapters

#: the one capability matrix: which families each schedule supports.
#: entrypoints.py consumes this — keep additions here, not there.
PIPELINE_FAMILIES: Dict[str, Tuple[str, ...]] = {
    "1f1b": ("llama", "gptneox", "mixtral"),
    "gpipe": ("llama", "gptneox"),
}


def _trunk_parts(family: str, params: Dict[str, Any], cfg, seq_len: int):
    """Per-family pieces the schedules compose: ``stage_fn(layers_local, c)``
    (a contiguous slice of the stacked layer scan over the family's carry)
    and ``head_loss(head_params, carry, targets)`` (final norm + LM head +
    CE, honoring ``cfg.ce_chunk``), plus the head-param subtree keys and
    the carry protocol (``init_carry`` wraps a microbatch activation into
    the carry pytree; ``carry_x`` extracts the activation leaf).

    Families supported: llama, gptneox (carry = the activation array) and
    mixtral (carry = (x, aux_sum, dropped_sum) — the router load-balance
    terms accumulate across stages and enter the loss at the head)."""
    from nexus_tpu.ops.losses import chunked_softmax_xent, dense_softmax_xent
    from nexus_tpu.ops.rope import rope_cos_sin

    init_carry = lambda x: x
    carry_x = lambda c: c
    extra_loss = None  # carry → additive loss term (mixtral router aux)
    carry_metrics = None  # carry → dict of scalar metrics at the head
    if family == "llama":
        from nexus_tpu.models.llama import _block
        from nexus_tpu.ops.norms import rms_norm

        cos, sin = rope_cos_sin(
            seq_len, cfg.head_dim, cfg.rope_theta, dtype=jnp.float32
        )
        block = lambda h, layer: _block(cfg, h, layer, cos, sin)
        head_keys = ("final_norm", "lm_head")

        def final_norm(head, y):
            return rms_norm(y, head["final_norm"], cfg.norm_eps)
    elif family == "gptneox":
        from nexus_tpu.models.gptneox import _block
        from nexus_tpu.ops.norms import layer_norm

        cos, sin = rope_cos_sin(
            seq_len, cfg.rotary_dims, cfg.rope_theta, dtype=jnp.float32
        )
        block = lambda h, layer: _block(cfg, h, layer, cos, sin)
        head_keys = ("final_norm", "final_norm_b", "lm_head")

        def final_norm(head, y):
            return layer_norm(
                y, head["final_norm"], head["final_norm_b"], cfg.norm_eps
            )
    elif family == "mixtral":
        from nexus_tpu.models.mixtral import _block
        from nexus_tpu.ops.norms import rms_norm

        cos, sin = rope_cos_sin(
            seq_len, cfg.head_dim, cfg.rope_theta, dtype=jnp.float32
        )
        block = lambda c, layer: _block(cfg, c, layer, cos, sin)
        head_keys = ("final_norm", "lm_head")
        init_carry = lambda x: (
            x, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)
        )
        carry_x = lambda c: c[0]

        def final_norm(head, y):
            return rms_norm(y, head["final_norm"], cfg.norm_eps)

        def extra_loss(carry):
            # layer-mean router aux, matching the non-pipelined loss_fn
            _, aux, _ = carry
            return cfg.router_aux_weight * aux / cfg.n_layers

        def carry_metrics(carry):
            # the observability scalars the non-pipelined loss_fn reports
            # (models/mixtral.py: aux dashboards, capacity tuning signal)
            _, aux, dropped = carry
            return {
                "aux": aux / cfg.n_layers,
                "router_dropped_fraction": dropped / cfg.n_layers,
            }
    else:
        raise ValueError(
            "pipeline parallelism supports llama/gptneox/mixtral "
            f"(got {family!r})"
        )

    if getattr(cfg, "remat", False):
        # per-layer remat inside the stage — under GPipe this bounds the M
        # in-flight activation sets at M×(layer I/O); under 1F1B the stage
        # input is the only saved tensor already, so remat only trims the
        # within-tick vjp residuals further
        from nexus_tpu.ops.remat import checkpoint_block

        block = checkpoint_block(block, getattr(cfg, "remat_policy", "full"))

    def stage_fn(layers_local, carry):
        def body(c, layer):
            return block(c, layer), None

        carry, _ = lax.scan(body, carry, layers_local)
        return carry

    def head_loss(head, carry, targets):
        """Final norm + LM head + CE (+ family extras, e.g. router aux).
        ``head`` needs only the head_keys entries, so the full params tree
        is also accepted."""
        y = final_norm(head, carry_x(carry))
        if getattr(cfg, "ce_chunk", 0) > 0:
            loss = chunked_softmax_xent(
                y, head["lm_head"], targets, chunk=cfg.ce_chunk
            )
        else:
            loss = dense_softmax_xent(y, head["lm_head"], targets)
        if extra_loss is not None:
            loss = loss + extra_loss(carry)
        return loss

    return (
        stage_fn, head_loss, final_norm, head_keys, init_carry, carry_x,
        carry_metrics,
    )


def _check_pipeline_shapes(b, n_microbatches, cfg, mesh):
    if b % n_microbatches:
        raise ValueError(
            f"batch {b} not divisible by microbatches {n_microbatches}"
        )
    n_stages = mesh.shape["pipeline"]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by {n_stages} stages"
        )


# ----------------------------------------------------- GPipe integration


def _pipeline_trunk(
    family: str, params: Dict[str, Any], cfg, tokens: jnp.ndarray,
    mesh: Mesh, n_microbatches: int,
):
    """GPipe trunk WITHOUT the final norm: tokens (B, S) → (B, S, d), plus
    the family parts so callers reuse the one norm/CE dispatch."""
    if family == "mixtral":
        raise ValueError(
            "mixtral pipeline parallelism requires the 1f1b schedule "
            "(the GPipe body carries a single activation array; the MoE "
            "carry also threads router aux terms)"
        )
    b, s = tokens.shape
    _check_pipeline_shapes(b, n_microbatches, cfg, mesh)
    parts = _trunk_parts(family, params, cfg, s)
    stage_fn = parts[0]

    x = params["embed"].astype(cfg.dtype)[tokens]
    x_mb = x.reshape(n_microbatches, b // n_microbatches, s, cfg.d_model)

    layer_spec = jax.tree_util.tree_map(lambda _: P("pipeline"), params["layers"])
    # microbatch dim replicated; per-microbatch batch dim keeps the data
    # sharding so the data axis parallelizes within each pipeline stage
    y_mb = pipeline_apply(
        stage_fn, params["layers"], x_mb, mesh,
        params_spec=layer_spec, x_spec=P(None, ("data", "fsdp")),
    )
    return y_mb.reshape(b, s, cfg.d_model), parts


def pipeline_hidden(
    family: str,
    params: Dict[str, Any],
    cfg,
    tokens: jnp.ndarray,
    mesh: Mesh,
    n_microbatches: int,
) -> jnp.ndarray:
    """Model trunk with layers pipelined over the 'pipeline' mesh axis:
    tokens (B, S) → final-norm hidden (B, S, d).

    Embedding and the LM head are replicated (cheap vs the layer stack);
    the (B, S) batch is split into M microbatches along batch."""
    y, (_stage, _loss, final_norm, *_rest) = _pipeline_trunk(
        family, params, cfg, tokens, mesh, n_microbatches
    )
    return final_norm(params, y)


def pipeline_forward(
    family: str, params: Dict[str, Any], cfg, tokens: jnp.ndarray,
    mesh: Mesh, n_microbatches: int,
) -> jnp.ndarray:
    """tokens (B, S) → logits (B, S, V) f32 through the GPipe trunk."""
    y = pipeline_hidden(family, params, cfg, tokens, mesh, n_microbatches)
    return (y @ params["lm_head"]).astype(jnp.float32)


def pipeline_loss(
    family: str, params: Dict[str, Any], cfg, batch: Dict[str, jnp.ndarray],
    mesh: Mesh, n_microbatches: int,
) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """GPipe next-token CE; honors ``cfg.ce_chunk`` exactly like the
    non-pipelined losses — the norm/CE dispatch is the same ``head_loss``
    the 1F1B schedule uses."""
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    y, (_stage, head_loss, *_rest) = _pipeline_trunk(
        family, params, cfg, inputs, mesh, n_microbatches
    )
    loss = head_loss(params, y, targets)
    return loss, {"loss": loss, "perplexity": jnp.exp(loss)}


# thin llama-named wrappers kept for callers/tests predating the
# family-generic surface
def llama_pipeline_hidden(params, cfg, tokens, mesh, n_microbatches):
    return pipeline_hidden("llama", params, cfg, tokens, mesh, n_microbatches)


def llama_pipeline_forward(params, cfg, tokens, mesh, n_microbatches):
    return pipeline_forward("llama", params, cfg, tokens, mesh, n_microbatches)


def llama_pipeline_loss(params, cfg, batch, mesh, n_microbatches):
    return pipeline_loss("llama", params, cfg, batch, mesh, n_microbatches)


# ------------------------------------------------------- 1F1B schedule


def _1f1b_body(
    stage_fn, head_loss, carry_metrics, axis, n_mb, data_axes,
    local_layers, head, x_mb, tgt_mb,
):
    """Per-device 1F1B schedule (manual forward + backward).

    Tick ``t`` runs two phases on every stage ``s``:
      * fwd phase: microbatch ``t - s`` (when in range) — stage input saved
        into a ``2S-1``-slot ring, stage forward applied, result hopped to
        ``s+1``;
      * bwd phase: microbatch ``t - (2S-2-s)`` — saved input pulled from
        the ring, stage forward REMATERIALIZED under ``jax.vjp``, cotangent
        taken from the next stage's gradient hop (or, on the last stage,
        from the loss just computed this tick), parameter grads
        accumulated, input gradient hopped to ``s-1``.

    The last stage's microbatch thus goes fwd -> head-loss -> bwd within
    one tick, and earlier stages drain backward one hop per tick — the
    PipeDream-flush (non-interleaved 1F1B) dependency structure, in
    M + 2S - 2 total ticks.

    Returns ``(loss, metrics_dict, d_layers, d_head, dx_mb)``;
    shared-param grads are already pmean'd over the data axes
    (global-batch mean semantics, matching what autodiff produces for the
    non-pipelined loss). ``metrics_dict`` holds the family's
    ``carry_metrics`` scalars (mixtral router aux/dropped), microbatch-
    averaged at the last stage — empty for families without extras."""
    n_stages = lax.axis_size(axis)
    stage = lax.axis_index(axis)
    is_last = stage == n_stages - 1
    m = n_mb
    n_slots = 2 * n_stages - 1  # max in-flight inputs per stage (stage 0)
    n_ticks = m + 2 * n_stages - 2

    f32 = jnp.float32

    tmap = jax.tree_util.tree_map

    def g(layers, head_p, c_in, tgt):
        """Unified per-microbatch stage computation: trunk slice + (last
        stage only, via lax.cond — other stages skip the FLOPs at run
        time) the LM-head loss. One vjp of this covers both the inner
        stages (cotangent = next stage's dh) and the last stage
        (cotangent = d loss). ``c_in`` is the family's carry pytree (a
        bare activation array for the dense families; (x, aux, dropped)
        for mixtral)."""
        c_out = stage_fn(layers, c_in)
        loss = lax.cond(
            is_last,
            lambda hp, c: head_loss(hp, c, tgt).astype(f32),
            lambda hp, c: jnp.zeros((), f32),
            head_p, c_out,
        )
        return c_out, loss

    # x_mb is the CARRY TREE with a leading microbatch dim on every leaf
    zero_act = tmap(lambda l: jnp.zeros(l.shape[1:], l.dtype), x_mb)
    metrics0 = (
        tmap(lambda v: jnp.zeros((), f32), carry_metrics(zero_act))
        if carry_metrics is not None
        else {}
    )
    carry0 = (
        zero_act,                                     # fwd_buf: c from s-1
        zero_act,                                     # bwd_buf: dc from s+1
        tmap(lambda l: jnp.zeros((n_slots,) + l.shape[1:], l.dtype), x_mb),
        tmap(lambda p: jnp.zeros(p.shape, f32), local_layers),
        tmap(lambda p: jnp.zeros(p.shape, f32), head),
        # dx_mb: input-dtype, written once per slot (no accumulation), only
        # stage 0's copy is ever read (out_specs stage-stack + [0] outside)
        tmap(jnp.zeros_like, x_mb),
        jnp.zeros((), f32),                           # loss accumulator
        metrics0,                                     # family extras acc
    )
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    perm_bwd = [(i, (i - 1) % n_stages) for i in range(n_stages)]

    def _index(tree, idx):
        return tmap(
            lambda l: lax.dynamic_index_in_dim(l, idx, 0, keepdims=False),
            tree,
        )

    def tick(carry, t):
        (
            fwd_buf, bwd_buf, saved, g_layers, g_head, dx_mb, loss_acc,
            m_acc,
        ) = carry

        # ---------------------------------------------------- fwd phase
        fwd_m = t - stage
        fwd_live = jnp.logical_and(fwd_m >= 0, fwd_m < m)
        inject = _index(x_mb, jnp.clip(fwd_m, 0, m - 1))
        c_in = tmap(
            lambda i, fb: jnp.where(
                fwd_live, jnp.where(stage == 0, i, fb), jnp.zeros_like(i)
            ),
            inject, fwd_buf,
        )
        slot_f = jnp.mod(jnp.clip(fwd_m, 0, None), n_slots)

        def save_slot(s_leaf, c_leaf):
            cur = lax.dynamic_index_in_dim(
                s_leaf, slot_f, axis=0, keepdims=False
            )
            return lax.dynamic_update_index_in_dim(
                s_leaf, jnp.where(fwd_live, c_leaf, cur), slot_f, axis=0
            )

        saved = tmap(save_slot, saved, c_in)
        c_out = stage_fn(local_layers, c_in)
        fwd_buf = tmap(lambda y: lax.ppermute(y, axis, perm_fwd), c_out)

        # ---------------------------------------------------- bwd phase
        bwd_m = t - (2 * n_stages - 2 - stage)
        bwd_live = jnp.logical_and(bwd_m >= 0, bwd_m < m)
        slot_b = jnp.mod(jnp.clip(bwd_m, 0, None), n_slots)
        c_saved = _index(saved, slot_b)
        tgt = lax.dynamic_index_in_dim(
            tgt_mb, jnp.clip(bwd_m, 0, m - 1), axis=0, keepdims=False
        )
        (c_re, loss_mb), vjp_fn = jax.vjp(
            lambda L, H, c: g(L, H, c, tgt), local_layers, head, c_saved
        )
        dc_out = tmap(
            lambda bb, rr: jnp.where(
                is_last, jnp.zeros_like(bb), bb
            ).astype(rr.dtype),
            bwd_buf, c_re,
        )
        # each microbatch contributes loss/M; the cotangent carries the 1/M
        dloss = jnp.where(
            jnp.logical_and(is_last, bwd_live), f32(1.0 / m), f32(0.0)
        )
        d_layers, d_head, dc_in = vjp_fn((dc_out, dloss))

        mask = bwd_live
        g_layers = tmap(
            lambda acc, d: acc + jnp.where(mask, d.astype(f32), 0.0),
            g_layers, d_layers,
        )
        g_head = tmap(
            lambda acc, d: acc + jnp.where(mask, d.astype(f32), 0.0),
            g_head, d_head,
        )
        loss_acc = loss_acc + jnp.where(mask, loss_mb / m, 0.0)
        if carry_metrics is not None:
            # family extras (mixtral router aux/dropped): meaningful
            # only from the LAST stage's fully-accumulated carry
            vals = carry_metrics(c_re)
            gate = jnp.logical_and(is_last, mask)
            m_acc = jax.tree_util.tree_map(
                lambda a, v: a + jnp.where(gate, v.astype(jnp.float32) / m, 0.0),
                m_acc, vals,
            )
        # stage 0's input gradient is d(embedding output) — record it
        record_dx = jnp.logical_and(stage == 0, mask)

        def record_slot(dx_leaf, d_leaf):
            cur = lax.dynamic_index_in_dim(
                dx_leaf, jnp.clip(bwd_m, 0, m - 1), axis=0, keepdims=False
            )
            return lax.dynamic_update_index_in_dim(
                dx_leaf,
                jnp.where(record_dx, d_leaf.astype(dx_leaf.dtype), cur),
                jnp.clip(bwd_m, 0, m - 1), axis=0,
            )

        dx_mb = tmap(record_slot, dx_mb, dc_in)
        bwd_buf = tmap(
            lambda d, bb: lax.ppermute(d.astype(bb.dtype), axis, perm_bwd),
            dc_in, bwd_buf,
        )

        return (
            fwd_buf, bwd_buf, saved, g_layers, g_head, dx_mb, loss_acc,
            m_acc,
        ), None

    carry, _ = lax.scan(tick, carry0, jnp.arange(n_ticks))
    _, _, _, g_layers, g_head, dx_mb, loss_acc, m_acc = carry

    # stage-varying scalars/params collapse over 'pipeline' (exactly one
    # stage holds nonzero values); shared-param grads and the loss then
    # average over the data shards — global-batch mean semantics. dx_mb is
    # NOT collectived: it is returned with a leading per-stage dim and the
    # caller reads stage 0's shard lazily (a full-batch-activation psum of
    # which S-1 contributions are zeros would be pure waste).
    loss = lax.psum(loss_acc, axis)
    m_acc = tmap(lambda v: lax.psum(v, axis), m_acc)
    g_head = tmap(lambda gv: lax.psum(gv, axis), g_head)
    if data_axes:
        loss = lax.pmean(loss, data_axes)
        m_acc = tmap(lambda v: lax.pmean(v, data_axes), m_acc)
        g_head = tmap(lambda gv: lax.pmean(gv, data_axes), g_head)
        g_layers = tmap(lambda gv: lax.pmean(gv, data_axes), g_layers)
        # dx is PER-SHARD (it feeds this shard's embedding-lookup rows); the
        # global loss carries a 1/n factor the local vjp didn't see — but
        # ONLY over the axes the batch is actually sharded on (data, fsdp).
        # Axes the activations are REPLICATED over (tensor/sequence/expert)
        # contribute identical dx copies, not disjoint batch shards, and
        # must not scale the gradient down.
        n_batch_shards = 1
        for ax in ("data", "fsdp"):
            if ax in data_axes:
                n_batch_shards *= lax.axis_size(ax)
        dx_mb = tmap(lambda l: l / n_batch_shards, dx_mb)
    return loss, m_acc, g_layers, g_head, tmap(lambda l: l[None], dx_mb)


def pipeline_1f1b_loss_and_grads(
    family: str,
    params: Dict[str, Any],
    cfg,
    batch: Dict[str, jnp.ndarray],
    mesh: Mesh,
    n_microbatches: int,
) -> Tuple[jnp.ndarray, Dict[str, Any], Dict[str, Any]]:
    """1F1B pipelined train computation: ``(loss, metrics, grads)``.

    Unlike the GPipe path this does NOT go through ``jax.grad`` — the
    backward is part of the schedule (see ``_1f1b_body``). Peak activation
    memory per stage is the static ``2S-1``-slot input ring (+ one
    microbatch's within-tick vjp residuals), versus GPipe's all-M in-flight
    activations. Grads cover the full param tree: trunk layers from the
    schedule, embed via a scatter-add of the returned input gradients,
    head/final-norm from the last stage's per-tick head vjp."""
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    b, s = inputs.shape
    _check_pipeline_shapes(b, n_microbatches, cfg, mesh)
    m = n_microbatches
    (
        stage_fn, head_loss, _norm, head_keys, init_carry, carry_x,
        carry_metrics,
    ) = _trunk_parts(family, params, cfg, s)

    embed = params["embed"]
    x = embed.astype(cfg.dtype)[inputs]
    x_mb = x.reshape(m, b // m, s, cfg.d_model)
    # the carry tree with a leading microbatch dim on every leaf (vmap of
    # the family's per-microbatch carry constructor — dense families: the
    # activation itself; mixtral: (x, 0-aux, 0-dropped))
    carry_mb = jax.vmap(init_carry)(x_mb)
    tgt_mb = targets.reshape(m, b // m, s)
    head = {k: params[k] for k in head_keys}

    data_axes = tuple(
        ax for ax in mesh.axis_names
        if ax != "pipeline" and mesh.shape[ax] > 1
    )
    layer_spec = jax.tree_util.tree_map(
        lambda _: P("pipeline"), params["layers"]
    )
    head_spec = jax.tree_util.tree_map(lambda _: P(), head)
    x_spec = P(None, ("data", "fsdp"))
    # batch-sharded spec for activation-shaped leaves; per-microbatch
    # scalar leaves (mixtral aux terms) are replicated
    carry_spec = jax.tree_util.tree_map(
        lambda l: x_spec if l.ndim > 1 else P(None), carry_mb
    )

    # dx comes back with a leading per-stage dim (P('pipeline')); reading
    # [0] pulls only stage 0's shard — the one that holds the real values —
    # with no collective
    dx_spec = jax.tree_util.tree_map(
        lambda l: P("pipeline", None, ("data", "fsdp"))
        if l.ndim > 1
        else P("pipeline", None),
        carry_mb,
    )
    from nexus_tpu.parallel.sharding import shard_map_unchecked_kwargs

    # metrics dict structure must be known for out_specs: probe it
    zero_c = jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape[1:], l.dtype), carry_mb
    )
    metrics_spec = (
        jax.tree_util.tree_map(lambda _: P(), carry_metrics(zero_c))
        if carry_metrics is not None
        else {}
    )
    kwargs = dict(
        mesh=mesh,
        in_specs=(layer_spec, head_spec, carry_spec, x_spec),
        out_specs=(P(), metrics_spec, layer_spec, head_spec, dx_spec),
        **shard_map_unchecked_kwargs(),
    )
    body = functools.partial(
        _1f1b_body, stage_fn, head_loss, carry_metrics, "pipeline", m,
        data_axes,
    )
    loss, extra_metrics, g_layers, g_head, dx_staged = shard_map(
        body, **kwargs
    )(params["layers"], head, carry_mb, tgt_mb)

    # embedding gradient: scatter the input gradients back onto the rows
    # the lookup read (plain SPMD — XLA shards/combines the scatter).
    # Only the activation leaf of the carry cotangent feeds the embedding;
    # the mixtral aux leaves' cotangents are w.r.t. CONSTANT zero inits.
    dx = carry_x(dx_staged)[0].reshape(b, s, cfg.d_model)
    d_embed = (
        jnp.zeros(embed.shape, jnp.float32)
        .at[inputs]
        .add(dx.astype(jnp.float32))
    )

    grads = {"embed": d_embed, "layers": g_layers, **g_head}
    metrics = {"loss": loss, "perplexity": jnp.exp(loss), **extra_metrics}
    return loss, metrics, grads
