"""Wave-boundary live gauges for the serving engine.

:class:`LiveGauges` publishes the engine's per-wave vitals — queue
depth, running rows, free pool blocks, host-tier bytes, committed
tokens, and ROLLING ttft/queue percentiles — into the in-process
telemetry registry (and over DogStatsD when an address is configured;
without one the client is registry-only, so statsd stays off by
default). This replaces the end-of-run-only visibility the engine had
before PR 12: a router or autoscaler (the fleet-scale ROADMAP item) can
now read ``serve_ttft_p95_s`` / ``serve_queue_depth`` from the registry
while the engine runs, and ``nexus_tpu/obs/exposition.py`` renders the
same registry as Prometheus text.

:class:`RollingPercentiles` is the bounded-window estimator behind the
percentile gauges: a deque of the last N observations scored with the
SHARED nearest-rank helper (utils/telemetry.py
``percentile_nearest_rank`` — the same formula the end-of-run rollups
use, so live and final numbers can never disagree about the estimator).
"""

from __future__ import annotations

import math
from collections import deque
from typing import List, Optional

from nexus_tpu.utils.telemetry import (
    METRIC_SERVE_COMMITTED,
    METRIC_SERVE_FREE_BLOCKS,
    METRIC_SERVE_HOST_BYTES,
    METRIC_SERVE_QUEUE_DEPTH,
    METRIC_SERVE_QUEUE_P50,
    METRIC_SERVE_QUEUE_P95,
    METRIC_SERVE_RUNNING_ROWS,
    METRIC_SERVE_TTFT_P50,
    METRIC_SERVE_TTFT_P95,
    METRIC_SERVE_WAVES,
    StatsdClient,
    get_client,
    percentile_nearest_rank,
)


class RollingPercentiles:
    """Nearest-rank percentiles over a bounded sliding window.

    O(1) add; O(w log w) score (the window is small — default 256 — and
    scored once per wave, not per observation). An empty window scores
    NaN, matching the end-of-run convention: a gauge is OMITTED rather
    than published as a flattering 0.0."""

    def __init__(self, window: int = 256) -> None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._xs: deque = deque(maxlen=int(window))
        self.count = 0  # total observations ever added

    def add(self, x: float) -> None:
        self._xs.append(float(x))
        self.count += 1

    def __len__(self) -> int:
        return len(self._xs)

    def percentile(self, q: float) -> float:
        return percentile_nearest_rank(list(self._xs), q)

    def percentiles(self, qs) -> List[float]:
        """Several ranks off ONE sorted copy of the window — the
        publish path scores p50+p95 of each window per wave, and
        copying+sorting the window once instead of per-rank halves the
        dominant per-publish cost at full windows. Same nearest-rank
        estimator (NaN for every rank of an empty window)."""
        if not self._xs:
            return [float("nan")] * len(qs)
        s = sorted(self._xs)
        n = len(s)
        return [s[min(n - 1, int(round(q * (n - 1))))] for q in qs]


class LiveGauges:
    """Publish one wave boundary's vitals into the telemetry registry.

    The engine owns the rolling windows (fed at request completion) and
    calls :meth:`publish` once per wave with plain ints — everything
    here is a handful of ``gauge()`` calls (lock + dict write each).
    ``tags`` (e.g. ``["engine:serve-0"]``) distinguish replicas sharing
    one process registry — the fleet item's per-replica signals."""

    def __init__(self, client: Optional[StatsdClient] = None,
                 tags: Optional[List[str]] = None,
                 ttft_window: int = 256, queue_window: int = 256) -> None:
        self._client = client  # None → resolve the process default lazily
        self.tags = list(tags or [])
        self.ttft = RollingPercentiles(ttft_window)
        self.queue_wait = RollingPercentiles(queue_window)
        self.publishes = 0

    @property
    def client(self) -> StatsdClient:
        if self._client is None:
            self._client = get_client()
        return self._client

    def observe_finish(self, ttft_s: float, queue_s: float) -> None:
        """Feed one SERVED request's observations into the rolling
        windows (the engine calls this where it appends to its
        end-of-run populations, so the two views see identical data)."""
        self.ttft.add(ttft_s)
        self.queue_wait.add(queue_s)

    def publish(self, queue_depth: int, running_rows: int,
                free_pool_blocks: int, host_cache_bytes: int,
                committed_tokens: int, waves: int) -> None:
        c = self.client
        tags = self.tags or None
        # every gauge of this boundary is stamped with the engine's wave
        # count — the per-series freshness record (GaugeSample.stamp)
        # the fleet autoscaler compares across polls: a wedged engine's
        # stamp (and the registry seq) stops advancing, so its frozen
        # last-known-good values can't pass for live health
        w = float(waves)
        c.gauge(METRIC_SERVE_QUEUE_DEPTH, queue_depth, tags=tags, stamp=w)
        c.gauge(METRIC_SERVE_RUNNING_ROWS, running_rows, tags=tags, stamp=w)
        c.gauge(METRIC_SERVE_FREE_BLOCKS, free_pool_blocks, tags=tags,
                stamp=w)
        c.gauge(METRIC_SERVE_HOST_BYTES, host_cache_bytes, tags=tags,
                stamp=w)
        c.gauge(METRIC_SERVE_COMMITTED, committed_tokens, tags=tags, stamp=w)
        c.gauge(METRIC_SERVE_WAVES, waves, tags=tags, stamp=w)
        for (name50, name95), win in (
            ((METRIC_SERVE_TTFT_P50, METRIC_SERVE_TTFT_P95), self.ttft),
            ((METRIC_SERVE_QUEUE_P50, METRIC_SERVE_QUEUE_P95),
             self.queue_wait),
        ):
            p50, p95 = win.percentiles((0.50, 0.95))
            for name, v in ((name50, p50), (name95, p95)):
                if not math.isnan(v):  # empty window: omit, never 0.0
                    c.gauge(name, round(v, 6), tags=tags, stamp=w)
        self.publishes += 1
