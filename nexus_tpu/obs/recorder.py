"""Flight recorder: a bounded ring of recent engine wave events.

The serving engine appends one compact event per wave boundary (plus
admission/shed/deadline/drain events as they happen) into a fixed-size
ring. In steady state that's all it is — O(1) appends into a deque,
nothing retained beyond ``capacity`` events. When something goes wrong
the ring becomes the postmortem: :meth:`FlightRecorder.trip` freezes a
JSON-safe snapshot of the recent past, stamped with the trip reason.

The engine trips it on three conditions (ISSUE 12 tentpole):

  * a runtime SANITIZER fires mid-serve (scratch-tail / radix-tree
    audit) — the dump shows the waves leading up to the invariant
    break, which the raising AssertionError alone cannot;
  * a deadline/shed STORM — one wave boundary terminating >=
    ``storm_threshold`` requests means the engine is in overload or
    clock trouble, exactly when end-of-run metrics are least useful;
  * an engine DRAIN (cancellation / confirmed death) — the failover
    supervisor (ha/serve_failover.py) collects the dump into its
    report, so a kill-mid-decode chaos postmortem shows precisely what
    the engine was doing when it died, request by request.

Like the tracer, the recorder never reads a clock of its own — the
engine stamps every event with its injectable clock (monotonic-only,
enforced by nexuslint NX-CLOCK003 for this package).
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Any, List, Optional

FLIGHT_SCHEMA_VERSION = 1

#: event kinds the engine records (the dump validator accepts exactly
#: these; every event additionally carries ``seq`` and ``t``)
FLIGHT_EVENT_KINDS = (
    "run_start",      # serve() entered its wave loop
    "wave",           # one decode-chunk boundary (the per-wave gauges)
    "admission",      # an admission wave placed >= 1 request
    "shed",           # a queued request shed (depth / delay bound)
    "deadline",       # a request terminated deadline_exceeded
    "drain_request",  # one request drained off a dying engine
    "run_end",        # serve() returned normally
)


class FlightRecorder:
    """Bounded ring of wave events + trip-to-snapshot.

    ``record`` is the hot-path append; ``trip`` freezes the ring into a
    dump dict (also kept in ``self.dumps`` / ``self.last_dump`` so the
    failover supervisor can collect it after the engine thread exits).
    One recorder may serve an engine across multiple serve() runs — the
    ring just keeps rolling; ``seq`` is monotonic over the recorder's
    lifetime so dumps from successive trips order globally. ``dumps``
    is itself a bounded ring (``max_dumps``, newest kept): a long-lived
    engine under sustained overload trips once per serve() run, and
    telemetry must never grow RSS."""

    def __init__(self, capacity: int = 512, max_dumps: int = 16) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if max_dumps < 1:
            raise ValueError(f"max_dumps must be >= 1, got {max_dumps}")
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self.dumps: deque = deque(maxlen=int(max_dumps))
        self.last_dump: Optional[dict] = None

    def record(self, kind: str, t: float, **fields: Any) -> None:
        """Append one event (``t``: seconds since the run's t0, stamped
        by the engine's injectable clock)."""
        ev = {"seq": self._seq, "t": round(float(t), 6), "kind": kind}
        ev.update(fields)
        self._seq += 1
        self._ring.append(ev)

    @property
    def events_recorded(self) -> int:
        """Total events ever recorded (>= len(ring) once it wraps)."""
        return self._seq

    def tail(self, n: int = 16) -> List[dict]:
        """The most recent ``n`` events (oldest first)."""
        if n <= 0:
            return []
        return list(self._ring)[-n:]

    def trip(self, reason: str, t: float,
             detail: Optional[dict] = None) -> dict:
        """Freeze the ring → dump dict (also appended to ``dumps``)."""
        dump = {
            "schema_version": FLIGHT_SCHEMA_VERSION,
            "reason": reason,
            "tripped_t": round(float(t), 6),
            "detail": dict(detail or {}),
            "events": list(self._ring),
        }
        self.dumps.append(dump)
        self.last_dump = dump
        return dump


def write_dump(dump: dict, path: str) -> str:
    """Persist a trip dump as JSON (postmortem artifact). Creates parent
    directories; returns ``path``."""
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(dump, f, indent=1, sort_keys=False)
        f.write("\n")
    return path


def validate_flight_dump(dump: dict) -> List[str]:
    """Schema check of a trip dump → problem list (empty = valid):
    version, reason present, events are known kinds with monotonic
    ``seq`` and numeric ``t``. ``make obs-smoke`` and the chaos tests
    gate on this."""
    problems: List[str] = []
    if dump.get("schema_version") != FLIGHT_SCHEMA_VERSION:
        problems.append(
            f"schema_version {dump.get('schema_version')!r} != "
            f"{FLIGHT_SCHEMA_VERSION}"
        )
    if not dump.get("reason"):
        problems.append("missing trip reason")
    events = dump.get("events")
    if not isinstance(events, list):
        problems.append("events is not a list")
        return problems
    last_seq = -1
    for ev in events:
        kind = ev.get("kind")
        if kind not in FLIGHT_EVENT_KINDS:
            problems.append(f"event seq={ev.get('seq')}: unknown kind "
                            f"{kind!r}")
        seq = ev.get("seq")
        if not isinstance(seq, int) or seq <= last_seq:
            problems.append(
                f"event seq {seq!r} not strictly increasing after "
                f"{last_seq}"
            )
        else:
            last_seq = seq
        if not isinstance(ev.get("t"), (int, float)):
            problems.append(f"event seq={seq}: t is not a number")
    return problems


# typing helper for engine call sites that accept "a recorder or the
# explicit off switch" (flight_recorder=False disables the default)
RecorderLike = Optional[Any]
