"""Exposition: render the in-process telemetry registry for scrapers.

Two formats over one consistent :meth:`StatsdClient.snapshot`:

  * :func:`render_prometheus` — Prometheus text exposition (v0.0.4):
    one ``# TYPE`` line per metric family (everything the registry
    holds is a gauge), one sample line per (name, tags) series, with
    DogStatsD ``key:value`` tags translated to Prometheus labels and
    metric names sanitized to ``[a-zA-Z0-9_:]``. Serve this from any
    HTTP handler (or dump it to a file) — no client library needed.
  * :func:`registry_snapshot` — a JSON-safe dict of the same view, for
    tooling that would rather not parse text.

Both read a single locked copy of the registry (the concurrency
contract tools/race_smoke_telemetry.py hammers): a render never sees a
torn write, and emitters are never blocked longer than one dict copy.
"""

from __future__ import annotations

import re
from typing import Optional

from nexus_tpu.utils.telemetry import StatsdClient, get_client

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    """DogStatsD dotted name → Prometheus metric name."""
    name = _NAME_SANITIZE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _prom_labels(tags) -> str:
    """DogStatsD ``key:value`` tag list → ``{key="value",...}`` (tags
    without a colon become ``tag="<raw>"``)."""
    if not tags:
        return ""
    parts = []
    for t in tags:
        k, sep, v = str(t).partition(":")
        if not sep:
            k, v = "tag", str(t)
        k = _LABEL_SANITIZE.sub("_", k) or "tag"
        v = str(v).replace("\\", r"\\").replace('"', r"\"")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def render_prometheus(client: Optional[StatsdClient] = None) -> str:
    """The registry as Prometheus text exposition (deterministic order:
    families and series sorted by name/labels, so two renders of one
    registry state are byte-identical — the format tests rely on it)."""
    snap = (client or get_client()).snapshot()
    series = snap["series"]
    by_family: dict = {}
    for (name, tags), value in series.items():
        by_family.setdefault(_prom_name(name), []).append(
            (_prom_labels(tags), value)
        )
    lines = []
    for fam in sorted(by_family):
        lines.append(f"# TYPE {fam} gauge")
        for labels, value in sorted(by_family[fam]):
            lines.append(f"{fam}{labels} {value}")
    return "\n".join(lines) + ("\n" if lines else "")


def registry_snapshot(client: Optional[StatsdClient] = None) -> dict:
    """JSON-safe snapshot of the registry: ``gauges`` (untagged
    last-value map) plus ``series`` (one entry per (name, tags) with
    the tags spelled out) — the machine-readable twin of
    :func:`render_prometheus`."""
    snap = (client or get_client()).snapshot()
    return {
        "gauges": {k: v for k, v in sorted(snap["gauges"].items())},
        "series": [
            {"name": name, "tags": list(tags), "value": value}
            for (name, tags), value in sorted(snap["series"].items())
        ],
        "history_len": snap["history_len"],
    }
