"""Cross-replica request journeys for the serve fleet.

PR 12 made a SINGLE engine's run observable: one :class:`~nexus_tpu
.obs.trace.ServeTracer` timeline per request, ending ``terminal`` — or
``drained`` when the engine died under it. PR 14 made serving a fleet,
and with it the per-engine view stopped being the per-REQUEST view: a
request routed to replica A, drained on A's death, and finished on
replica B leaves two disconnected traces whose request indices don't
even agree (each serve call numbers its own batch). The journey layer
stitches them back together:

  * a **journey id** — stable for the request's whole life, stamped by
    the :class:`~nexus_tpu.ha.serve_failover.ServeFailoverPlanner` at
    generation 0 (``j<queue index>``) and carried through every
    requeue on ``ServeRequest.journey`` — threads from the fleet's
    dispatch through the router into each engine's tracer;
  * a **leg** — one engine generation's span timeline for the journey
    (the ServeTracer spans, verbatim — same schema, same golden file),
    tagged with the replica that served it and the serve call's start
    on the FLEET's clock (span ``t`` stays engine-local: each engine's
    t0 is its own serve start, so legs subtract cleanly within
    themselves and order globally by ``t_start``);
  * the **seam invariant** — a requeued generation's prompt is the
    prior generation's prompt plus its drained committed tokens (the
    planner folds them in), so consecutive legs must satisfy
    ``enqueued[k+1].prompt_tokens == enqueued[k].prompt_tokens +
    drained[k].committed_tokens``. :func:`validate_journey` checks it
    structurally — "no gap, no token lost or re-decoded across the
    seam" is a schema property, not a test-only assertion.

Like every obs module: host-side dict bookkeeping only, no JAX, no
clock reads of its own (callers stamp ``t_start`` from their injectable
clocks), schema pinned by a golden file
(``tests/golden/fleet_obs_schema.json``).

SLO accounting rides the same stitched view. A journey's end-to-end
latency decomposes into three delay buckets (the attribution the
ROADMAP's goodput-under-SLO yardstick needs):

  * ``queue_s``   — admission waits, summed over every leg (a leg that
    drained before admitting contributes its whole duration here: the
    request only ever waited);
  * ``requeue_s`` — serve time spent on generations that DIED, net of
    their queue waits (committed tokens were preserved, but the wall
    the request lived through on dead engines is failover-induced);
  * ``decode_s``  — the final generation's serve time past admission
    (prefill + decode, the work the user actually paid for once).

``slo_attained`` is then ``status == ok and latency <= slo_s`` with
``latency = queue_s + requeue_s + decode_s`` — identical to the
stitched ``ServeResult.latency_s`` the planner reports (it adds dead
generations' elapsed time back in), so the journey view and the result
view can never disagree about whether an SLO was met.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from nexus_tpu.obs.trace import SPAN_FIELDS

JOURNEY_SCHEMA_VERSION = 1

#: Key order of one journey entry in a dump — pinned by the golden
#: file, like the span fields. ``legs`` holds the per-generation
#: timelines in serve order.
JOURNEY_ENTRY_FIELDS = ("journey", "request", "legs")

#: Key order of one leg. ``timeline`` is a list of ServeTracer spans
#: (SPAN_FIELDS schema, engine-local ``t``); ``t_start`` is the serve
#: call's start on the stitching fleet's clock.
JOURNEY_LEG_FIELDS = ("replica", "t_start", "timeline")


class JourneyBook:
    """Stitch per-serve-call tracer dumps into cross-replica journeys.

    The fleet drives it: after every engine ``serve()`` call it absorbs
    that call's :meth:`ServeTracer.to_dict` dump, tagged with the
    replica id, the call's start time on the fleet clock, and the
    ORIGINAL queue index each batch entry answers (engine request
    indices are per-call). Entries whose tracer timeline carries a
    journey id join that journey as its next leg; ``to_dict()`` renders
    the golden-pinned dump.

    Thread-safety: the fleet absorbs under its own lock (one worker's
    serve call completes at a time per replica; the book itself is
    plain dicts)."""

    def __init__(self) -> None:
        self._journeys: Dict[str, dict] = {}
        self.legs_absorbed = 0

    def absorb_trace(self, trace_dump: dict, replica: str, t_start: float,
                     request_idxs: Sequence[int]) -> int:
        """Fold one serve call's tracer dump in as legs → legs added.

        ``request_idxs[i]`` is the original queue index of the call's
        i-th request (the fleet's ``RequeueEntry.request_idx``).
        Entries without a journey id are skipped — a journey-less trace
        is a single-engine run, which needs no stitching."""
        added = 0
        for entry in trace_dump.get("spans", []):
            jid = str(entry.get("journey", "") or "")
            if not jid:
                continue
            i = int(entry.get("request", 0))
            idx = int(request_idxs[i]) if i < len(request_idxs) else i
            rec = self._journeys.get(jid)
            if rec is None:
                rec = {"journey": jid, "request": idx, "legs": []}
                self._journeys[jid] = rec
            rec["legs"].append({
                "replica": str(replica),
                "t_start": round(float(t_start), 6),
                "timeline": list(entry.get("timeline", [])),
            })
            added += 1
        self.legs_absorbed += added
        return added

    def journey_ids(self) -> List[str]:
        return list(self._journeys)

    def to_dict(self, only: Optional[Sequence[str]] = None) -> dict:
        """The golden-pinned journey dump (``only`` restricts to a
        cohort of journey ids — the flight-trip path)."""
        keep = None if only is None else set(only)
        return {
            "schema_version": JOURNEY_SCHEMA_VERSION,
            "journeys": [
                {
                    "journey": rec["journey"],
                    "request": rec["request"],
                    "legs": [dict(leg) for leg in rec["legs"]],
                }
                for rec in self._journeys.values()
                if keep is None or rec["journey"] in keep
            ],
        }


def _leg_problems(jid: str, k: int, leg: dict, final: bool,
                  problems: List[str]) -> None:
    got = tuple(leg.keys())
    if got != JOURNEY_LEG_FIELDS:
        problems.append(
            f"journey {jid} leg {k}: keys {got} != {JOURNEY_LEG_FIELDS}"
        )
        return
    tl = leg.get("timeline") or []
    if not tl:
        problems.append(f"journey {jid} leg {k}: empty timeline")
        return
    last_t: Optional[float] = None
    for j, span in enumerate(tl):
        kind = span.get("kind")
        if kind not in SPAN_FIELDS:
            problems.append(
                f"journey {jid} leg {k} span {j}: unknown kind {kind!r}"
            )
            continue
        expect = ("kind",) + SPAN_FIELDS[kind]
        if tuple(span.keys()) != expect:
            problems.append(
                f"journey {jid} leg {k} span {j} ({kind}): fields "
                f"{tuple(span.keys())} != schema {expect}"
            )
        t = span.get("t")
        if not isinstance(t, (int, float)):
            problems.append(
                f"journey {jid} leg {k} span {j} ({kind}): t not a number"
            )
        elif last_t is not None and t < last_t:
            problems.append(
                f"journey {jid} leg {k} span {j} ({kind}): t went "
                f"backwards ({last_t} -> {t})"
            )
        else:
            last_t = t
    if tl[0].get("kind") != "enqueued":
        problems.append(
            f"journey {jid} leg {k}: timeline does not start 'enqueued'"
        )
    end = tl[-1].get("kind")
    if final:
        if end not in ("terminal", "drained"):
            problems.append(
                f"journey {jid} final leg {k} ends {end!r}, not "
                "terminal/drained"
            )
    elif end != "drained":
        problems.append(
            f"journey {jid} non-final leg {k} ends {end!r}, not "
            "'drained' (only a drain hands a journey to the next leg)"
        )


def validate_journey(dump: dict) -> List[str]:
    """Schema + stitching check of a :meth:`JourneyBook.to_dict` dump →
    problem list (empty = valid). Beyond the golden-pinned key orders
    and per-leg span validity, this enforces the CROSS-REPLICA
    invariants stitching exists to witness: every non-final leg ends
    ``drained`` (the only handoff), leg ``t_start`` never decreases
    (generations serve in order on the fleet clock), and the SEAM is
    token-conserving — the successor leg's prompt is exactly the prior
    leg's prompt plus its drained committed tokens, so no committed
    token is lost or re-decoded across an engine death."""
    problems: List[str] = []
    if dump.get("schema_version") != JOURNEY_SCHEMA_VERSION:
        problems.append(
            f"schema_version {dump.get('schema_version')!r} != "
            f"{JOURNEY_SCHEMA_VERSION}"
        )
    journeys = dump.get("journeys")
    if not isinstance(journeys, list):
        problems.append("journeys is not a list")
        return problems
    for rec in journeys:
        got = tuple(rec.keys())
        if got != JOURNEY_ENTRY_FIELDS:
            problems.append(
                f"journey entry keys {got} != {JOURNEY_ENTRY_FIELDS}"
            )
            continue
        jid = rec.get("journey")
        legs = rec.get("legs") or []
        if not legs:
            problems.append(f"journey {jid}: no legs")
            continue
        last_start: Optional[float] = None
        for k, leg in enumerate(legs):
            _leg_problems(jid, k, leg, final=(k == len(legs) - 1),
                          problems=problems)
            ts = leg.get("t_start")
            if isinstance(ts, (int, float)):
                if last_start is not None and ts < last_start:
                    problems.append(
                        f"journey {jid} leg {k}: t_start went backwards "
                        f"({last_start} -> {ts})"
                    )
                else:
                    last_start = ts
        # the seam: committed tokens conserved across every handoff
        for k in range(len(legs) - 1):
            a = (legs[k].get("timeline") or [{}])
            b = (legs[k + 1].get("timeline") or [{}])
            if (a[0].get("kind") != "enqueued"
                    or b[0].get("kind") != "enqueued"
                    or a[-1].get("kind") != "drained"):
                continue  # already reported above
            expect = (int(a[0].get("prompt_tokens", 0))
                      + int(a[-1].get("committed_tokens", 0)))
            got_p = int(b[0].get("prompt_tokens", 0))
            if got_p != expect:
                problems.append(
                    f"journey {jid} seam {k}->{k + 1}: prompt_tokens "
                    f"{got_p} != prior prompt + drained committed "
                    f"({expect}) — tokens lost or re-decoded across "
                    "the failover"
                )
    return problems


# --------------------------------------------------------- SLO accounting

def _leg_queue_s(tl: List[dict]) -> Optional[float]:
    for span in tl:
        if span.get("kind") == "admitted":
            return float(span.get("queue_s", 0.0))
    return None  # never admitted on this leg


def journey_attribution(rec: dict) -> Dict[str, float]:
    """One journey entry → its delay decomposition (module docstring):
    ``{"queue_s", "requeue_s", "decode_s", "latency_s",
    "committed_tokens", "status"}``. ``latency_s`` is the bucket sum —
    the stitched end-to-end serve latency (detection/restart wall
    between generations is excluded, exactly as the planner excludes
    it from ``ServeResult.latency_s``)."""
    queue = requeue = decode = 0.0
    committed = 0
    status = ""
    legs = rec.get("legs") or []
    for k, leg in enumerate(legs):
        tl = leg.get("timeline") or []
        if not tl:
            continue
        final = k == len(legs) - 1
        end = tl[-1]
        leg_total = float(end.get("t", 0.0))
        q = _leg_queue_s(tl)
        if end.get("kind") == "drained":
            committed += int(end.get("committed_tokens", 0))
            if q is None:
                queue += leg_total  # drained out of the wait queue
            else:
                queue += q
                requeue += max(0.0, leg_total - q)
        elif end.get("kind") == "terminal":
            status = str(end.get("status", ""))
            committed += int(end.get("new_tokens", 0))
            leg_total = float(end.get("latency_s", leg_total))
            if q is None:
                queue += leg_total  # shed / queued-deadline: all wait
            else:
                queue += q
                decode += max(0.0, leg_total - q)
        if final and end.get("kind") == "drained":
            status = "drained"  # interrupted dump: journey still open
    return {
        "queue_s": round(queue, 6),
        "requeue_s": round(requeue, 6),
        "decode_s": round(decode, 6),
        "latency_s": round(queue + requeue + decode, 6),
        "committed_tokens": committed,
        "status": status,
    }


def slo_verdicts(dump: dict, slo_s: float) -> List[dict]:
    """Per-journey ``slo_attained`` verdicts with delay attribution —
    one dict per journey: the attribution buckets plus ``journey``,
    ``request``, ``replicas`` (every replica the journey touched),
    ``migrations`` and ``slo_attained``."""
    out: List[dict] = []
    for rec in dump.get("journeys", []):
        att = journey_attribution(rec)
        legs = rec.get("legs") or []
        out.append({
            "journey": rec.get("journey"),
            "request": rec.get("request"),
            "replicas": [leg.get("replica") for leg in legs],
            "migrations": max(0, len(legs) - 1),
            **att,
            "slo_attained": bool(
                att["status"] == "ok" and att["latency_s"] <= float(slo_s)
            ),
        })
    return out


def goodput_under_slo(results: Sequence[Any], slo_s: float,
                      wall_s: float) -> Dict[str, float]:
    """The fleet-level goodput rollup off stitched ``ServeResult``s:
    tokens of requests that finished ``ok``/``failed_over``-to-ok
    WITHIN the SLO, over the serve wall — the ROADMAP's
    goodput-under-SLO yardstick (raw tok/s counts tokens nobody was
    still waiting for). ``failed_over`` results count when under the
    SLO: the request completed; its migration already shows up as
    requeue-attributed latency."""
    finished = [r for r in results if r is not None]
    ok = [r for r in finished
          if getattr(r, "status", "") in ("ok", "failed_over")]
    attained = [r for r in ok if float(r.latency_s) <= float(slo_s)]
    return {
        "slo_s": round(float(slo_s), 6),
        "slo_attainment": round(
            len(attained) / max(1, len(finished)), 4
        ),
        "goodput_tok_s": round(
            sum(int(r.new_tokens) for r in attained)
            / max(1e-9, float(wall_s)), 2
        ),
        "ok_under_slo": len(attained),
    }
