"""Per-request span timelines for the serving engine.

One :class:`ServeTracer` records one serve run: a list of SPANS per
request, appended by the engine at the host-side points where it
already touches per-row state (admission wave, chunk-boundary commit
loop, release). A span is a plain dict — ``kind`` first, then the
fields :data:`SPAN_FIELDS` fixes for that kind, in that order — so the
dump's field names and ordering are a stable schema golden-file tests
can pin (tests/golden/serve_trace_schema.json) and downstream tooling
(tools/trace_summary.py) can rely on.

Design constraints, in order:

  1. **Cheap.** Recording is a method call + one dict literal per
     event; no JAX ops, no device fetches, no string formatting. The
     engine guards every call site with ``if tracer is not None`` so
     the untraced path pays a single predictable branch.
  2. **No clocks.** The tracer NEVER reads time — the engine stamps
     every event with ``t`` (seconds since the run's ``t0``, from its
     own injectable clock), so traced timelines replay exactly under
     the fake-clock test discipline and the nexuslint monotonic-only
     rule for this package is trivially satisfied.
  3. **Attributable.** Admission spans carry the cache economics of
     the decision (radix-matched tokens, shared/restored block counts,
     CoW), decode spans carry speculation accept/reject counts, and
     lease growth is its own span kind — the per-request
     restore-vs-recompute attribution the disaggregation ROADMAP item
     needs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

TRACE_SCHEMA_VERSION = 1

#: Span kinds and their REQUIRED fields, in emission order. ``kind`` is
#: always the first key of a span dict; the listed fields follow in
#: this exact order (insertion-ordered dicts make that observable).
#: This table IS the trace schema — the golden-file test and
#: :func:`validate_trace` both read it, and tools/trace_summary.py
#: renders from it.
SPAN_FIELDS: Dict[str, tuple] = {
    # request entered the engine's wait queue (serve() start)
    "enqueued": ("t", "prompt_tokens", "max_new_tokens"),
    # request won a decode row; cache attribution of the admission
    "admitted": (
        "t", "row", "queue_s", "prompt_tokens", "budget",
        "matched_tokens", "shared_blocks", "restored_blocks",
        "cow_copy", "reserved_blocks",
    ),
    # one dispatch's worth of chunked-prefill progress for the row
    "prefill_chunk": ("t", "row", "wave", "from_pos", "to_pos"),
    # the row's first committed token (the ttft observation)
    "first_token": ("t", "row", "wave", "ttft_s"),
    # one dispatch's worth of committed decode tokens for the row;
    # accepted/rejected attribute the speculative tiers (0/0 for plain
    # decode — every committed token was one scheduled forward slot)
    "decode_wave": ("t", "row", "wave", "tokens", "accepted", "rejected"),
    # the row's lease mapped additional pool blocks this wave
    "lease_grow": ("t", "row", "wave", "blocks_mapped"),
    # terminal disposition (ok / deadline_exceeded / shed / drained)
    "terminal": ("t", "status", "new_tokens", "latency_s",
                 "finished_by_stop"),
    # engine death: the request was drained with its committed tokens
    # preserved for the failover requeue (not a terminal status — the
    # request lives on, on a replacement engine)
    "drained": ("t", "committed_tokens", "admitted"),
}


class ServeTracer:
    """Span timeline of one serve run, keyed by request index.

    The engine drives it::

        tracer = ServeTracer()
        engine.serve(requests, ...)   # engine constructed with tracer=
        dump = tracer.to_dict()       # JSON-safe, schema-stable

    ``to_dict()`` output::

        {"schema_version": 1,
         "requests": N,
         "spans": [{"request": i, "timeline": [span, ...]}, ...]}

    Timelines are in emission order, which is time order per request
    (the engine appends at wave boundaries). A tracer may be reused
    across serve() calls; ``begin()`` resets it."""

    def __init__(self) -> None:
        self._timelines: List[List[dict]] = []
        self._journeys: List[str] = []
        self.runs = 0

    def begin(self, n_requests: int,
              journeys: Optional[List[str]] = None) -> None:
        """Reset for a run of ``n_requests`` (the engine calls this
        right after its warm-up, before enqueuing spans). ``journeys``
        optionally names each request's fleet-level journey id (the
        engine reads ``ServeRequest.journey``) — the dump then carries
        it per request so the fleet's :class:`~nexus_tpu.obs.journey
        .JourneyBook` can stitch this run's timelines into
        cross-replica journeys."""
        n = int(n_requests)
        self._timelines = [[] for _ in range(n)]
        self._journeys = (
            [str(j or "") for j in journeys] if journeys is not None
            else [""] * n
        )
        self.runs += 1

    def extend(self, journey: str = "") -> int:
        """Open a timeline for ONE request that arrived MID-RUN
        (round 16 streamed admission: the engine polls its arrival
        source at wave boundaries and each delivery needs a timeline of
        its own) → the new request index. The dump's shape is identical
        to a begin()-sized run — a streamed request's timeline simply
        starts at its arrival ``t`` instead of 0."""
        self._timelines.append([])
        self._journeys.append(str(journey or ""))
        return len(self._timelines) - 1

    def event(self, request_idx: int, kind: str, **fields: Any) -> None:
        """Append one span. ``fields`` must be exactly
        ``SPAN_FIELDS[kind]`` — enforced cheaply by construction order
        here (the dict literal walks the schema), loudly by
        :func:`validate_trace` in tests and the obs smoke."""
        span = {"kind": kind}
        for f in SPAN_FIELDS[kind]:
            span[f] = fields[f]
        self._timelines[request_idx].append(span)

    def timeline(self, request_idx: int) -> List[dict]:
        return self._timelines[request_idx]

    def to_dict(self) -> dict:
        # "journey" rides per request entry only when ``begin`` was
        # given journey ids — single-engine dumps keep their exact
        # pre-round-15 shape (the golden test pins span fields either
        # way; entry keys gain nothing silently)
        journeys = any(self._journeys)
        return {
            "schema_version": TRACE_SCHEMA_VERSION,
            "requests": len(self._timelines),
            "spans": [
                (
                    {"request": i, "journey": self._journeys[i],
                     "timeline": list(tl)}
                    if journeys else
                    {"request": i, "timeline": list(tl)}
                )
                for i, tl in enumerate(self._timelines)
            ],
        }


def validate_trace(dump: dict) -> List[str]:
    """Schema check of a :meth:`ServeTracer.to_dict` dump → problem
    list (empty = valid). Checks: version, top-level shape, every span's
    kind is known, every span's keys are exactly ``("kind",) +
    SPAN_FIELDS[kind]`` IN ORDER, per-request ``t`` never decreases,
    and every non-empty timeline starts ``enqueued`` and ends
    ``terminal`` or ``drained``. The obs smoke (``make obs-smoke``) and
    the golden-file test both gate on this."""
    problems: List[str] = []
    if dump.get("schema_version") != TRACE_SCHEMA_VERSION:
        problems.append(
            f"schema_version {dump.get('schema_version')!r} != "
            f"{TRACE_SCHEMA_VERSION}"
        )
    spans = dump.get("spans")
    if not isinstance(spans, list):
        problems.append("spans is not a list")
        return problems
    for entry in spans:
        rid = entry.get("request")
        tl = entry.get("timeline", [])
        last_t: Optional[float] = None
        for j, span in enumerate(tl):
            kind = span.get("kind")
            if kind not in SPAN_FIELDS:
                problems.append(f"request {rid} span {j}: unknown kind "
                                f"{kind!r}")
                continue
            expect = ("kind",) + SPAN_FIELDS[kind]
            got = tuple(span.keys())
            if got != expect:
                problems.append(
                    f"request {rid} span {j} ({kind}): fields {got} != "
                    f"schema {expect}"
                )
            t = span.get("t")
            if not isinstance(t, (int, float)):
                problems.append(
                    f"request {rid} span {j} ({kind}): t is not a number"
                )
            elif last_t is not None and t < last_t:
                problems.append(
                    f"request {rid} span {j} ({kind}): t went backwards "
                    f"({last_t} -> {t})"
                )
            else:
                last_t = t
        if tl:
            if tl[0].get("kind") != "enqueued":
                problems.append(
                    f"request {rid}: timeline does not start 'enqueued'"
                )
            if tl[-1].get("kind") not in ("terminal", "drained"):
                problems.append(
                    f"request {rid}: timeline ends "
                    f"{tl[-1].get('kind')!r}, not terminal/drained"
                )
    return problems
