"""Flag-gated ``jax.profiler`` named-trace annotations.

With ``NEXUS_OBS_JAX_TRACE=1`` the serving engine wraps its dispatch
sites (decode chunk, insert wave, restore upload) in
``jax.profiler.TraceAnnotation`` scopes, so a profiler capture
(train/trainer.py:270's ``start_trace`` window, or ``jax.profiler``
driven externally) shows named serve phases instead of anonymous XLA
launches — and ``tools/trace_summary.py`` rolls them up by name.

CPU-safe: ``TraceAnnotation`` is a no-op-ish host-side scope on every
backend. Still flag-gated OFF by default because the hot loop enters
the scope once per dispatch and the engine's overhead budget
(docs/bench_serve_r12.json) is measured with the default
configuration; the flag is read ONCE at import (the sanitizers'
pattern — flipping it mid-process is not a supported path).
"""

from __future__ import annotations

import os


def _env_enabled() -> bool:
    return os.environ.get("NEXUS_OBS_JAX_TRACE", "").strip().lower() in (
        "1", "true", "yes", "on",
    )


TRACE_ANNOTATIONS_ENABLED = _env_enabled()


class _NullAnnotation:
    """Shared no-op context (the disabled path's entire cost: one
    attribute load + two trivial calls per dispatch)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullAnnotation()


def dispatch_annotation(name: str):
    """A context manager naming the enclosed dispatch in profiler
    traces — the shared null scope unless ``NEXUS_OBS_JAX_TRACE`` was
    set at import."""
    if not TRACE_ANNOTATIONS_ENABLED:
        return _NULL
    import jax

    return jax.profiler.TraceAnnotation(name)
