"""Serve-plane observability (PR 12).

Before this package the serving engine's metrics existed only as the
aggregate dict ``ServeEngine.serve()`` assembles at return — nothing
was observable *while* the engine ran, and a chaos postmortem had
nothing but terminal statuses to reconstruct from. This package is the
substrate the ROADMAP's fleet-scale and disaggregation items tune
against (vLLM/SGLang treat per-step engine stats and per-request
timelines the same way — PAPERS.md):

  * :mod:`~nexus_tpu.obs.trace` — :class:`ServeTracer`: a span timeline
    per request (enqueued → admitted → prefill chunks → decode-wave
    participations → terminal) with per-span cache attribution (radix
    hit tokens, host-tier restores, CoW copies, speculative
    accepted/rejected tokens, lease growth). Plain dict appends on the
    host — no JAX ops, no clock reads (the engine stamps every event
    with its own injectable clock).
  * :mod:`~nexus_tpu.obs.gauges` — :class:`LiveGauges` +
    :class:`RollingPercentiles`: wave-boundary publication of queue
    depth / running rows / free pool blocks / host-tier bytes / rolling
    ttft & queue percentiles into the in-process telemetry registry
    (and statsd when an address is configured — off by default).
  * :mod:`~nexus_tpu.obs.recorder` — :class:`FlightRecorder`: a bounded
    ring of recent wave events that dumps a JSON snapshot when a
    sanitizer trips, a deadline/shed storm hits, or the failover path
    drains a dead engine.
  * :mod:`~nexus_tpu.obs.exposition` — Prometheus-text + JSON snapshot
    renderers over the telemetry registry.
  * :mod:`~nexus_tpu.obs.profiling` — flag-gated ``jax.profiler``
    named-trace annotations around the engine's dispatch sites
    (CPU-safe; ``NEXUS_OBS_JAX_TRACE=1``).

Round 15 extends the substrate to the FLEET plane (docs/fleet.md):

  * :mod:`~nexus_tpu.obs.journey` — :class:`JourneyBook`: one stitched
    cross-replica timeline per request (the journey id threads from
    the fleet dispatch through the router into each engine's tracer
    and back through drain/requeue), with a token-conserving seam
    invariant across engine deaths and the SLO delay attribution
    (queue vs decode vs requeue-induced) behind goodput-under-SLO;
  * :mod:`~nexus_tpu.obs.fleet_log` — :class:`FleetDecisionLog`: the
    audit ring of every routing/scaling/failover decision WITH its
    gauge evidence, doubling as the fleet-wide flight recorder (death
    storms, autoscale flapping);
  * :mod:`~nexus_tpu.obs.federation` — :class:`FleetGauges`:
    fleet-level rollups (aggregate depth/blocks/committed,
    merged-sample ttft/latency percentiles, SLO attainment) over the
    per-replica tagged gauges, through the same exposition path.

Cost discipline: everything here must be cheap enough to leave on — the
serve bench's tracing A/B budgets <= 2% tok/s overhead
(docs/bench_serve_r12.json). Clock discipline: monotonic clocks only
(nexuslint NX-CLOCK003 enforces it for this package); wall-clock time
never enters a span, so timelines subtract cleanly and replay exactly
under the injectable-clock test discipline.
"""

from nexus_tpu.obs.exposition import (  # noqa: F401
    registry_snapshot,
    render_prometheus,
)
from nexus_tpu.obs.federation import (  # noqa: F401
    FleetGauges,
    fleet_rollup,
)
from nexus_tpu.obs.fleet_log import (  # noqa: F401
    FLEET_EVENT_FIELDS,
    FLEET_LOG_SCHEMA_VERSION,
    FleetDecisionLog,
    validate_fleet_log,
)
from nexus_tpu.obs.gauges import LiveGauges, RollingPercentiles  # noqa: F401
from nexus_tpu.obs.journey import (  # noqa: F401
    JOURNEY_ENTRY_FIELDS,
    JOURNEY_LEG_FIELDS,
    JOURNEY_SCHEMA_VERSION,
    JourneyBook,
    goodput_under_slo,
    journey_attribution,
    slo_verdicts,
    validate_journey,
)
from nexus_tpu.obs.recorder import (  # noqa: F401
    FlightRecorder,
    validate_flight_dump,
    write_dump,
)
from nexus_tpu.obs.trace import (  # noqa: F401
    SPAN_FIELDS,
    TRACE_SCHEMA_VERSION,
    ServeTracer,
    validate_trace,
)
