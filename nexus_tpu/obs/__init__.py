"""Serve-plane observability (PR 12).

Before this package the serving engine's metrics existed only as the
aggregate dict ``ServeEngine.serve()`` assembles at return — nothing
was observable *while* the engine ran, and a chaos postmortem had
nothing but terminal statuses to reconstruct from. This package is the
substrate the ROADMAP's fleet-scale and disaggregation items tune
against (vLLM/SGLang treat per-step engine stats and per-request
timelines the same way — PAPERS.md):

  * :mod:`~nexus_tpu.obs.trace` — :class:`ServeTracer`: a span timeline
    per request (enqueued → admitted → prefill chunks → decode-wave
    participations → terminal) with per-span cache attribution (radix
    hit tokens, host-tier restores, CoW copies, speculative
    accepted/rejected tokens, lease growth). Plain dict appends on the
    host — no JAX ops, no clock reads (the engine stamps every event
    with its own injectable clock).
  * :mod:`~nexus_tpu.obs.gauges` — :class:`LiveGauges` +
    :class:`RollingPercentiles`: wave-boundary publication of queue
    depth / running rows / free pool blocks / host-tier bytes / rolling
    ttft & queue percentiles into the in-process telemetry registry
    (and statsd when an address is configured — off by default).
  * :mod:`~nexus_tpu.obs.recorder` — :class:`FlightRecorder`: a bounded
    ring of recent wave events that dumps a JSON snapshot when a
    sanitizer trips, a deadline/shed storm hits, or the failover path
    drains a dead engine.
  * :mod:`~nexus_tpu.obs.exposition` — Prometheus-text + JSON snapshot
    renderers over the telemetry registry.
  * :mod:`~nexus_tpu.obs.profiling` — flag-gated ``jax.profiler``
    named-trace annotations around the engine's dispatch sites
    (CPU-safe; ``NEXUS_OBS_JAX_TRACE=1``).

Cost discipline: everything here must be cheap enough to leave on — the
serve bench's tracing A/B budgets <= 2% tok/s overhead
(docs/bench_serve_r12.json). Clock discipline: monotonic clocks only
(nexuslint NX-CLOCK003 enforces it for this package); wall-clock time
never enters a span, so timelines subtract cleanly and replay exactly
under the injectable-clock test discipline.
"""

from nexus_tpu.obs.exposition import (  # noqa: F401
    registry_snapshot,
    render_prometheus,
)
from nexus_tpu.obs.gauges import LiveGauges, RollingPercentiles  # noqa: F401
from nexus_tpu.obs.recorder import (  # noqa: F401
    FlightRecorder,
    validate_flight_dump,
    write_dump,
)
from nexus_tpu.obs.trace import (  # noqa: F401
    SPAN_FIELDS,
    TRACE_SCHEMA_VERSION,
    ServeTracer,
    validate_trace,
)
