"""Federated fleet gauges: per-replica serve gauges rolled up to one
fleet-level view.

The PR 12 live gauges made each ENGINE observable (``serve_*`` gauges
tagged ``engine:<id>``); the PR 14 fleet reads them per replica for
routing and autoscaling, but nothing answered fleet-level questions —
total backlog, aggregate committed tokens, the ttft a user of the
WHOLE fleet experiences. :class:`FleetGauges` publishes exactly that,
through the same registry and exposition path (`render_prometheus` /
`registry_snapshot` pick the ``fleet_*`` families up with no new
plumbing):

  * **sum rollups** over the live replicas' tagged gauges — queue
    depth, free pool blocks, committed tokens — read through the typed
    ``get_tagged`` path (a replica that never published is skipped,
    not counted as zero);
  * **merged-sample percentiles**: fleet ttft/latency p50/p95 come
    from ONE rolling window fed with every replica's finished requests
    (the fleet observes each stitched result). Averaging per-replica
    p95s would not be a percentile of anything; pooling the samples
    and ranking once is — the same nearest-rank estimator as every
    other percentile in the repo;
  * **goodput-under-SLO**: when the fleet is given an SLO, the rolling
    fraction of finished requests served ``ok`` within it.

Same discipline as the rest of the package: registry writes only, no
JAX, no clock reads (the publisher stamps with its poll sequence).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

from nexus_tpu.obs.gauges import RollingPercentiles
from nexus_tpu.utils.telemetry import (
    METRIC_FLEET_COMMITTED,
    METRIC_FLEET_FREE_BLOCKS,
    METRIC_FLEET_LATENCY_P50,
    METRIC_FLEET_LATENCY_P95,
    METRIC_FLEET_QUEUE_DEPTH,
    METRIC_FLEET_REPLICAS,
    METRIC_FLEET_SLO_ATTAINMENT,
    METRIC_FLEET_TTFT_P50,
    METRIC_FLEET_TTFT_P95,
    METRIC_SERVE_COMMITTED,
    METRIC_SERVE_FREE_BLOCKS,
    METRIC_SERVE_QUEUE_DEPTH,
    StatsdClient,
    get_client,
)

#: the per-replica gauges the sum rollups federate (name → fleet name)
_SUM_ROLLUPS = (
    (METRIC_SERVE_QUEUE_DEPTH, METRIC_FLEET_QUEUE_DEPTH),
    (METRIC_SERVE_FREE_BLOCKS, METRIC_FLEET_FREE_BLOCKS),
    (METRIC_SERVE_COMMITTED, METRIC_FLEET_COMMITTED),
)


def _sum_rollups(client: StatsdClient,
                 replica_ids: Sequence[str]) -> dict:
    """THE one sum-rollup loop (``{fleet name: total}``): a family
    appears only when at least one replica published it (a replica
    that never published is skipped, never counted as zero).
    ``FleetGauges.publish`` and :func:`fleet_rollup` both read through
    this, so the published gauges and the read-side rollup can never
    disagree about skip-vs-zero semantics or the tag shape."""
    out = {}
    for per_replica, fleet_name in _SUM_ROLLUPS:
        total, seen = 0.0, 0
        for rid in replica_ids:
            sample = client.get_tagged(per_replica, [f"engine:{rid}"])
            if sample is not None:
                total += float(sample.value)
                seen += 1
        if seen:
            out[fleet_name] = total
    return out


class FleetGauges:
    """Publish fleet-level rollups into the telemetry registry.

    The fleet monitor drives it: :meth:`observe_result` per stitched
    finished request (feeds the merged percentile windows and the SLO
    counter), :meth:`publish` once per monitor poll (reads the live
    replicas' tagged gauges, publishes the ``fleet_*`` family).
    ``tags`` (e.g. ``["fleet:<template>"]``) distinguish fleets sharing
    one process registry."""

    def __init__(self, client: Optional[StatsdClient] = None,
                 tags: Optional[List[str]] = None,
                 slo_s: float = 0.0,
                 ttft_window: int = 512,
                 latency_window: int = 512) -> None:
        self._client = client  # None → resolve the process default lazily
        self.tags = list(tags or [])
        self.slo_s = float(slo_s)
        self.ttft = RollingPercentiles(ttft_window)
        self.latency = RollingPercentiles(latency_window)
        self.finished = 0
        self.attained = 0
        self.publishes = 0

    @property
    def client(self) -> StatsdClient:
        if self._client is None:
            self._client = get_client()
        return self._client

    def observe_result(self, ttft_s: float, latency_s: float,
                       ok: bool) -> None:
        """Feed one stitched finished request. ``latency_s`` is the
        stitched end-to-end latency (dead generations included) and
        ``ok`` means the request completed (``ok``/``failed_over``) —
        shed/deadline terminals count as finished but never attained."""
        self.finished += 1
        if ok:
            self.ttft.add(float(ttft_s))
            self.latency.add(float(latency_s))
            if self.slo_s > 0 and float(latency_s) <= self.slo_s:
                self.attained += 1

    def publish(self, replica_ids: Sequence[str], stamp: float) -> None:
        """One poll's federated publication. ``stamp`` is the
        publisher's own freshness record (the fleet stamps its poll
        count — the same frozen-emitter story as the engine's wave
        stamp)."""
        c = self.client
        tags = self.tags or None
        s = float(stamp)
        for fleet_name, total in _sum_rollups(c, replica_ids).items():
            c.gauge(fleet_name, total, tags=tags, stamp=s)
        c.gauge(METRIC_FLEET_REPLICAS, len(replica_ids), tags=tags,
                stamp=s)
        for (name50, name95), win in (
            ((METRIC_FLEET_TTFT_P50, METRIC_FLEET_TTFT_P95), self.ttft),
            ((METRIC_FLEET_LATENCY_P50, METRIC_FLEET_LATENCY_P95),
             self.latency),
        ):
            p50, p95 = win.percentiles((0.50, 0.95))
            for name, v in ((name50, p50), (name95, p95)):
                if not math.isnan(v):
                    c.gauge(name, round(v, 6), tags=tags, stamp=s)
        if self.slo_s > 0 and self.finished:
            c.gauge(
                METRIC_FLEET_SLO_ATTAINMENT,
                round(self.attained / self.finished, 4),
                tags=tags, stamp=s,
            )
        self.publishes += 1


def fleet_rollup(replica_ids: Sequence[str],
                 client: Optional[StatsdClient] = None) -> dict:
    """One-shot read-side rollup over the per-replica tagged gauges —
    for tooling (`make fleet-obs-smoke`, dashboards) that wants the
    fleet totals WITHOUT owning a publisher: ``{fleet_name: total}``
    for every sum-rollup family at least one replica published, plus
    ``fleet_replicas_alive``."""
    c = client or get_client()
    out = {METRIC_FLEET_REPLICAS: len(replica_ids)}
    out.update(_sum_rollups(c, replica_ids))
    return out
