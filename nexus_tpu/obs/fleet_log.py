"""The fleet decision audit log: why the router, autoscaler, and
failover machinery did what they did.

PR 14's fleet plane makes decisions that move real traffic — which
replica a request lands on, when capacity grows or shrinks, which
engine gets fenced and drained — and until this log those decisions
left no record beyond their side effects. The ledger counted spills;
nothing said WHICH request spilled, off which home, justified by which
queue-depth reading. :class:`FleetDecisionLog` records every decision
WITH its evidence:

  * ``route``   — the affinity key, the rendezvous ranking, the
    power-of-two-choices candidate loads (the live queue-depth gauges
    + pending counts that justified a spill), the chosen replica;
  * ``scale_decision`` — the full :class:`~nexus_tpu.fleet.autoscaler
    .ScaleDecision` (target/current/reason, breach/clear streaks,
    stale set) plus the per-replica :class:`ReplicaSample` vitals it
    was computed from;
  * ``spawn`` / ``kill`` / ``death_confirmed`` — replica lifecycle,
    with detection seconds and whether a live engine had to be fenced;
  * ``drain``  — the failover drain→requeue mapping: which journeys
    left which replica, and why (death vs graceful scale-down). The
    journeys' subsequent ``route`` events ARE the requeue side of the
    mapping — the audit reads end to end.

Same discipline as every obs module (docs/observability.md): host-side
dict appends into a bounded ring, schema (field names AND order) frozen
by :data:`FLEET_EVENT_FIELDS` and pinned by the golden file, monotonic
clock only — the log stamps ``t`` from the clock its owner injects (the
fleet's own), never a wall clock, so audit timelines subtract cleanly
against the same run's journey ``t_start``s.

The log doubles as the FLEET-WIDE flight recorder: :meth:`trip`
freezes the ring — plus the affected cohort's stitched journeys — on
death storms and autoscale flapping, the two failure shapes a
single-engine recorder cannot see (each engine's own ring shows one
drain; only the fleet view shows three in a row, or a scale-up
chasing a scale-down).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

FLEET_LOG_SCHEMA_VERSION = 1

#: Event kinds and their REQUIRED fields, in emission order. Every
#: event is ``{"seq": ..., "t": ..., "kind": ...}`` followed by exactly
#: these fields in this order — the golden file pins the table, and
#: :func:`validate_fleet_log` enforces it (the ServeTracer pattern).
FLEET_EVENT_FIELDS: Dict[str, tuple] = {
    # one routing decision: key is the affinity digest (hex prefix),
    # ranked the rendezvous candidate order, loads the per-candidate
    # spill-over signal actually read (empty when no choice existed)
    "route": ("journey", "key", "policy", "ranked", "loads", "chosen",
              "spilled", "spill_threshold"),
    # one autoscaler poll: the ScaleDecision + the ReplicaSample
    # evidence (one dict per replica: replica/busy/ttft_p95_s/
    # queue_depth/seq, NaN signals recorded as None)
    "scale_decision": ("current", "target", "reason", "breach_streak",
                       "clear_streak", "stale", "samples"),
    # replica lifecycle
    "spawn": ("replica",),
    "kill": ("replica", "hard"),
    "death_confirmed": ("replica", "detection_s", "fenced_alive"),
    # the drain→requeue mapping: journeys that left `replica`; their
    # re-routing shows up as subsequent `route` events
    "drain": ("replica", "reason", "journeys"),
}


class FleetDecisionLog:
    """Bounded audit ring of fleet-plane decisions (see module
    docstring). ``clock`` is injectable (the fleet passes its own);
    ``t`` is seconds since the log's construction — the fleet run's
    time base, shared with journey ``t_start``s.

    Thread-safety: routed from the monitor thread and workers race on
    the ring — every append/read holds ``_lock``."""

    def __init__(self, capacity: int = 4096, max_dumps: int = 8,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self.dumps: deque = deque(maxlen=int(max_dumps))
        self.last_dump: Optional[dict] = None

    def record(self, kind: str, **fields) -> None:
        """Append one decision. ``fields`` must be exactly
        ``FLEET_EVENT_FIELDS[kind]`` — enforced by construction order
        here (the dict literal walks the schema; a missing field is a
        loud KeyError at the call site, not a drifted dump)."""
        # the clock is read INSIDE the lock: racing recorders (monitor
        # thread + a chaos kill) must append in the same order they
        # stamp, or the ring's time axis could run backwards against
        # its seq order
        with self._lock:
            t = round(self._clock() - self._t0, 6)
            ev = {"seq": self._seq, "t": t, "kind": kind}
            for f in FLEET_EVENT_FIELDS[kind]:
                ev[f] = fields[f]
            self._seq += 1
            self._ring.append(ev)

    @property
    def events_recorded(self) -> int:
        with self._lock:
            return self._seq

    def events(self, kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            evs = list(self._ring)
        if kind is None:
            return evs
        return [e for e in evs if e["kind"] == kind]

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "schema_version": FLEET_LOG_SCHEMA_VERSION,
                "events_recorded": self._seq,
                "events": [dict(e) for e in self._ring],
            }

    def trip(self, reason: str, detail: Optional[dict] = None,
             journeys: Optional[dict] = None) -> dict:
        """Freeze the ring into a fleet postmortem dump — the fleet-wide
        flight-recorder trip. ``journeys`` is the affected cohort's
        stitched journey dump (:meth:`JourneyBook.to_dict`), embedded so
        the postmortem shows both WHAT the fleet decided and what each
        affected request lived through."""
        with self._lock:
            t = round(self._clock() - self._t0, 6)
            dump = {
                "schema_version": FLEET_LOG_SCHEMA_VERSION,
                "reason": reason,
                "tripped_t": t,
                "detail": dict(detail or {}),
                "events": [dict(e) for e in self._ring],
                "journeys": dict(journeys or {"journeys": []}),
            }
        self.dumps.append(dump)
        self.last_dump = dump
        return dump


def validate_fleet_log(dump: dict) -> List[str]:
    """Schema check of a :meth:`FleetDecisionLog.to_dict` (or
    :meth:`trip`) dump → problem list (empty = valid): version, every
    event a known kind with keys exactly ``("seq", "t", "kind") +
    FLEET_EVENT_FIELDS[kind]`` in order, ``seq`` strictly increasing,
    ``t`` numeric and non-decreasing. Trip dumps additionally need a
    reason. The golden-file test and ``make fleet-obs-smoke`` gate on
    this."""
    problems: List[str] = []
    if dump.get("schema_version") != FLEET_LOG_SCHEMA_VERSION:
        problems.append(
            f"schema_version {dump.get('schema_version')!r} != "
            f"{FLEET_LOG_SCHEMA_VERSION}"
        )
    if "tripped_t" in dump and not dump.get("reason"):
        problems.append("trip dump missing reason")
    events = dump.get("events")
    if not isinstance(events, list):
        problems.append("events is not a list")
        return problems
    last_seq = -1
    last_t: Optional[float] = None
    for ev in events:
        kind = ev.get("kind")
        if kind not in FLEET_EVENT_FIELDS:
            problems.append(
                f"event seq={ev.get('seq')}: unknown kind {kind!r}"
            )
            continue
        expect = ("seq", "t", "kind") + FLEET_EVENT_FIELDS[kind]
        got = tuple(ev.keys())
        if got != expect:
            problems.append(
                f"event seq={ev.get('seq')} ({kind}): fields {got} != "
                f"schema {expect}"
            )
        seq = ev.get("seq")
        if not isinstance(seq, int) or seq <= last_seq:
            problems.append(
                f"event seq {seq!r} not strictly increasing after "
                f"{last_seq}"
            )
        else:
            last_seq = seq
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            problems.append(f"event seq={seq}: t is not a number")
        elif last_t is not None and t < last_t:
            problems.append(
                f"event seq={seq}: t went backwards ({last_t} -> {t})"
            )
        else:
            last_t = t
    return problems
