"""TPU compute ops: norms, rotary embeddings, attention (XLA and Pallas
flash kernels), ring attention for sequence parallelism, MoE routing.

All ops are pure functions over jnp arrays, designed for jit/shard_map:
static shapes, no data-dependent Python control flow (SURVEY.md §2c — this
entire layer is NEW vs. the reference, which contains no model/attention
code).
"""

from nexus_tpu.ops.norms import rms_norm
from nexus_tpu.ops.rope import apply_rope, rope_cos_sin
from nexus_tpu.ops.attention import attention
from nexus_tpu.ops.ring_attention import ring_attention
from nexus_tpu.ops.moe import top_k_routing, moe_dispatch_dense

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_cos_sin",
    "attention",
    "ring_attention",
    "top_k_routing",
    "moe_dispatch_dense",
]
