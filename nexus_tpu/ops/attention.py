"""Causal attention: XLA einsum path + Pallas flash kernel.

The XLA path is the always-correct reference (XLA already fuses
softmax(QK^T)V reasonably); the Pallas kernel is the HBM-bandwidth-optimal
flash-attention (online softmax, O(seq) memory) for the TPU hot path.
``attention(...)`` picks the kernel on TPU when shapes are tile-friendly and
falls back to XLA elsewhere (CPU tests run the kernel via interpret mode).

GQA (n_q_heads > n_kv_heads) is supported everywhere; K/V heads are
broadcast to query heads.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU too; guard only for safety
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)

# Per-row statistics (logsumexp, delta) are stored broadcast across a small
# minor dimension: the TPU lowering requires every block's last two dims to
# be (8k, 128m) *or equal to the array's*, so a 2-D (1, block_q) row-vector
# block is not tileable, but a 3-D block whose minor dim spans the whole
# (bh, sq, LANES) array is. 8 lanes (not the 128 the reference JAX TPU
# kernel uses) keeps the HBM padding tax 16x smaller — the buffers carry
# one value per row either way.
LANES = 8


def _kv_row(bh, hq: int, hkv: int, n_rep: int):
    """Grid row (over B*Hq) → K/V row (over B*Hkv) for GQA head sharing.

    THE load-bearing invariant of the no-repeat GQA layout: must match
    ``_repeat_kv``'s contiguous-group convention (query heads g*n_rep..
    (g+1)*n_rep-1 read kv head g) and is shared by the fwd and both bwd
    kernels' index maps."""
    return (bh // hq) * hkv + (bh % hq) // n_rep


def _tile_needed(i, j, *, block_q: int, block_k: int, q_offset: int,
                 causal: bool, window: int = 0):
    """Does k-tile ``j`` intersect the visible band of q-tile ``i``?

    Shared by the fwd / bwd-dq / bwd-dkv kernels (the dkv kernel calls it
    with the same (i, j) semantics — i is always the q tile). Causal upper
    bound: the tile's smallest k position must be visible to the q tile's
    largest row (``j*block_k <= i*block_q + block_q - 1 + q_offset``).
    ``window > 0`` (sliding-window attention) adds the lower bound: the
    tile's largest k position must be inside the window of the tile's
    OLDEST (smallest) q row — the most permissive row for the lower
    bound, mirroring how the upper bound uses the newest row."""
    if not causal:
        return True
    needed = j * block_k <= i * block_q + (block_q - 1) + q_offset
    if window > 0:
        # newest visible position for the tile's smallest q row is
        # i*block_q + q_offset; its window floor is that - window + 1
        needed = needed & (
            j * block_k + (block_k - 1) > i * block_q + q_offset - window
        )
    return needed


def _last_needed_k_tile(i, *, block_q: int, block_k: int, q_offset: int):
    """Largest k-tile index the causal triangle of q-tile ``i`` touches.
    Clamped at 0: a negative q_offset can push the triangle entirely before
    k-tile 0 (fully-masked rows) — the fetch must still be in range."""
    return jnp.maximum(
        (i * block_q + (block_q - 1) + q_offset) // block_k, 0
    )


def _first_needed_q_tile(j, *, block_q: int, block_k: int, q_offset: int):
    """Smallest q-tile index whose causal triangle touches k-tile ``j``."""
    return jnp.maximum(j * block_k - q_offset, 0) // block_q


def _first_windowed_k_tile(i, *, block_q: int, block_k: int, q_offset: int,
                           window: int):
    """Smallest k-tile index inside q-tile ``i``'s sliding window (the
    lower-bound mirror of _last_needed_k_tile): the OLDEST q row's window
    floor is ``i*block_q + q_offset - window + 1`` — clamping to it keeps
    every fetch that any row of the tile still needs."""
    return jnp.maximum(
        (i * block_q + q_offset - window + 1) // block_k, 0
    )


def _last_windowed_q_tile(j, *, block_q: int, block_k: int, q_offset: int,
                          window: int, n_q_tiles: int):
    """Largest q-tile index whose window still reaches k-tile ``j``:
    needed iff ``j*block_k + block_k - 1 > i*block_q + q_offset - window``."""
    bound = (j * block_k + block_k - 1 + window - 1 - q_offset) // block_q
    return jnp.clip(bound, 0, n_q_tiles - 1)


def _window_tile_span(block_fixed: int, block_scan: int, window: int) -> int:
    """Max number of scan-dim tiles a fixed tile's sliding window can touch.

    For k tiles under a q tile (or q tiles over a k tile) the first/last
    needed indices differ by at most ``floor((block_fixed + window - 2) /
    block_scan) + 1`` (numerator = newest-row upper bound minus oldest-row
    window floor), so the span is that + 1 — a STATIC bound, independent of
    the tile position and q_offset. This is what lets the windowed kernels
    compact their grid: instead of enumerating every scan tile and
    `pl.when`-skipping the out-of-window ones (which still costs a grid
    step and, on the clamped index maps, a DMA fetch — measured at only
    ~1.2-1.4x instead of the tile-count ratio, docs/PERF.md), the grid's
    scan dimension shrinks to this span and the kernel offsets the local
    index by the window's first tile."""
    return (block_fixed + window - 2) // block_scan + 2


def _compact_kv_tile(i, j, *, block_q: int, block_k: int, q_offset: int,
                     window: int, nk_total: int):
    """Local→global k-tile index for the COMPACTED windowed grids: offset
    the grid-local ``j`` by q-tile ``i``'s first in-window tile, elide
    beyond-diagonal fetches (min with the causal last), and keep the fetch
    in range when the footprint overhangs the array. Shared by the forward
    kv index map and the backward dq kernel's k map — the two must agree
    for DMA elision and the kernels' needed-guards to line up."""
    return jnp.clip(
        jnp.minimum(
            j + _first_windowed_k_tile(
                i, block_q=block_q, block_k=block_k, q_offset=q_offset,
                window=window,
            ),
            _last_needed_k_tile(
                i, block_q=block_q, block_k=block_k, q_offset=q_offset
            ),
        ),
        0,
        nk_total - 1,
    )


def _repeat_kv(k: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """(B, S, Hkv, D) → (B, S, Hkv*n_rep, D) broadcasting each kv head."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(
        k[:, :, :, None, :], (b, s, h, n_rep, d)
    ).reshape(b, s, h * n_rep, d)


def attention_xla(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    q_offset: int = 0,
    mask_value: float = DEFAULT_MASK_VALUE,
    window: int = 0,
) -> jnp.ndarray:
    """Reference attention. q: (B, Sq, Hq, D); k/v: (B, Skv, Hkv, D).

    ``q_offset``: global position of q[0] relative to k[0] (decode-time
    steps and sequence-parallel shards pass nonzero offsets). ``window > 0``
    restricts each row to the newest ``window`` positions (sliding-window
    attention, the Mixtral-8x7B convention; requires ``causal``)."""
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        rows = lax.broadcasted_iota(jnp.int32, (sq, sk), 0) + q_offset
        cols = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        visible = cols <= rows
        if window > 0:
            visible = visible & (cols > rows - window)
        logits = jnp.where(visible, logits, mask_value)
    elif window > 0:
        raise ValueError("window requires causal attention")
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# --------------------------------------------------------------------- pallas


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int, q_offset: int,
    window: int = 0, compact_nk: int = 0,
):
    i = pl.program_id(1)  # q block
    jl = pl.program_id(2)  # k block (grid-local; == global unless compacted)
    nk = pl.num_programs(2)
    # compacted windowed grid (compact_nk = TOTAL k tiles): the scan dim
    # only spans the window's tile footprint; the global k tile is the
    # local index offset by the q tile's first in-window tile
    if compact_nk:
        j = jl + _first_windowed_k_tile(
            i, block_q=block_q, block_k=block_k, q_offset=q_offset,
            window=window,
        )
    else:
        j = jl

    @pl.when(jl == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal tile skipping: a k tile entirely above the diagonal contributes
    # exp(-inf)=0 to every row of this q tile — skip its matmuls (~2x FLOPs
    # at long seq; the K/V fetches for skipped tiles are elided by the
    # clamped index maps in _flash_impl, which repeat the last needed block
    # index so Pallas sees a no-op DMA). Exact: accumulators are untouched.
    needed = _tile_needed(
        i, j, block_q=block_q, block_k=block_k, q_offset=q_offset,
        causal=causal, window=window,
    )
    if compact_nk:
        # the offset local index can land past the real tile range (the
        # window footprint overhangs the diagonal or the array end)
        needed = needed & (j < compact_nk)

    @pl.when(needed)
    def _compute():
        q = q_ref[0]  # (block_q, D)
        k = k_ref[0]  # (block_k, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (block_q, block_k)

        if causal:
            rows = (
                lax.broadcasted_iota(jnp.int32, s.shape, 0)
                + i * block_q + q_offset
            )
            cols = lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * block_k
            visible = cols <= rows
            if window > 0:
                visible = jnp.logical_and(visible, cols > rows - window)
            s = jnp.where(visible, s, DEFAULT_MASK_VALUE)

        m_prev = m_ref[:, :1]  # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(jl == nk - 1)
    def _finish():
        # guard against fully-masked rows (padding): l == 0 → output 0
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        # logsumexp per q row, saved for the backward recompute of P;
        # m/l scratches already carry the value on every lane
        lse_ref[0] = m_ref[:] + jnp.log(jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:]))


def _fit_block(seq: int, want: int) -> int:
    """Pick the block size: ``seq`` itself when it fits under ``want``
    (a single block spanning the array is always tileable), else the
    largest power of two ≤ ``want`` that divides ``seq``, else 0 (no
    usable block — caller raises).

    Measured on v5e at (B8, H16, S2048, D64): 1024×1024 blocks run the fwd
    kernel at 31.8 causal-TF/s vs 5.4 at 128×128 — per-instance MXU work
    amortizes the grid/DMA overhead, and VMEM stays comfortable (the f32
    probability tile is bq×bk×4 = 4 MB at 1024²)."""
    if seq <= want:
        return seq
    b = 1
    while b * 2 <= want:
        b *= 2
    while b >= 8 and seq % b:
        b //= 2
    # blocks far below the requested size mean an awkward sequence (e.g.
    # 1032 = 8·129, whose best power-of-two divisor is 8): per the table in
    # docs/PERF.md tiny blocks are an order-of-magnitude perf cliff, so
    # refuse rather than silently crawl
    return b if b >= 8 and b >= want // 8 and seq % b == 0 else 0


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    q_offset: int = 0,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    window: int = 0,
) -> jnp.ndarray:
    """Pallas flash attention. Same signature/semantics as attention_xla.

    Differentiable: custom VJP — flash forward saves (O, logsumexp), and
    dedicated Pallas dq / dk+dv kernels recompute P blockwise on the
    backward pass (no S×S materialization; see _flash_bwd_impl)."""
    if interpret is None:
        from nexus_tpu.utils.hw import is_tpu

        interpret = not is_tpu()
    return _flash(
        q, k, v, (causal, q_offset, block_q, block_k, interpret, window)
    )


def flash_attention_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    q_offset: int = 0,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    window: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Flash attention that ALSO returns the per-row logsumexp as a
    differentiable output: (out (B,Sq,Hq,D), lse (B,Sq,Hq) f32).

    The lse output is what makes block-merged attention (ring attention's
    per-visiting-block partials) exactly differentiable: for
    ``lse_i = logsumexp_j(s_ij)`` the cotangent folds into the score grads
    as ``dL/ds_ij += P_ij·ḡ_lse_i`` — the same shape as the delta term the
    backward kernels already subtract, so the bwd pass just computes
    ``delta = rowsum(dO⊙O) − ḡ_lse`` and the kernels stay untouched."""
    if interpret is None:
        from nexus_tpu.utils.hw import is_tpu

        interpret = not is_tpu()
    return _flash_lse(
        q, k, v, (causal, q_offset, block_q, block_k, interpret, window)
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_lse(q, k, v, opts):
    out, lse = _flash_impl(q, k, v, opts)
    return out, _lse_rows(lse, q.shape)


def _lse_rows(lse: jnp.ndarray, q_shape) -> jnp.ndarray:
    """(B*Hq, Sq, LANES) lane-broadcast buffer → (B, Sq, Hq) rows."""
    b, sq, hq, _ = q_shape
    return lse[:, :, 0].reshape(b, hq, sq).transpose(0, 2, 1)


def _flash_lse_fwd_rule(q, k, v, opts):
    out, lse = _flash_impl(q, k, v, opts)
    out, lse = _tag_residuals(out, lse)
    return (out, _lse_rows(lse, q.shape)), (q, k, v, out, lse)


def _flash_lse_bwd_rule(opts, residuals, cts):
    q, k, v, out, lse = residuals
    g_out, g_lse = cts
    return _flash_bwd_impl(q, k, v, out, lse, g_out, opts, g_lse=g_lse)


_flash_lse.defvjp(_flash_lse_fwd_rule, _flash_lse_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, opts):
    out, _ = _flash_impl(q, k, v, opts)
    return out


def _tag_residuals(out, lse):
    """Name the flash VJP residuals so the 'dots_attn' remat policy can
    save them: without this, rematerialized backward passes rerun the
    whole forward kernel just to rebuild (out, lse) (ops/remat.py).
    Shared by the plain and lse-returning flash entry points."""
    from jax.ad_checkpoint import checkpoint_name

    from nexus_tpu.ops.remat import ATTN_LSE_NAME, ATTN_OUT_NAME

    return (
        checkpoint_name(out, ATTN_OUT_NAME),
        checkpoint_name(lse, ATTN_LSE_NAME),
    )


def _flash_fwd_rule(q, k, v, opts):
    out, lse = _flash_impl(q, k, v, opts)
    out, lse = _tag_residuals(out, lse)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(opts, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_bwd_impl(q, k, v, out, lse, g, opts)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _fold_heads(x):
    """(B, S, H, D) → (B*H, S, D)."""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _out_struct(shape, dtype, like):
    """ShapeDtypeStruct carrying ``like``'s varying-axes set: under
    shard_map manual axes (ring attention's per-block calls) pallas_call
    outputs must declare their vma explicitly."""
    try:
        vma = jax.typeof(like).vma
        if vma is not None:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):  # older jax: no vma plumbing
        pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _flash_impl(q, k, v, opts):
    causal, q_offset, block_q, block_k, interpret, window = opts
    if window > 0 and not causal:
        raise ValueError("window requires causal attention")
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    n_rep = hq // hkv
    sk = k.shape[1]
    block_q = _fit_block(sq, block_q)
    block_k = _fit_block(sk, block_k)
    # TPU tiling: a block's second-to-minor dim must be 8-divisible or span
    # the whole array dim (the minor dim of the q/k/v tiles is d, full-span)
    if (
        not block_q
        or not block_k
        or (block_q % 8 and block_q != sq)
        or (block_k % 8 and block_k != sk)
    ):
        raise ValueError(
            "flash_attention requires tileable sequences (pad the sequence "
            f"or pass explicit blocks): sq={sq} (block_q={block_q}), "
            f"sk={sk} (block_k={block_k})"
        )

    # fold heads into the grid's batch dim: q (B*Hq, S, D); K/V stay at
    # their native (B*Hkv, S, D) — GQA is handled by the index map (each
    # query-head grid row reads its group's kv row), not by materializing
    # n_rep copies of K/V in HBM
    qf, kf, vf = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    kv_row = functools.partial(_kv_row, hq=hq, hkv=hkv, n_rep=n_rep)

    # windowed grid compaction: when the window's static tile footprint is
    # smaller than the full k range, the grid's scan dim shrinks to it and
    # every index is offset by the q tile's first in-window tile — skipped
    # tiles stop costing grid steps and DMA fetches entirely
    nk_total = sk // block_k
    nkw = (
        min(nk_total, _window_tile_span(block_q, block_k, window))
        if (causal and window > 0)
        else nk_total
    )
    compact = nkw < nk_total

    kernel = functools.partial(
        _flash_kernel,
        scale=d ** -0.5,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        q_offset=q_offset,
        window=window,
        compact_nk=nk_total if compact else 0,
    )

    # clamp skipped k tiles onto the last needed one: Pallas elides the DMA
    # when the requested block index repeats, so above-diagonal tiles cost
    # neither FLOPs (pl.when in the kernel) nor HBM fetches
    if causal and compact:
        def kv_index(bh, i, j):
            jc = _compact_kv_tile(
                i, j, block_q=block_q, block_k=block_k, q_offset=q_offset,
                window=window, nk_total=nk_total,
            )
            return (kv_row(bh), jc, 0)
    elif causal:
        def kv_index(bh, i, j):
            jc = jnp.minimum(
                j,
                _last_needed_k_tile(
                    i, block_q=block_q, block_k=block_k, q_offset=q_offset
                ),
            )
            if window > 0:
                # pre-window tiles repeat the first in-window index so
                # their DMAs are elided alongside the pl.when-skipped
                # compute (the window mirror of the causal upper clamp);
                # the outer min keeps the fetch in range when the window
                # floor itself lands past the last k tile (small window +
                # large q_offset, e.g. later ring hops) — compute there is
                # pl.when-skipped, any valid block satisfies the DMA
                jc = jnp.minimum(
                    jnp.maximum(
                        jc,
                        _first_windowed_k_tile(
                            i, block_q=block_q, block_k=block_k,
                            q_offset=q_offset, window=window,
                        ),
                    ),
                    sk // block_k - 1,
                )
            return (kv_row(bh), jc, 0)
    else:
        def kv_index(bh, i, j):
            return (kv_row(bh), j, 0)

    grid = (b * hq, sq // block_q, nkw)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            _out_struct((b * hq, sq, d), q.dtype, qf),
            _out_struct((b * hq, sq, LANES), jnp.float32, qf),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, hq, sq, d).transpose(0, 2, 1, 3), lse


# ------------------------------------------------------------ flash backward
#
# Standard flash-attention backward (Dao 2022): with S = scale·QKᵀ,
# P = softmax(S) recomputed blockwise from the saved logsumexp,
#   D  = rowsum(dO ⊙ O)
#   dP = dO Vᵀ
#   dS = P ⊙ (dP − D)
#   dQ = scale · dS K      (kernel 1: grid over q blocks, scan k blocks)
#   dK = scale · dSᵀ Q     (kernel 2: grid over k blocks, scan q blocks)
#   dV = Pᵀ dO             (kernel 2)
# Both kernels recompute P from (q, k, lse) — O(S/block) memory, no S×S
# materialization (the previous backward fell back to the XLA einsum path).


def _flash_bwd_p(q, k, lse, *, scale, causal, i, j, block_q, block_k,
                 q_offset, window=0):
    """Recompute the (block_q, block_k) probability tile. ``lse``:
    (block_q, 1) column vector (lane 0 of the lane-broadcast buffer)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    p = jnp.exp(s - lse)
    if causal:
        rows = lax.broadcasted_iota(jnp.int32, p.shape, 0) + i * block_q + q_offset
        cols = lax.broadcasted_iota(jnp.int32, p.shape, 1) + j * block_k
        visible = cols <= rows
        if window > 0:
            visible = jnp.logical_and(visible, cols > rows - window)
        p = jnp.where(visible, p, 0.0)
    return p


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref,
    *, scale, causal, block_q, block_k, q_offset, window=0, compact_nk=0,
):
    i = pl.program_id(1)  # q block (parallel)
    jl = pl.program_id(2)  # k block (sequential accumulation; grid-local)
    nk = pl.num_programs(2)
    if compact_nk:  # compacted windowed grid — see _flash_kernel
        j = jl + _first_windowed_k_tile(
            i, block_q=block_q, block_k=block_k, q_offset=q_offset,
            window=window,
        )
    else:
        j = jl

    @pl.when(jl == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    needed = _tile_needed(
        i, j, block_q=block_q, block_k=block_k, q_offset=q_offset,
        causal=causal, window=window,
    )
    if compact_nk:
        needed = needed & (j < compact_nk)

    @pl.when(needed)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse, delta = lse_ref[0][:, :1], delta_ref[0][:, :1]
        p = _flash_bwd_p(
            q, k, lse, scale=scale, causal=causal, i=i, j=j,
            block_q=block_q, block_k=block_k, q_offset=q_offset,
            window=window,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, bk)
        ds = p * (dp - delta)  # (bq, bk) f32
        acc_ref[:] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jl == nk - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref,
    *, scale, causal, block_q, block_k, q_offset, n_rep, window=0,
    compact_nq=0,
):
    j = pl.program_id(1)  # k block (parallel, one per KV head row)
    # sequential dim enumerates (q tile, query-head group member): the
    # whole group accumulates into ONE kv-shaped scratch, so dK/dV leave
    # the kernel already group-summed — no per-q-head (B*Hq, Sk, D)
    # materialization + XLA reduction pass afterwards (GQA)
    t = pl.program_id(2)
    nt = pl.num_programs(2)
    # tile-fast ordering (i = t % n_q_tiles, member = t // n_q_tiles): the
    # q row stays constant across each member's whole tile run, so the
    # causal qi clamp still repeats block indices on skipped tiles and
    # their DMAs stay elided (member-fast ordering would cycle rows and
    # defeat the elision)
    i = t % (nt // n_rep)  # q tile (grid-local)
    if compact_nq:
        # compacted windowed grid (compact_nq = TOTAL q tiles): the local
        # q-tile index offsets from the k tile's first causally-needed q
        # tile; the window's upper bound and the array end are enforced by
        # the needed-guard below
        i = i + _first_needed_q_tile(
            j, block_q=block_q, block_k=block_k, q_offset=q_offset
        )

    @pl.when(t == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    # a q tile entirely above the diagonal sees P == 0 for this k tile
    needed = _tile_needed(
        i, j, block_q=block_q, block_k=block_k, q_offset=q_offset,
        causal=causal, window=window,
    )
    if compact_nq:
        needed = needed & (i < compact_nq)

    @pl.when(needed)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse, delta = lse_ref[0][:, :1], delta_ref[0][:, :1]
        p = _flash_bwd_p(
            q, k, lse, scale=scale, causal=causal, i=i, j=j,
            block_q=block_q, block_k=block_k, q_offset=q_offset,
            window=window,
        )
        dv_acc_ref[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # Pᵀ dO: (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk_acc_ref[:] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # dSᵀ Q: (bk, d)

    @pl.when(t == nt - 1)
    def _finish():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, out, lse, g, opts, g_lse=None):
    causal, q_offset, block_q, block_k, interpret, window = opts
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    n_rep = hq // hkv
    sk = k.shape[1]
    block_q = _fit_block(sq, block_q)
    block_k = _fit_block(sk, block_k)

    # K/V stay un-repeated (B*Hkv, S, D), shared across each query-head
    # group via the index maps — mirrors the forward. dK/dV are still
    # produced per *query* head (each grid row accumulates independently)
    # and group-summed back onto kv heads at the end.
    qf, kf, vf = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    dof, of = _fold_heads(g), _fold_heads(out)
    bh = b * hq
    kv_row = functools.partial(_kv_row, hq=hq, hkv=hkv, n_rep=n_rep)

    # D = rowsum(dO ⊙ O) — cheap elementwise+reduce; plain XLA. An lse
    # cotangent (flash_attention_lse) folds in here with a minus sign:
    # dL/ds_ij = P_ij·(dP_ij − D_i + ḡ_lse_i), and the kernels compute
    # ds = p·(dp − delta). Broadcast across the lane dim to match the LSE
    # buffer layout (see LANES).
    delta_rows = jnp.sum(
        dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1
    )  # (BH, Sq)
    if g_lse is not None:
        # (B, Sq, Hq) → (B*Hq, Sq), matching the folded-head layout
        delta_rows = delta_rows - g_lse.astype(jnp.float32).transpose(
            0, 2, 1
        ).reshape(bh, sq)
    delta = jnp.broadcast_to(delta_rows[..., None], (bh, sq, LANES))

    common = dict(
        scale=d ** -0.5, causal=causal,
        block_q=block_q, block_k=block_k, q_offset=q_offset, window=window,
    )

    # windowed grid compaction (mirrors the forward): the dq kernel's k
    # scan and the dkv kernel's q scan shrink to the window's static tile
    # footprint when that is smaller than the full range
    nk_total = sk // block_k
    nq_total = sq // block_q
    windowed = causal and window > 0
    nkw = (
        min(nk_total, _window_tile_span(block_q, block_k, window))
        if windowed else nk_total
    )
    nqw = (
        min(nq_total, _window_tile_span(block_k, block_q, window))
        if windowed else nq_total
    )
    compact_k = nkw < nk_total  # dq kernel's scan dim
    compact_q = nqw < nq_total  # dkv kernel's scan dim

    # clamped index maps mirror the forward kernel: skipped tiles repeat the
    # last (dq; k side) / first (dkv; q side) needed block index so their
    # DMAs are elided alongside the pl.when-skipped compute
    if causal and compact_k:
        def kj(i, j):
            # local j → global (same map as the forward's compacted
            # kv_index — shared helper keeps fwd/bwd elision in agreement)
            return _compact_kv_tile(
                i, j, block_q=block_q, block_k=block_k, q_offset=q_offset,
                window=window, nk_total=nk_total,
            )
    elif causal:
        def kj(i, j):
            jc = jnp.minimum(
                j,
                _last_needed_k_tile(
                    i, block_q=block_q, block_k=block_k, q_offset=q_offset
                ),
            )
            if window > 0:
                # same upper clamp as the forward kv_index: the window
                # floor can exceed the last k tile when window <= q_offset
                jc = jnp.minimum(
                    jnp.maximum(
                        jc,
                        _first_windowed_k_tile(
                            i, block_q=block_q, block_k=block_k,
                            q_offset=q_offset, window=window,
                        ),
                    ),
                    sk // block_k - 1,
                )
            return jc
    else:
        def kj(i, j):
            return j

    if causal and compact_q:
        def qi(j, i):
            # local i → global: offset by the k tile's first causally-
            # needed q tile, elide post-window fetches (min with the last
            # in-window q tile), keep the fetch in range
            return jnp.clip(
                jnp.minimum(
                    i + _first_needed_q_tile(
                        j, block_q=block_q, block_k=block_k,
                        q_offset=q_offset,
                    ),
                    _last_windowed_q_tile(
                        j, block_q=block_q, block_k=block_k,
                        q_offset=q_offset, window=window,
                        n_q_tiles=nq_total,
                    ),
                ),
                0,
                nq_total - 1,
            )
    elif causal:
        def qi(j, i):
            # upper clamp: a k tile past every q row (sk > sq + offset)
            # would otherwise request an out-of-range q block — its compute
            # is skipped anyway, any valid block satisfies the fetch
            ic = jnp.minimum(
                jnp.maximum(
                    i,
                    _first_needed_q_tile(
                        j, block_q=block_q, block_k=block_k, q_offset=q_offset
                    ),
                ),
                sq // block_q - 1,
            )
            if window > 0:
                # post-window q tiles (too new to see this k tile) repeat
                # the last in-window q tile
                ic = jnp.minimum(
                    ic,
                    _last_windowed_q_tile(
                        j, block_q=block_q, block_k=block_k,
                        q_offset=q_offset, window=window,
                        n_q_tiles=sq // block_q,
                    ),
                )
            return ic
    else:
        def qi(j, i):
            return i

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0))
    k_spec = pl.BlockSpec(
        (1, block_k, d), lambda bh, i, j: (kv_row(bh), kj(i, j), 0)
    )
    row_spec = pl.BlockSpec((1, block_q, LANES), lambda bh, i, j: (bh, i, 0))

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel,
            compact_nk=nk_total if compact_k else 0,
            **common,
        ),
        grid=(bh, sq // block_q, nkw),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=_out_struct((bh, sq, d), q.dtype, qf),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    # dk/dv: grid's parallel dims walk (B*Hkv, k blocks); the sequential
    # dim enumerates (q tile × group member) so the whole query-head group
    # accumulates into one kv-shaped scratch (kernel docstring). Index maps
    # receive (bhk, j, t) with tile-fast ordering: t = member*tiles_per_
    # member + q_tile (q_row constant across each member's tile run — DMA
    # elision). Under q-side compaction (compact_q) the per-member tile run
    # is the window footprint nqw, not the full q range.
    tiles_per_member = nqw if compact_q else nq_total

    def q_row(bhk, t):
        return (bhk // hkv) * hq + (bhk % hkv) * n_rep + t // tiles_per_member

    qT_spec = pl.BlockSpec(
        (1, block_q, d),
        lambda bhk, j, t: (q_row(bhk, t), qi(j, t % tiles_per_member), 0),
    )
    kT_spec = pl.BlockSpec((1, block_k, d), lambda bhk, j, t: (bhk, j, 0))
    rowT_spec = pl.BlockSpec(
        (1, block_q, LANES),
        lambda bhk, j, t: (q_row(bhk, t), qi(j, t % tiles_per_member), 0),
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel,
            n_rep=n_rep,
            compact_nq=nq_total if compact_q else 0,
            **common,
        ),
        grid=(b * hkv, sk // block_k, tiles_per_member * n_rep),
        in_specs=[qT_spec, kT_spec, kT_spec, qT_spec, rowT_spec, rowT_spec],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bhk, j, t: (bhk, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhk, j, t: (bhk, j, 0)),
        ],
        out_shape=[
            _out_struct((b * hkv, sk, d), k.dtype, qf),
            _out_struct((b * hkv, sk, d), v.dtype, qf),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    dq = dq.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)
    dk = dk.reshape(b, hkv, sk, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(b, hkv, sk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


# ------------------------------------------------- KV-cache decode attention
#
# The length-masked cache-read attention the incremental-decode scaffold
# (models/decoding.py::generic_forward_decode) dispatches per layer. Two
# layouts share the math:
#   * dense:  each row owns a contiguous (max_len, Hkv, D) stripe of a
#     (B, max_len, Hkv, D) buffer — the original layout, still used by the
#     static decode paths (one sequence per row for its whole life);
#   * paged:  K/V live in a (num_blocks, block_size, Hkv, D) POOL and each
#     row maps virtual positions onto pool blocks through a (B, M) block
#     table — the serving engine's layout, where rows hold only the blocks
#     their actual sequence needs instead of a worst-case max_len stripe.
# The paged read gathers the row's blocks into the same (B, S, Hkv, D)
# virtual view the dense mask logic already handles: static shapes, one
# compiled decode program regardless of per-row depths or table contents.


def decode_attention(
    q: jnp.ndarray, k_buf: jnp.ndarray, v_buf: jnp.ndarray,
    start: jnp.ndarray, window: int = 0,
    k_scale=None, v_scale=None,
) -> jnp.ndarray:
    """Length-masked attention of q's tokens over the full cache buffer.

    Static shapes (the mask, not a slice, hides unwritten cache tail) — one
    compiled program regardless of decode position. GQA runs as grouped
    einsums against the raw (B, L, Hkv, D) cache: no ``jnp.repeat``
    materialization, so per-step HBM traffic is the cache itself, not
    n_rep copies of it (the decode-throughput driver for config #3).

    ``start``: scalar (all rows at one depth) or (B,) vector (per-row
    depths — the batched-speculation cache, where each sequence committed
    a different number of tokens)."""
    b, t, hq, hd = q.shape
    max_len = k_buf.shape[1]
    hkv = k_buf.shape[2]
    n_rep = hq // hkv
    if k_scale is not None:
        # int8 cache: dequantize at the model's compute width (bf16), not
        # f32 — if XLA fails to fuse the convert+scale into the dot read,
        # the materialized temporary is then no wider than the fp cache
        k_buf = (
            k_buf.astype(jnp.float32) * k_scale[..., None]
        ).astype(q.dtype)
        v_buf = (
            v_buf.astype(jnp.float32) * v_scale[..., None]
        ).astype(q.dtype)
    qg = q.reshape(b, t, hkv, n_rep, hd)
    logits = jnp.einsum(
        "btgrd,bkgd->bgrtk", qg, k_buf, preferred_element_type=jnp.float32
    ) * hd ** -0.5  # (B, Hkv, rep, T, L)
    starts = jnp.broadcast_to(jnp.asarray(start), (b,))  # scalar or (B,)
    q_pos = starts[:, None] + jnp.arange(t)[None, :]  # (B, t)
    visible = (
        jnp.arange(max_len)[None, None, :] <= q_pos[..., None]
    )  # (B, t, max_len)
    if window > 0:  # sliding-window attention: newest `window` positions
        visible = visible & (
            jnp.arange(max_len)[None, None, :] > q_pos[..., None] - window
        )
    mask_value = -0.7 * float(jnp.finfo(jnp.float32).max)
    logits = jnp.where(visible[:, None, None], logits, mask_value)
    probs = jax.nn.softmax(logits, axis=-1).astype(v_buf.dtype)
    out = jnp.einsum("bgrtk,bkgd->btgrd", probs, v_buf)
    return out.reshape(b, t, hq, hd).astype(q.dtype)


def gather_kv_blocks(pool: jnp.ndarray, block_table: jnp.ndarray) -> jnp.ndarray:
    """(N, Bs, ...) block pool + (B, M) table → (B, M·Bs, ...) per-row
    virtual view, rows' blocks concatenated in table order. The gather is
    the whole paged↔dense bridge: the result has exactly the dense
    layout's per-row axis, so mask/rope/write semantics need no second
    implementation. Table entries for unmapped tails MUST point at the
    scratch block (pool id ``N - 1`` — the allocator guarantees it and
    asserts it under NEXUS_SANITIZE; runtime/serving.py::BlockAllocator):
    those virtual positions sit at or beyond the row's length, the mask
    hides them, and the scratch convention means a stale table entry can
    never alias a block another row owns.

    This gather-then-attend read is the REFERENCE path: it materializes
    the whole (B, M·Bs, ...) view in HBM every decode step — traffic
    proportional to the table WIDTH, not actual row depths — which is
    exactly what ``fused_paged_decode_attention`` avoids. It stays as the
    parity oracle and as the `attention_path="gather"` A/B baseline."""
    b, m = block_table.shape
    gathered = pool[block_table]  # (B, M, Bs, ...)
    return gathered.reshape((b, m * pool.shape[1]) + pool.shape[2:])


def paged_decode_attention(
    q: jnp.ndarray, k_pool: jnp.ndarray, v_pool: jnp.ndarray,
    block_table: jnp.ndarray, start: jnp.ndarray, window: int = 0,
    k_scale=None, v_scale=None,
) -> jnp.ndarray:
    """``decode_attention`` reading through a paged block pool.

    q: (B, T, Hq, D); k_pool/v_pool: (num_blocks, block_size, Hkv, D);
    block_table: (B, M) int32 pool indices; start: (B,) per-row depths
    (paged caches always run vector lengths). Scale planes (int8 cache)
    are (num_blocks, block_size, Hkv) and gather through the same table.
    """
    k_buf = gather_kv_blocks(k_pool, block_table)
    v_buf = gather_kv_blocks(v_pool, block_table)
    ks = gather_kv_blocks(k_scale, block_table) if k_scale is not None else None
    vs = gather_kv_blocks(v_scale, block_table) if v_scale is not None else None
    return decode_attention(
        q, k_buf, v_buf, start, window=window, k_scale=ks, v_scale=vs
    )


# ------------------------------------------- fused block-table decode (r8)
#
# The gather path above pays B·M·Bs·Hkv·D of HBM traffic per decode step
# per layer — the MAX table width, not actual row depths — plus a full
# (B, ..., M·Bs) logits materialization. The fused path streams over the
# table slots instead (vLLM PagedAttention's core trick): each iteration
# reads ONE (block_size, Hkv, D) block per row straight from the pool,
# folds it into a flash-style running (max, sum, accumulator), and moves
# on — the virtual view is never materialized, the loop's trip count is
# the max VALID block count across rows (lax.fori_loop with traced
# bounds), and per-slot masks derived from `start` hide unmapped tails
# and unwritten block interiors. GQA (grouped einsums against the raw
# Hkv blocks), sliding-window, and int8 k_scale/v_scale dequant all ride
# the same per-block inner loop.
#
# On top of it sits the Hydragen shared-prefix decomposition: when every
# live row's leading table entries alias the SAME physical blocks (the
# prefix cache makes this the common case for same-preamble waves),
# `shared_prefix_attention_partials` computes prefix attention once per
# wave with the rows' queries batched — each shared block is read ONCE,
# not once per row, and the score matmul is a dense (B·T·Hq) × Bs GEMM
# instead of B gathered GEMVs — while the per-row loop covers only the
# private tails; `merge_attention_partials` combines the two partial
# softmaxes exactly via log-sum-exp. The split lengths are TRACED
# operands, so one compiled program serves every wave.
#
# Numerics: per-position logits are bitwise identical to the gather
# oracle (same dots, same scale, same finite mask value); only the
# softmax reduction ORDER differs (blockwise rescaling vs one flat
# reduce), so outputs agree to f32 roundoff — tests/test_fused_attention
# pins the tolerance and test_serving.py proves token-for-token parity
# through the engine.


def _online_softmax_init(b, hkv, n_rep, t, hd):
    """Fresh partial-softmax state (m, l, acc). `m` starts at the FINITE
    mask value, not -inf: with finite masking, an all-masked block folds
    as exp(MASK-MASK)=1 against an explicit zero probability (see
    `_fold_block`), so no -inf minus -inf NaN can ever appear — the same
    finite-mask convention `decode_attention` uses."""
    return (
        jnp.full((b, hkv, n_rep, t), DEFAULT_MASK_VALUE, jnp.float32),
        jnp.zeros((b, hkv, n_rep, t), jnp.float32),
        jnp.zeros((b, hkv, n_rep, t, hd), jnp.float32),
    )


def _fold_block(carry, s, v_blk, visible):
    """Fold one block's masked logits + values into the running softmax.

    s: (B, Hkv, rep, T, Bs) f32 logits already set to DEFAULT_MASK_VALUE
    at invisible positions; visible: (B, T, Bs) bool; v_blk: the block's
    values, (B, Bs, Hkv, D) (per-row gather) or (Bs, Hkv, D) (shared
    block, read once for the whole wave).

    The probability of an invisible position is forced to literal 0.0
    (not exp(MASK - m), which is only zero once a real max has been
    seen): a block that is entirely masked — the sliding window not yet
    reaching it, or a slot past the row's valid count — contributes
    exactly nothing, whatever the running max currently is."""
    m_prev, l_prev, acc = carry
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.where(
        visible[:, None, None], jnp.exp(s - m_new[..., None]), 0.0
    )  # (B, Hkv, rep, T, Bs)
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1)
    if v_blk.ndim == 4:  # per-row blocks
        pv = jnp.einsum(
            "bgrts,bsgd->bgrtd", p, v_blk.astype(jnp.float32),
        )
    else:  # one shared block for every row (Hydragen prefix)
        pv = jnp.einsum("bgrts,sgd->bgrtd", p, v_blk.astype(jnp.float32))
    return m_new, l_new, acc * alpha[..., None] + pv


def merge_attention_partials(a, b):
    """Exact log-sum-exp combination of two partial-softmax states over
    DISJOINT key sets — the Hydragen prefix/suffix merge. For states
    (m_i, l_i, acc_i) with l_i = Σ_j exp(s_ij - m_i) and
    acc_i = Σ_j exp(s_ij - m_i)·v_j, rescaling both onto the joint max
    reproduces the single-pass softmax state over the union exactly
    (tests/test_fused_attention.py proves it against the unsplit loop
    and the dense oracle)."""
    m1, l1, a1 = a
    m2, l2, a2 = b
    m = jnp.maximum(m1, m2)
    e1 = jnp.exp(m1 - m)
    e2 = jnp.exp(m2 - m)
    return m, l1 * e1 + l2 * e2, a1 * e1[..., None] + a2 * e2[..., None]


def finalize_attention_partials(parts, out_dtype):
    """(m, l, acc) → normalized attention output (B, T, Hq, D). Rows
    whose every position was masked carry l == 0 and emit exact zeros
    (only ever padding/garbage slots the caller ignores)."""
    _, l, acc = parts
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = acc / safe_l[..., None]  # (B, Hkv, rep, T, D)
    b, hkv, n_rep, t, hd = out.shape
    return out.transpose(0, 3, 1, 2, 4).reshape(
        b, t, hkv * n_rep, hd
    ).astype(out_dtype)


def _dequant_block(blk, blk_scale, dtype):
    """int8 block → compute dtype, bitwise-matching the gather oracle's
    dequant (`decode_attention`): f32 multiply, cast to the model's
    compute width."""
    return (blk.astype(jnp.float32) * blk_scale[..., None]).astype(dtype)


# positions one loop iteration covers: each fori_loop step processes a
# GROUP of ceil(SLOT_GROUP_SPAN / block_size) table slots at once (the
# paged-attention "pages per compute block"), so the per-iteration
# gather+matmul is big enough to amortize dispatch overhead — a
# slot-per-iteration loop measured ~1.5x SLOWER than the gather path at
# 16 rows on the CPU lane purely on loop overhead. Boundary
# over-compute is bounded by one group span, fully masked, and ∝B —
# every row pays the span-rounding past its true depth — so the span
# trades per-row over-read (wants small) against loop fixed overhead
# (wants big): the interleaved pf=1 sweep at rows 4/16 (each engine
# compiled under its own span, matched queues) measured 128 and 256
# equivalent within noise (rows16/rows4 1.54x / 1.55x) and 512 worse
# (1.48x) — docs/PERF.md round 8.
SLOT_GROUP_SPAN = 128


def _slots_per_group(block_size: int) -> int:
    return max(1, SLOT_GROUP_SPAN // int(block_size))


def _group_visibility(slots, bs, q_pos, slot_ok, window):
    """Visibility of a slot-group's positions for every (row, query):
    the causal length mask (position <= q_pos), the sliding window, and
    the per-slot validity (stale/out-of-range slots) — the mask that
    makes a stale table entry unreadable regardless of what it points
    at. ``slots``: (G,) global slot ids; ``slot_ok``: (B, G)."""
    g = slots.shape[0]
    pos = (slots[:, None] * bs + jnp.arange(bs)[None, :]).reshape(
        g * bs
    )  # (G·Bs,) global virtual positions
    vis = pos[None, None, :] <= q_pos[..., None]  # (B, T, G·Bs)
    if window > 0:
        vis = vis & (pos[None, None, :] > q_pos[..., None] - window)
    ok = jnp.repeat(slot_ok, bs, axis=-1)  # (B, G·Bs)
    return vis & ok[:, None, :]


def paged_attention_partials(
    q: jnp.ndarray, k_pool: jnp.ndarray, v_pool: jnp.ndarray,
    block_table: jnp.ndarray, start: jnp.ndarray, lo, hi,
    n_blocks: jnp.ndarray, window: int = 0, k_scale=None, v_scale=None,
):
    """Per-row fused block-table attention partials over table slots
    ``lo <= mi < hi`` (traced bounds — the loop runs exactly the needed
    trip count, so per-step traffic tracks actual row depths, not the
    table width). Returns the (m, l, acc) online-softmax state.

    ``n_blocks`` (B,) is each row's VALID block count: slots at or past
    it are fully masked AND their table entry is replaced by the scratch
    block (pool id N-1) before the gather, so a stale entry can never be
    read — not even into masked lanes."""
    b, t, hq, hd = q.shape
    bs, hkv = k_pool.shape[1], k_pool.shape[2]
    n_rep = hq // hkv
    m_slots = block_table.shape[1]
    scale = hd ** -0.5
    starts = jnp.broadcast_to(jnp.asarray(start), (b,))
    q_pos = starts[:, None] + jnp.arange(t)[None, :]  # (B, T)
    qg = q.reshape(b, t, hkv, n_rep, hd)
    scratch = k_pool.shape[0] - 1
    G = _slots_per_group(bs)

    def body(i, carry):
        slots = lo + i * G + jnp.arange(G)  # (G,) global slot ids
        slot_ok = (slots[None, :] < n_blocks[:, None]) & (
            slots < hi
        )[None, :]  # (B, G)
        idx = jnp.clip(slots, 0, m_slots - 1)
        blk = jnp.take(block_table, idx, axis=1)  # (B, G)
        blk = jnp.where(slot_ok, blk, scratch)
        k_blk = k_pool[blk].reshape(
            b, G * bs, hkv, k_pool.shape[-1]
        )  # (B, G·Bs, Hkv, D)
        v_blk = v_pool[blk].reshape(b, G * bs, hkv, v_pool.shape[-1])
        if k_scale is not None:
            ks = k_scale[blk].reshape(b, G * bs, hkv)
            vs = v_scale[blk].reshape(b, G * bs, hkv)
            k_blk = _dequant_block(k_blk, ks, q.dtype)
            v_blk = _dequant_block(v_blk, vs, q.dtype)
        vis = _group_visibility(slots, bs, q_pos, slot_ok, window)
        s = jnp.einsum(
            "btgrd,bsgd->bgrts", qg, k_blk,
            preferred_element_type=jnp.float32,
        ) * scale
        s = jnp.where(vis[:, None, None], s, DEFAULT_MASK_VALUE)
        return _fold_block(carry, s, v_blk, vis)

    n_groups = -(-(hi - lo) // G)  # traced ceil — exact trip count
    return lax.fori_loop(
        0, n_groups, body, _online_softmax_init(b, hkv, n_rep, t, hd)
    )


def shared_prefix_attention_partials(
    q: jnp.ndarray, k_pool: jnp.ndarray, v_pool: jnp.ndarray,
    shared_table: jnp.ndarray, n_shared, start: jnp.ndarray,
    n_blocks: jnp.ndarray, window: int = 0, k_scale=None, v_scale=None,
):
    """Hydragen prefix partials: attention of EVERY row's queries over
    the ``n_shared`` leading blocks all live rows alias (``shared_table``
    (M,) physical ids, ``n_shared`` a traced scalar). Each shared block
    is read from the pool ONCE for the whole wave — per-slot traffic is
    Bs·Hkv·D instead of the per-row loop's B·Bs·Hkv·D — and the score
    matmul runs dense over the batched queries. Masks are identical to
    the per-row loop, so rows whose depth or window doesn't reach a
    shared position simply see it masked."""
    b, t, hq, hd = q.shape
    bs, hkv = k_pool.shape[1], k_pool.shape[2]
    n_rep = hq // hkv
    m_slots = shared_table.shape[0]
    scale = hd ** -0.5
    starts = jnp.broadcast_to(jnp.asarray(start), (b,))
    q_pos = starts[:, None] + jnp.arange(t)[None, :]
    qg = q.reshape(b, t, hkv, n_rep, hd)
    scratch = k_pool.shape[0] - 1
    G = _slots_per_group(bs)

    def body(i, carry):
        slots = i * G + jnp.arange(G)  # (G,) leading slot ids
        in_run = slots < n_shared  # (G,) — past-run slots masked
        idx = jnp.clip(slots, 0, m_slots - 1)
        blk = jnp.where(in_run, shared_table[idx], scratch)  # (G,)
        k_blk = k_pool[blk].reshape(
            G * bs, hkv, k_pool.shape[-1]
        )  # (G·Bs, Hkv, D) — each shared block read ONCE for the wave
        v_blk = v_pool[blk].reshape(G * bs, hkv, v_pool.shape[-1])
        if k_scale is not None:
            ks = k_scale[blk].reshape(G * bs, hkv)
            vs = v_scale[blk].reshape(G * bs, hkv)
            k_blk = _dequant_block(k_blk, ks, q.dtype)
            v_blk = _dequant_block(v_blk, vs, q.dtype)
        slot_ok = in_run & (slots[None, :] < n_blocks[:, None])  # (B, G)
        vis = _group_visibility(slots, bs, q_pos, slot_ok, window)
        s = jnp.einsum(
            "btgrd,sgd->bgrts", qg, k_blk,
            preferred_element_type=jnp.float32,
        ) * scale
        s = jnp.where(vis[:, None, None], s, DEFAULT_MASK_VALUE)
        return _fold_block(carry, s, v_blk, vis)

    n_groups = -(-n_shared // G)
    return lax.fori_loop(
        0, n_groups, body, _online_softmax_init(b, hkv, n_rep, t, hd)
    )


def fused_paged_decode_attention(
    q: jnp.ndarray, k_pool: jnp.ndarray, v_pool: jnp.ndarray,
    block_table: jnp.ndarray, start: jnp.ndarray, window: int = 0,
    k_scale=None, v_scale=None, n_blocks: Optional[jnp.ndarray] = None,
    shared_blocks=None, shared_table: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """``paged_decode_attention`` without the gather: attend THROUGH the
    block table with a blockwise online softmax. Same signature and
    semantics as the gather oracle, plus:

    ``n_blocks`` (B,) int32: per-row valid-block counts (defaults to
    ceil((start + T) / Bs)); slots past a row's count are masked and
    their gather is redirected to the scratch block, and the slot loop's
    trip count is the max count across rows — traffic proportional to
    actual depths.

    ``shared_blocks`` (traced scalar) + ``shared_table`` ((M,) physical
    ids): the Hydragen shared-prefix decomposition. Slots below
    ``shared_blocks`` — leading table entries every live row aliases —
    are computed once per wave from ``shared_table`` with the queries
    batched; the per-row loop covers only ``[shared_blocks, hi)``; the
    two partial softmaxes combine exactly via log-sum-exp
    (``merge_attention_partials``). ``shared_blocks == 0`` at runtime
    degrades to the plain fused loop in the SAME compiled program — the
    split length is an operand, never a compile key."""
    b, t = q.shape[0], q.shape[1]
    bs = k_pool.shape[1]
    m_slots = block_table.shape[1]
    starts = jnp.broadcast_to(jnp.asarray(start), (b,))
    if n_blocks is None:
        n_blocks = -(-(starts + t) // bs)
    n_blocks = jnp.clip(n_blocks, 1, m_slots)
    hi = jnp.max(n_blocks)  # traced scalar loop bound
    common = dict(window=window, k_scale=k_scale, v_scale=v_scale)
    if shared_table is not None and shared_blocks is not None:
        s_eff = jnp.clip(jnp.asarray(shared_blocks, jnp.int32), 0, hi)
        prefix = shared_prefix_attention_partials(
            q, k_pool, v_pool, shared_table, s_eff, starts, n_blocks,
            **common,
        )
        suffix = paged_attention_partials(
            q, k_pool, v_pool, block_table, starts, s_eff, hi, n_blocks,
            **common,
        )
        parts = merge_attention_partials(prefix, suffix)
    else:
        parts = paged_attention_partials(
            q, k_pool, v_pool, block_table, starts, 0, hi, n_blocks,
            **common,
        )
    return finalize_attention_partials(parts, q.dtype)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    q_offset: int = 0,
    impl: Optional[str] = None,
    window: int = 0,
) -> jnp.ndarray:
    """Dispatching entry point: impl in {None (auto), 'xla', 'flash'}."""
    if impl is None:
        from nexus_tpu.utils.hw import is_tpu

        tile_ok = (
            q.shape[1] % min(128, q.shape[1]) == 0
            and k.shape[1] % min(128, k.shape[1]) == 0
            and q.shape[-1] in (64, 128, 256)
            and q.shape[1] >= 128
        )
        impl = "flash" if (is_tpu() and tile_ok) else "xla"
    if impl == "flash":
        return flash_attention(
            q, k, v, causal=causal, q_offset=q_offset, window=window
        )
    if impl == "xla":
        return attention_xla(
            q, k, v, causal=causal, q_offset=q_offset, window=window
        )
    # 'ring' must go through ops.ring_attention.ring_attention_sharded (the
    # model blocks dispatch it); silently degrading an unknown impl to the
    # dense path would hide a real configuration error
    raise ValueError(
        f"unknown attention impl {impl!r}; expected None, 'xla', or 'flash' "
        "(ring attention dispatches via ring_attention_sharded)"
    )
