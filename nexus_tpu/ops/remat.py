"""Shared rematerialization policy selection for model blocks.

One place maps the spec's ``remat_policy`` string onto ``jax.checkpoint``
variants (used by models/llama.py, models/mixtral.py, parallel/pipeline.py)
— and an unknown policy is a loud error, not a silent fall-through to
full recompute."""

from __future__ import annotations

from typing import Callable

import jax

REMAT_POLICIES = ("full", "dots")


def checkpoint_block(fn: Callable, remat_policy: str = "full") -> Callable:
    """Wrap ``fn`` in jax.checkpoint per the named policy.

    ``full``: recompute everything on backward (min memory, max recompute).
    ``dots``: save matmul outputs, recompute elementwise/norms
    (``dots_with_no_batch_dims_saveable`` — most of the memory win at a few
    percent recompute)."""
    if remat_policy == "dots":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    if remat_policy == "full":
        return jax.checkpoint(fn)
    raise ValueError(
        f"unknown remat_policy {remat_policy!r}; expected one of {REMAT_POLICIES}"
    )
