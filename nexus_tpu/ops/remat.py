"""Shared rematerialization policy selection for model blocks.

One place maps the spec's ``remat_policy`` string onto ``jax.checkpoint``
variants (used by models/llama.py, models/mixtral.py, parallel/pipeline.py)
— and an unknown policy is a loud error, not a silent fall-through to
full recompute."""

from __future__ import annotations

from typing import Callable

import jax

REMAT_POLICIES = ("full", "dots", "dots_attn")

# the model blocks tag their attention output with this name
# (jax.ad_checkpoint.checkpoint_name) so a name-aware policy can save it
ATTN_OUT_NAME = "attn_out"
# ...and the flash custom-VJP tags its residual logsumexp: saving the
# block-level output alone is NOT enough — autodiff still reruns the
# kernel to reconstruct the VJP residuals (out, lse), so the names must
# sit on the residual values inside the fwd rule (ops/attention.py)
ATTN_LSE_NAME = "attn_lse"


def checkpoint_block(fn: Callable, remat_policy: str = "full") -> Callable:
    """Wrap ``fn`` in jax.checkpoint per the named policy.

    ``full``: recompute everything on backward (min memory, max recompute).
    ``dots``: save matmul outputs, recompute elementwise/norms
    (``dots_with_no_batch_dims_saveable`` — most of the memory win at a few
    percent recompute).
    ``dots_attn``: ``dots`` PLUS the tagged attention outputs. Flash
    attention is a pallas_call, not a dot — under plain ``dots`` the
    backward recomputes the whole forward attention kernel before running
    the dq/dkv kernels. Saving the (B,S,H,D) attention output (~the size
    of one activation tensor per layer) skips that recompute. Caveat:
    under RING attention every ring hop's flash call tags its own
    residuals, so an N-way ring saves up to N pairs per layer — at
    memory-tight long-context shapes prefer ``dots`` (measured win is on
    the non-ring flash path, docs/PERF.md)."""
    if remat_policy == "dots":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    if remat_policy == "dots_attn":
        return jax.checkpoint(
            fn,
            policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(
                    ATTN_OUT_NAME, ATTN_LSE_NAME
                ),
            ),
        )
    if remat_policy == "full":
        return jax.checkpoint(fn)
    raise ValueError(
        f"unknown remat_policy {remat_policy!r}; expected one of {REMAT_POLICIES}"
    )
